"""Engine facade: SiddhiManager / SiddhiAppRuntime / InputHandler / callbacks.

The TPU framework's analog of the reference runtime layer (reference:
core:SiddhiManager.java:45, core:SiddhiAppRuntime.java:93,
core:stream/input/InputHandler.java:51, core:stream/StreamJunction.java:62).

Execution model difference, by design: the reference walks a processor
graph per event on the caller thread.  Here events accumulate into
host-side columnar builders (per stream); `flush()` drains them as
micro-batches through the compiled array programs and routes outputs —
batched dataflow instead of event-at-a-time interpretation.  `send()`
auto-flushes when a builder reaches capacity.
"""
from __future__ import annotations

import threading
import time
import numpy as np
from collections import defaultdict
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Union

from ..query import ast as qast
from ..query.parser import parse
from ..utils.locks import new_lock, new_rlock
from .batch import BatchBuilder, EventBatch
from .planner import OutputBatch, PlanError, QueryPlan
from .schema import StreamSchema, StringTable


@dataclass
class Event:
    """Host-side decoded event (reference: core:event/Event.java).

    `uid` is an optional per-instance identity (0 = unassigned) used by
    consumers that must pair CURRENT/EXPIRED emissions of the same event
    instance (join retained-lists); windows preserve it when re-stamping
    expired events."""
    timestamp: int
    data: tuple
    uid: int = 0

    def __iter__(self):
        return iter(self.data)


class InputHandler:
    """User-facing ingest handle (reference: InputHandler.send:51-94)."""

    def __init__(self, runtime: "SiddhiAppRuntime", stream_id: str):
        self._rt = runtime
        self.stream_id = stream_id

    def send(self, data, timestamp: Optional[int] = None) -> None:
        """Accepts one row tuple, a list of row tuples, or an Event."""
        self._rt.send(self.stream_id, data, timestamp)

    def send_batch(self, columns: dict, timestamps=None) -> None:
        """Columnar ingest: one micro-batch straight from numpy arrays —
        the struct-of-arrays analog of `send(list_of_rows)` without the
        per-row Python loop.  `columns` maps attribute name -> (n,) array
        (string attributes: array/list of str, or pre-encoded int32 dict
        codes); `timestamps` is an (n,) int64 ms array (default: now).
        Dispatches through the same junction path as `send` — batches are
        NOT split or coalesced, so one call = one device micro-batch."""
        self._rt.send_columnar(self.stream_id, columns, timestamps)


def _parse_interval_s(text: str) -> float:
    """'5 sec' / '500 ms' / bare seconds -> float seconds (unit table
    shared with the SiddhiQL time-constant lexer)."""
    from ..query.parser import _TIME_UNITS_MS
    parts = str(text).strip().split()
    if len(parts) == 1:
        return float(parts[0])
    unit = parts[1].lower()
    if unit not in _TIME_UNITS_MS:
        raise PlanError(f"unknown time unit {parts[1]!r} in interval {text!r}")
    return float(parts[0]) * _TIME_UNITS_MS[unit] / 1000.0


class SiddhiAppRuntime:
    def __init__(self, app: qast.SiddhiApp, manager: Optional["SiddhiManager"] = None):
        self.app = app
        self.manager = manager
        self.strings = StringTable()
        self.batch_capacity = 2048
        self._started = False
        self._playback = qast.find_annotation(app.annotations, "app:playback") is not None
        self._clock_ms: Optional[int] = None   # virtual/playback clock
        # device pattern matching: "auto" (device when partitioned),
        # "always" (device or error), "prefer" (device when supported, host
        # fallback), "never" (sequential host matcher).  The
        # SIDDHI_DEVICE_PATTERNS env var overrides the default for apps
        # without the annotation (the device test lane runs the whole
        # pattern suite with SIDDHI_DEVICE_PATTERNS=prefer).
        import os as _os
        dp = qast.find_annotation(app.annotations, "app:devicePatterns")
        self.device_patterns = dp.element() if dp is not None else \
            _os.environ.get("SIDDHI_DEVICE_PATTERNS", "auto")
        # starting partition-axis capacity for device pattern plans (grows
        # by doubling as new keys arrive; each growth recompiles the kernel)
        pc = qast.find_annotation(app.annotations, "app:partitionCapacity")
        self.partition_capacity = int(pc.element()) if pc is not None else 1024
        # starting pending-match slots per partition for device pattern
        # plans (grows adaptively; pre-sizing skips a growth recompile)
        ds = qast.find_annotation(app.annotations, "app:deviceSlots")
        self.device_slots = int(ds.element()) if ds is not None else 16
        # device window-aggregation: "auto" (device when supported),
        # "always" (device or error), "never" (host interpreter)
        dw = qast.find_annotation(app.annotations, "app:deviceWindows")
        self.device_windows = dw.element() if dw is not None else "auto"
        # device window-joins: "auto" (device for supported shapes, host
        # fallback), "always" (device or error), "never"
        dj = qast.find_annotation(app.annotations, "app:deviceJoins")
        self.device_joins = dj.element() if dj is not None else \
            _os.environ.get("SIDDHI_DEVICE_JOINS", "auto")
        # stateless filter/projection: "auto" (jitted device kernel),
        # "never" (host interpreter — benchmarking / debugging)
        df = qast.find_annotation(app.annotations, "app:deviceFilters")
        self.device_filters = df.element() if df is not None else "auto"
        # multi-chip mesh for device plans: "auto" (shard the partition
        # axis over jax.devices() when >1), "always", "never"
        dm = qast.find_annotation(app.annotations, "app:deviceMesh")
        self.device_mesh = dm.element() if dm is not None else "auto"
        # @Async analog (reference StreamJunction Disruptor ring): ingest
        # worker(s) decouple send() from flush/compute so host batch
        # assembly overlaps device execution.  Knobs mirror the reference
        # @Async(workers=..., batch.size.max=..., buffer.size=...)
        # (StreamJunction.java:299-307): workers>1 trades CROSS-BATCH
        # ORDER for concurrency exactly as the reference junction does.
        asy = qast.find_annotation(app.annotations, "app:async")
        self._async = asy is not None
        self._async_workers = 1
        self._async_buffer = 8
        if asy is not None:
            def _el(key):
                return next((v for k, v in asy.elements if k and
                             k.lower() == key), None)
            w = _el("workers")
            if w is not None:
                self._async_workers = max(1, int(w))
            bs = _el("batch.size.max")
            if bs is not None:
                self.batch_capacity = max(1, int(bs))
            bf = _el("buffer.size")
            if bf is not None:
                self._async_buffer = max(1, int(bf))
        # @app:enforceOrder restores cross-batch ordering under
        # workers>1 via ticketed lock acquisition (reference:
        # SiddhiAppParser.java:94-98)
        self._enforce_order = qast.find_annotation(
            app.annotations, "app:enforceOrder") is not None
        if self._enforce_order and self._async_workers > 1:
            # ordered processing is serialized by the runtime lock anyway:
            # one worker with a FIFO queue gives identical semantics to
            # N mutex-serialized workers, with none of the deadlock
            # surface (reference: SiddhiAppParser.java:94-98 restores
            # ordering over the multi-worker junction)
            self._async_workers = 1
        if asy is not None:
            if self._async_workers > 1 and not self._enforce_order:
                import warnings
                warnings.warn(
                    f"@app:async(workers={self._async_workers}): cross-batch "
                    f"ordering is not preserved with multiple workers (same "
                    f"trade as the reference multi-worker StreamJunction; "
                    f"add @app:enforceOrder to restore it)",
                    RuntimeWarning, stacklevel=2)
        # auto-batching to a latency target: builders flush when their
        # oldest buffered event has waited this long, so micro-batch size
        # adapts to the event rate instead of always filling batchCapacity
        # (the latency/throughput knob; cf. reference harness latency in
        # SimpleFilterSingleQueryPerformance.java:40-77)
        mbl = qast.find_annotation(app.annotations, "app:maxBatchLatency")
        self.max_batch_latency_s = (_parse_interval_s(mbl.element())
                                    if mbl is not None else None)
        self._builder_t0: dict = {}     # stream -> first-append wall time

        # adaptive execution geometry (core/autotune.py): the tuning-cache
        # facade plan constructors consult at build time, and the AIMD
        # batching controller behind @app:latencySLO.  @app:maxBatchLatency
        # rides the SAME controller in cadence-only (non-adaptive) mode —
        # its one-shot flush-when-aged heuristic is unchanged.
        from .autotune import SLOController, TunerRuntime
        self.tuner = TunerRuntime(self)
        slo_ann = qast.find_annotation(app.annotations, "app:latencySLO")
        if slo_ann is not None:
            # an explicit @app:maxBatchLatency alongside the SLO pins the
            # flush cadence; otherwise it defaults to target / 2
            self.slo = SLOController(
                target_s=_parse_interval_s(slo_ann.element()),
                flush_after_s=self.max_batch_latency_s,
                initial_batch=self.batch_capacity)
        elif self.max_batch_latency_s is not None:
            self.slo = SLOController(
                flush_after_s=self.max_batch_latency_s, adaptive=False)
        else:
            self.slo = None
        if self.slo is not None:
            self.max_batch_latency_s = self.slo.flush_after_s
        # tuned app-level micro-batch capacity (cache warm + no explicit
        # @app:async(batch.size.max) override)
        if asy is None or _el("batch.size.max") is None:
            hint = self.tuner.batch_hint()
            if hint:
                self.batch_capacity = hint
                if self.slo is not None and self.slo.adaptive:
                    self.slo.batch_target = hint

        # stream schemas: defined + inferred from query outputs
        self.schemas: dict = {}
        for sid, sd in app.stream_definitions.items():
            self.schemas[sid] = StreamSchema.of(sd)

        self.tables: dict = {}
        self.named_windows: dict = {}
        self.aggregations: dict = {}
        self.sources: list = []
        self.sinks: list = []

        # @OnError handling per stream (reference: StreamJunction.java:77-139
        # OnErrorAction LOG/STREAM/STORE/WAIT):
        #   log    - log the failure, drop the failing batch's results
        #   stream - reroute the batch into the "!<id>" fault stream
        #            (schema = original attrs + _error string)
        #   store  - capture events + cause into the runtime's ErrorStore
        #            (replayable; GET/POST /siddhi/errors)
        #   wait   - block ingest, retrying the failed work with backoff
        #            until a deadline (@OnError(action='wait',
        #            timeout='10 sec'))
        self._onerror: dict = {}
        self._onerror_wait: dict = {}
        for sid, sd in list(app.stream_definitions.items()):
            oe = qast.find_annotation(sd.annotations, "onerror")
            if oe is None:
                continue
            action = (oe.element("action") or "stream").lower()
            if action not in ("log", "stream", "store", "wait"):
                raise PlanError(
                    f"stream {sid!r}: unknown @OnError action {action!r} "
                    f"(have: log | stream | store | wait)")
            self._onerror[sid] = action
            if action == "stream":
                self.schemas["!" + sid] = StreamSchema(
                    "!" + sid, tuple(sd.attributes) + (
                        qast.Attribute("_error", qast.AttrType.STRING),))
            elif action == "wait":
                to = next((v for k, v in oe.elements
                           if k and k.lower() in ("timeout", "wait.timeout")),
                          None)
                self._onerror_wait[sid] = \
                    _parse_interval_s(to) if to else 10.0

        # @app:durability('off'|'batch'|'fsync'): write-ahead log of
        # admitted frames (core/wal.py), coordinated with snapshot
        # revisions via per-stream durable watermarks so a crash or
        # redeploy recovers exactly-once (docs/RELIABILITY.md).  The
        # log opens at start()/recover(); `dir=` overrides the
        # directory (default: under the manager's persistence store,
        # else $SIDDHI_WAL_DIR)
        dur_ann = qast.find_annotation(app.annotations, "app:durability")
        self.durability = (dur_ann.element() or "batch").lower() \
            if dur_ann is not None else "off"
        if self.durability not in ("off", "batch", "fsync"):
            raise PlanError(
                f"@app:durability({self.durability!r}): unknown sync "
                f"policy (have: off | batch | fsync)")
        self._wal_dir_opt = next(
            (v for k, v in dur_ann.elements if k == "dir"), None) \
            if dur_ann is not None else None
        self._wal_segment_bytes = int(next(
            (v for k, v in dur_ann.elements if k == "segment.bytes"),
            8 << 20)) if dur_ann is not None else (8 << 20)
        self.wal = None                  # WriteAheadLog once opened
        self._wal_replaying = False      # recovery replay: no re-append
        self._wal_recovery = None        # last recover() report
        self.last_revision_descriptor = None   # last persist() Revision

        # @app:replication('async'|'semi-sync', role=, peer=...): hot-
        # standby WAL replication (core/replication.py + net/repl.py,
        # docs/RELIABILITY.md "High availability & failover").  The
        # coordinator is built at start() (or lazily when a standby
        # subscribes to an un-annotated durable app)
        from .replication import ReplicationError, config_from_annotations
        try:
            self.replication_config = config_from_annotations(app)
        except ReplicationError as e:
            raise PlanError(str(e)) from None
        if self.replication_config is not None and self.durability == "off":
            raise PlanError(
                "@app:replication requires @app:durability — without a "
                "write-ahead log there is nothing to ship (analysis "
                "rule SA14)")
        self.replication = None          # ReplicationCoordinator
        self._repl_receiver = None       # standby-side net.repl.WalReceiver
        self._standby_active = False     # standby replica: ingest blocked

        # end-to-end frame tracing (core/tracing.py): cross-thread span
        # trees carried by Work/EventBatch/sink-outbox entries, plus the
        # trigger registry that promotes the always-on ring into retained
        # dumps.  `@app:trace('off')` -> None (zero hot-path cost); the
        # thread-local scope hands the active frame's handle across the
        # feed -> freeze -> dispatch -> egress call chain.
        from .tracing import tracer_from_annotations
        self.tracing = tracer_from_annotations(app)
        self._trace_tls = threading.local()
        # continuous device-time attribution (core/profiler.py): every
        # dispatch round splits its wall into the six-phase taxonomy,
        # kernel/h2d via duty-cycle block_until_ready sampling.
        # `@app:profile('off')` -> None (zero hot-path cost); a windowed
        # host-dispatch-share breach promotes a flight-recorder dump
        # through the tracing trigger registry (enqueue-only)
        from .profiler import profiler_from_annotations
        self.profiler = profiler_from_annotations(app)
        if self.profiler is not None and self.tracing is not None:
            _trc = self.tracing
            self.profiler.on_host_share_breach = (
                lambda detail: _trc.trigger("host_share_breach", detail))
        if self.slo is not None and self.tracing is not None:
            _tr = self.tracing
            self.slo.on_breach = lambda dec: _tr.trigger(
                "slo_breach",
                f"window p99 {dec.get('p99_ms')}ms > target "
                f"{dec.get('target_ms')}ms at batch {dec.get('batch_from')}")

        # fault-tolerance state: the replayable ErrorStore behind
        # @OnError(action='store') and sink on.error, the per-plan
        # degradation ladders, and the (optional) seeded fault injector
        from .faults import ErrorStore
        self.error_store = ErrorStore()
        self.fault_injector = None      # set a faults.FaultInjector to arm
        # serving-plane admission controllers, one per net-ingesting
        # stream (siddhi_tpu.net.admission) — shared across transports,
        # throttled by the SLO controller's admission_factor; the gate
        # serializes net feeds against retire() across EVERY server
        # feeding this runtime (net/server.py _gate_of)
        self.admission: dict = {}
        self._net_gate = new_rlock("SiddhiAppRuntime._net_gate")
        self._ladders: dict = {}        # plan name -> FaultLadder
        self._degraded: list = []       # quarantined-plan records
        # placement accounting (core/placement.py): every interpreter
        # fallback and rejected plan family in the build path records a
        # Demotion here — rt.explain() / statistics()["placement"] /
        # `python -m siddhi_tpu.analysis` surface them, and the self-lint
        # fails CI on swallow sites that record nothing
        from .placement import PlacementLog
        self.placement = PlacementLog()
        qa = qast.find_annotation(app.annotations, "app:quarantineAfter")
        # consecutive resource failures before a device plan is
        # quarantined onto the interpreter path
        self.quarantine_after = int(qa.element()) if qa is not None else 3

        self._plans: list[QueryPlan] = []
        self._subscribers: dict = defaultdict(list)   # stream_id -> [plan]
        self._stream_callbacks: dict = defaultdict(list)
        self._batch_callbacks: dict = defaultdict(list)
        self._query_callbacks: dict = defaultdict(list)
        self._plan_by_name: dict = {}
        self._known_query_names: set = set()   # incl. lazily-cloned partition queries

        self._builders: dict = {}
        self._pending: list = []      # FIFO of (stream_id, EventBatch) awaiting dispatch
        self._seq = 0                 # global arrival order counter
        # rotating device-upload pad buffers shared by all plans (see
        # pipeline.py PadPool + EventBatch.padded)
        from .pipeline import PadPool
        self._pad_pool = PadPool()
        self._store_cache: dict = {}  # store-query text -> StoreQueryExec
        # ingest/timer mutual exclusion (the reference's ThreadBarrier +
        # per-query locks collapse to one runtime lock: state is columnar
        # and single-writer by design)
        self._lock = new_rlock("SiddhiAppRuntime._lock")
        # sink deliveries staged inside _drain (under the lock) and flushed
        # after release: a sink publishing into another runtime's source
        # (which takes THAT runtime's lock) could otherwise ABBA-deadlock
        # when two runtimes publish to each other's topics (advisor r2)
        self._sink_outbox: list = []
        self._sched_thread = None
        self._sched_stop = None
        self._ingest_q = None
        self._ingest_thread = None
        self._ingest_err = None
        self._async_outbox: list = []   # full builders staged under the lock
        self._outbox_mutex = new_lock(
            "SiddhiAppRuntime._outbox_mutex")    # orders producer enqueues
        # shutdown() is reachable concurrently (service.stop() racing an
        # undeploy of the same snapshot, user teardown racing atexit):
        # the teardown sequence must run once, not interleave
        self._shutdown_mutex = new_lock("SiddhiAppRuntime._shutdown_mutex")

        from .telemetry import StatisticsManager
        self.stats = StatisticsManager(self)
        sa = qast.find_annotation(app.annotations, "app:statistics")
        if sa is not None and (sa.element() or "true").lower() != "false":
            self.stats.enabled = True
            # keyed elements only: the lone-positional fallback would turn
            # @app:statistics('true') into interval='true'
            rep = next((v for k, v in sa.elements if k == "reporter"), None)
            iv = next((v for k, v in sa.elements if k == "interval"), None)
            if rep is not None or iv is not None:
                iv_s = _parse_interval_s(iv) if iv is not None else 5.0
                self.stats.configure(rep or "console", iv_s)
        self._debugger = None

        # @app:strictAnalysis: the deploy-time contract — run the static
        # analyzer and refuse to deploy on anything at error OR warn
        # severity (docs/ANALYSIS.md).  The rules are pure-AST, so the
        # check runs BEFORE the build: a rejected app never pays (or
        # waits for) device plan lowering
        if qast.find_annotation(app.annotations, "app:strictAnalysis") \
                is not None:
            from ..analysis import strict_check
            strict_check(self)

        with self.stats.stage("plan"):
            self._build()

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        from . import build as _build_mod
        from .io import build_io
        _build_mod.build_app(self)
        build_io(self)

    def _register_plan(self, plan: QueryPlan) -> None:
        self._plans.append(plan)
        self._plan_by_name[plan.name] = plan
        if getattr(plan, "rt", None) is None:
            plan.rt = self      # fault-injection + recovery back-ref
        pipe = getattr(plan, "_pipe", None)
        if pipe is not None:
            # D2H-readback injection point (faults.FaultInjector "d2h")
            pipe.inject = (lambda p=plan: self.inject("d2h", p.name))
            # the pipeline's blocking pull is the d2h_materialize phase
            pipe.prof = self.profiler
        self._known_query_names.add(getattr(plan, "callback_name", plan.name))
        for sid in plan.input_streams:
            self._subscribers[sid].append(plan)
        tgt = plan.output_target
        if tgt is not None and plan.out_schema is not None and tgt not in self.tables:
            if tgt in self.schemas:
                have = self.schemas[tgt]
                want = plan.out_schema
                if [a.type for a in have.attributes] != [a.type for a in want.attributes]:
                    raise PlanError(
                        f"query {plan.name!r} inserts into {tgt!r} with mismatched "
                        f"schema {want.attributes} vs {have.attributes}")
            else:
                self.schemas[tgt] = StreamSchema(tgt, plan.out_schema.attributes)

    # -- public API ----------------------------------------------------------

    def input_handler(self, stream_id: str) -> InputHandler:
        if stream_id not in self.schemas:
            raise KeyError(f"unknown stream {stream_id!r}")
        if stream_id in self.named_windows:
            raise KeyError(f"{stream_id!r} is a named window; feed it with "
                           f"a query (`insert into {stream_id}`)")
        return InputHandler(self, stream_id)

    # alias matching the reference name
    getInputHandler = input_handler

    def add_callback(self, stream_id: str, fn: Callable) -> None:
        """StreamCallback: fn(list[Event]) on every batch reaching stream_id."""
        self._stream_callbacks[stream_id].append(fn)

    def add_batch_callback(self, stream_id: str, fn: Callable) -> None:
        """Columnar StreamCallback: fn(EventBatch), no row decode (the
        zero-copy consumer path; decode via batch.rows(rt.strings))."""
        self._batch_callbacks[stream_id].append(fn)

    def add_query_callback(self, query_name: str, fn: Callable) -> None:
        """QueryCallback: fn(timestamp_ms, in_events, removed_events)."""
        if query_name not in self._known_query_names:
            raise KeyError(f"unknown query {query_name!r}; "
                           f"have {sorted(self._known_query_names)}")
        self._query_callbacks[query_name].append(fn)

    def start(self) -> None:
        """Start the runtime: fire `at 'start'` triggers, anchor periodic/
        cron triggers, and (in real-time mode) start the wall-clock
        scheduler pump (reference: SiddhiAppRuntime.start:370 starts
        sources + trigger schedulers; Scheduler.java:89 timer service).

        Under `@app:replication(role='standby')` the runtime starts as
        a passive replica instead: it opens its local WAL and tails the
        primary (net/repl.py), serving nothing until promote()."""
        cfg = self.replication_config   # lint: allow (set once at parse)
        coord = self._ensure_replication()
        if cfg is not None and cfg.role == "standby" \
                and not (coord is not None and coord.promoted):
            self._start_standby()
            return
        self._start_serving()

    def _start_serving(self) -> None:
        from .trigger import TriggerRuntime
        self._started = True
        if self.tracing is not None:
            # shutdown()/start() cycle: the closed tracer must re-arm
            # (the WAL-reopen analog) or every trigger after the restart
            # would be silently dropped
            self.tracing.reopen()
        if self.durability != "off" and self.wal is None:
            if self._wal_recovery is None:
                # the recovery manager runs on start (start/redeploy):
                # opening the log WITHOUT replaying its pre-existing
                # records would fold their seqs into the live counters,
                # so the next snapshot's watermark would claim
                # unapplied frames and the barrier would truncate them
                # — silent loss.  Fresh log: a cheap no-op.
                self.recover()
            else:
                # shutdown()/start() cycle in one process: the state is
                # still live (nothing to replay) — REOPEN the log so
                # durability doesn't silently lapse; seq continuity
                # comes from the previous generation's counters
                self._open_wal()
        now = self.now_ms()
        with self._lock:
            for p in self._plans:
                if isinstance(p, TriggerRuntime):
                    # playback apps anchor at the first virtual-clock value
                    # instead (set_time), not at the wall clock
                    if not p.anchored and not (self._playback
                                               and self._clock_ms is None):
                        p.anchor(now)
                    for ob in p.fire_start(now):
                        self._emit(p, ob)
            self._drain()
        if self.stats.enabled and self.stats.reporter is not None:
            self.stats.start_reporting()
        if self._async and self._ingest_thread is None:
            self._start_ingest_worker()
        for s in self.sources:
            if not s.connected:
                s.connect_with_retry()
        for s in self.sinks:
            if not s.connected:
                s.connect()
                s.connected = True
        if not self._playback:
            self._start_scheduler()

    # -- replication: standby role & failover (core/replication.py) ----------

    def _ensure_replication(self, default: bool = False):
        """The app's ReplicationCoordinator — built from the
        annotation config, or (default=True, the serving plane's path
        when a standby subscribes to an UN-annotated durable app) from
        an implicit async-primary config."""
        with self._lock:
            if self.replication is not None:
                return self.replication
            cfg = self.replication_config
            if cfg is None:
                if not default or self.durability == "off":
                    return None
                from .replication import ReplicationConfig
                cfg = self.replication_config = ReplicationConfig("async")
            from .replication import ReplicationCoordinator
            tr = self.tracing
            self.replication = ReplicationCoordinator(
                cfg, on_lag_breach=None if tr is None else
                (lambda detail: tr.trigger("repl_lag_breach", detail)))
            return self.replication

    def is_standby(self) -> bool:
        return self._standby_active

    def _start_standby(self) -> None:
        """Start as a passive replica: open the local WAL (healing scan
        + seq recovery, NO replay into plans — state materializes at
        promote()) and run the WalReceiver tailing the primary."""
        self._started = True
        self._standby_active = True
        if self.tracing is not None:
            self.tracing.reopen()
        wal = self._open_wal()
        if wal is None:
            raise RuntimeError(
                f"standby {self.app.name!r} could not open a WAL "
                f"({getattr(self, '_wal_disabled_reason', 'no directory')})"
                f" — a replica without a log cannot replicate")
        if self.stats.enabled and self.stats.reporter is not None:
            self.stats.start_reporting()
        if self._repl_receiver is None:
            from ..net.repl import WalReceiver
            self._repl_receiver = WalReceiver(
                self,
                self.replication,   # lint: allow (set once at construction)
                self.replication_config.peer).start()

    def promote(self) -> dict:
        """Fail over: flip this standby replica to serving primary.
        Stops the tail, FENCES the log above every generation seen from
        the old primary (its post-promote appends are rejected loudly),
        then runs the ordinary recovery manager — restore the newest
        shipped revision, heal the replicated log's torn tail, replay
        to head — and starts serving.  Producers reconnect and
        retransmit from their last ACK; with semi-sync that window is
        exactly what the standby already has, so outputs stay
        byte-identical and `events_in == applied + shed` holds across
        the failover."""
        coord = self.replication    # lint: allow (set once at construction)
        if coord is None or not self._standby_active:
            raise RuntimeError(
                f"promote(): app {self.app.name!r} is not a standby "
                f"replica")
        t0 = time.perf_counter()
        if self._repl_receiver is not None:
            self._repl_receiver.stop()
            self._repl_receiver = None
        self.inject("repl.promote", self.app.name)
        # fence FIRST: from here the old primary's generation is dead,
        # even if recovery below fails and is retried
        generation = self.wal.fence(coord.source_generation())
        # close the tailing log so recover() re-opens it through the
        # healing scan and replays the suffix past the restored
        # watermark (seq continuity: _open_wal floors from _wal_closed)
        self.wal.close()
        self._wal_closed, self.wal = self.wal, None
        self._standby_active = False
        coord.mark_promoted(generation)
        report = self.recover()
        self._start_serving()
        out = {"promoted": True, "generation": generation,
               "watermark": self.wal.watermark()
               if self.wal is not None else {},
               "recovery": report,
               "promote_s": round(time.perf_counter() - t0, 6)}
        self._promote_report = out      # snapshot_info/explain audit trail
        return out

    def _start_ingest_worker(self) -> None:
        """@app:async: frozen micro-batches queue to a worker that runs
        the device/interp plans, so the producer thread keeps assembling
        the next batch while the previous one computes (the reference's
        Disruptor + StreamHandler drain, StreamJunction.java:280-316)."""
        import queue as _queue
        # bounded: backpressure (reference buffer.size ring capacity)
        self._ingest_q = _queue.Queue(maxsize=self._async_buffer)

        def worker():
            while True:
                item = self._ingest_q.get()
                try:
                    if item is None:
                        return
                    if self._ingest_err is not None:
                        continue   # latched: drop (but ack) until surfaced
                    sid, batch = item
                    with self._lock:
                        self._pending.append((sid, batch))
                        self._drain()
                    self._flush_sink_outbox()
                except BaseException as e:   # surface at the flush barrier
                    self._ingest_err = e
                finally:
                    self._ingest_q.task_done()

        self._ingest_thread = threading.Thread(
            target=worker, name="siddhi-ingest", daemon=True)
        self._ingest_thread.start()
        self._extra_workers = []
        for i in range(self._async_workers - 1):
            t = threading.Thread(target=worker,
                                 name=f"siddhi-ingest-{i + 1}", daemon=True)
            t.start()
            self._extra_workers.append(t)

    def _start_scheduler(self) -> None:
        """Wall-clock timer pump: fires due timers (time windows, rate
        limits, triggers, absent patterns) without requiring set_time()."""
        if self._sched_thread is not None:
            return
        self._sched_stop = threading.Event()

        tick = 0.02
        if self.max_batch_latency_s is not None:
            tick = min(tick, max(self.max_batch_latency_s / 2, 0.001))

        def pump():
            while not self._sched_stop.wait(tick):
                self._pump_admission()  # outside the lock: feeds re-enter
                with self._lock:
                    virtual = self._clock_ms is not None
                    if not virtual and self.max_batch_latency_s is not None:
                        # age-out partially filled builders (quiescent
                        # streams would otherwise hold events past the
                        # latency target until the next send).  In async
                        # mode aged batches MUST ride the ingest queue —
                        # draining them here would jump ahead of earlier
                        # batches the worker hasn't popped yet.
                        now_w = time.perf_counter()
                        for sid, b in self._builders.items():
                            if len(b) and now_w - self._builder_t0.get(
                                    sid, 0.0) >= self.max_batch_latency_s:
                                frozen = self._freeze(sid, b)
                                if self._async and self._ingest_q is not None:
                                    self._async_outbox.append((sid, frozen))
                                else:
                                    self._pending.append((sid, frozen))
                        if self._pending:
                            self._drain()
                        # bounded delivery under a latency target: a
                        # depth-D pipeline may still hold the aged
                        # batch's results in flight — they must not
                        # outlive the flush cadence waiting for an
                        # explicit flush() (tuned depth + latency
                        # cadence compose)
                        if any(len(getattr(p, "_pipe", None) or ())
                               for p in self._plans):
                            self._flush_plan_pipelines()
                    if virtual:
                        continue            # virtual clock took over
                    due = [w for p in self._plans
                           for w in [p.next_wakeup()] if w is not None]
                    now = int(time.time() * 1000)
                    if due and min(due) <= now:
                        self._fire_timers_locked(now)
                        self._clock_ms = None    # stay in wall-clock mode
                self._drain_async_outbox()      # outside the lock
                self._flush_sink_outbox()

        self._sched_thread = threading.Thread(
            target=pump, name="siddhi-scheduler", daemon=True)
        self._sched_thread.start()

    def _pump_admission(self) -> None:
        """Drain pending admission work ('oldest'-policy frames, queued
        REST batches) whose tokens have refilled.  Wire connections
        pump their own controller between frames, but once a producer
        goes quiet nothing else would — without this timer tick, queued
        work could sit unfed until the next frame arrived or teardown
        shed it to the ErrorStore."""
        for ctrl in list(self.admission.values()):
            for w in ctrl.pump():
                ctrl.feed_safely(w)

    # -- on-demand (store) queries (reference: SiddhiAppRuntime.query:272) ---

    def query(self, text: str) -> list:
        """Execute an on-demand query against tables / named windows /
        aggregations; returns [(timestamp_ms, row_tuple)].  Compiled form
        is cached per query text (reference LRU-caches similarly)."""
        return self.query_with_schema(text)[1]

    def query_with_schema(self, text: str) -> tuple:
        """query() plus the compiled output schema -> (StreamSchema,
        rows) — the wire RESULT path needs the column names/types to
        encode the columnar reply; REST and in-process callers share
        this one compile/validate/execute path."""
        from ..query.parser import parse_store_query
        from .store import StoreQueryExec
        import time as _time
        # Take the net feed gate BEFORE the runtime lock (the same order
        # as net/server.py make_work): net feeds hold the gate across
        # admission -> feed, so a store query racing a frame flush can
        # never observe a half-applied batch.
        with self._net_gate, self._lock:
            exec_ = self._store_cache.get(text)
            if exec_ is None:
                if len(self._store_cache) >= 64:   # bounded like the
                    # reference's LRU (SiddhiAppRuntime.java:286)
                    self._store_cache.pop(next(iter(self._store_cache)))
                from ..interp.expr import udf_scope
                with udf_scope(getattr(self, "udfs", None)):
                    exec_ = StoreQueryExec(self, parse_store_query(text))
                self._store_cache[text] = exec_
            else:
                self._store_cache[text] = self._store_cache.pop(text)  # LRU touch
            self.flush()
            t0 = _time.perf_counter()
            rows = exec_.execute()
            self.stats.observe_store_query(
                _time.perf_counter() - t0, len(rows),
                trace=self.current_trace())
            return exec_.out_schema, rows

    def config_reader(self, namespace: str, name: str):
        """ConfigReader for one extension instance (reference:
        ConfigManager.generateConfigReader)."""
        from .config import ConfigManager, ConfigReader
        cm = getattr(self.manager, "config_manager", None) if self.manager \
            else None
        if cm is None:
            return ConfigReader({})
        return cm.generate_config_reader(namespace, name)

    def sources_for(self, stream_id: str) -> list:
        return [s for s in self.sources if s.stream_id == stream_id]

    def enable_stats(self, on: bool = True) -> None:
        """Runtime statistics toggle (reference: SiddhiAppRuntime.enableStats:763)."""
        self.stats.enabled = on

    def statistics(self) -> dict:
        return self.stats.report()

    def profile(self, window: Optional[int] = None) -> dict:
        """Device-time attribution report (core/profiler.py): per-plan
        phase seconds/shares, host-dispatch share, the windowed ring
        (last `window` snapshots; all when None), and the roofline fold
        — kernel eps (sampled estimate) vs the bench's native-C++
        roofline eps vs end-to-end eps per plan family.  `{"mode":
        "off"}` when `@app:profile('off')` disabled the plane."""
        if self.profiler is None:
            return {"mode": "off"}
        from .profiler import fold_roofline
        rep = self.profiler.profile(window=window)
        fold_roofline(rep, self._plans)
        return rep

    # -- frame tracing (core/tracing.py) -------------------------------------

    def current_trace(self):
        """The frame TraceHandle active on THIS thread (None when the
        in-flight work is untraced) — set by the net feed path, the
        dispatch loop's scatter block, and the sink outbox flush."""
        return getattr(self._trace_tls, "handle", None)

    def _set_trace(self, h):
        """Install `h` as this thread's active trace; returns the
        previous handle for the caller's finally-restore."""
        tls = self._trace_tls
        prev = getattr(tls, "handle", None)
        tls.handle = h
        return prev

    def explain(self) -> dict:
        """The EXPLAIN plane (core/placement.py): per-query execution
        path (device family vs interpreter), chosen pattern plan family,
        geometry provenance (annotation / tuning-cache / default), and
        the full Demotion reason chain for every rejected alternative.
        Served verbatim by `GET /siddhi/artifact/explain` and the
        `python -m siddhi_tpu.analysis` CLI."""
        from .placement import explain as _explain
        return _explain(self)

    def debug(self):
        """Attach the step debugger (reference: SiddhiAppRuntime.debug:575)."""
        from .telemetry import SiddhiDebugger
        if self._debugger is None:
            self._debugger = SiddhiDebugger(self)
        return self._debugger

    def shutdown(self) -> None:
        # serialized: two concurrent shutdowns (service.stop() racing an
        # undeploy that snapshotted the same runtime, user teardown
        # racing atexit) used to race the `self._sched_thread = None`
        # hand-off below — the loser crashed joining a None thread.
        # The mutex makes the second call a clean no-op pass-through.
        with self._shutdown_mutex:
            # joining the worker/scheduler threads under the mutex is the
            # point: the second caller must not proceed until teardown —
            # joins included — finished.  The joined threads never take
            # this mutex, so the joins always complete.
            # lint: allow (join-under-mutex is the once-only teardown barrier)
            self._shutdown_serialized()

    def _shutdown_serialized(self) -> None:
        if self._repl_receiver is not None:
            self._repl_receiver.stop()
            self._repl_receiver = None
        self._standby_active = False
        for s in (*self.sources, *self.sinks):
            if s.connected:
                s.disconnect()
                s.connected = False
        if self._ingest_thread is not None:
            try:
                self._async_barrier()    # deliver everything still queued
            finally:
                extras = getattr(self, "_extra_workers", [])
                for _ in range(1 + len(extras)):
                    self._ingest_q.put(None)     # one sentinel per worker
                self._ingest_thread.join(timeout=5)
                for t in extras:
                    t.join(timeout=5)
                self._ingest_thread = None
                self._extra_workers = []
                self._ingest_q = None    # flush() falls back to sync path
        if self._sched_stop is not None:
            self._sched_stop.set()
            self._sched_thread.join(timeout=2)
            self._sched_thread = None
            self._sched_stop = None
        self.stats.stop_reporting()
        self.flush()
        if self.wal is not None:
            # final barrier + close; keep the object for late metrics
            # scrapes but stop logging (the engine is down — a
            # post-shutdown send has no durability claim to honor)
            self.wal.close()
            self._wal_closed, self.wal = self.wal, None
        if self.tracing is not None:
            self.tracing.close()     # flush pending dumps, join exporter
        self._started = False

    # -- time ----------------------------------------------------------------

    def now_ms(self) -> int:
        # unguarded virtual-clock read: an int read is atomic under the
        # GIL and telemetry/scrape callers tolerate one tick of staleness
        if self._clock_ms is not None:  # lint: allow (atomic int read)
            return self._clock_ms
        return int(time.time() * 1000)

    def set_time(self, ms: int) -> None:
        """Advance the virtual clock (playback/test mode), firing due timers
        in wakeup order so timer-driven emissions interleave deterministically
        (reference: core:util/Scheduler.java:89 notifyAt semantics)."""
        from .trigger import TriggerRuntime
        if self._async and self._ingest_q is not None:
            self._async_barrier()
        with self._lock:
            self.flush()
            # entering virtual time (clock was wall) re-anchors all triggers
            # at the new timeline — a wall-clock anchor from start() would
            # otherwise put their next fire ~50 years out
            for p in self._plans:
                if isinstance(p, TriggerRuntime) and \
                        (self._clock_ms is None or not p.anchored):
                    p.anchor(self._clock_ms if self._clock_ms is not None else ms)
            # enter virtual time BEFORE firing: a pattern matcher lazily
            # anchors its absent wait-clocks at now_ms() on first
            # next_wakeup(), and a wall-clock anchor would put every
            # `not X for T` deadline ~50 years out on the event timeline
            if self._clock_ms is None:
                self._clock_ms = ms
            self._fire_timers_locked(ms)
            self._clock_ms = ms
            self._drain()
        self._flush_sink_outbox()

    def _fire_timers_locked(self, upto_ms: int) -> None:
        guard = 0
        while True:
            guard += 1
            if guard > 1_000_000:
                raise RuntimeError("runaway timer loop")
            due = [(w, p) for p in self._plans
                   for w in [p.next_wakeup()] if w is not None and w <= upto_ms]
            if not due:
                return
            w0 = min(w for w, _ in due)
            self._clock_ms = w0
            for w, plan in due:
                if w <= w0:
                    for ob in plan.on_timer(w0):
                        self._emit(plan, ob)
            self._drain()

    # -- ingest --------------------------------------------------------------

    def _check_not_standby(self) -> None:
        if self._standby_active:
            raise RuntimeError(
                f"app {self.app.name!r} is a standby replica — "
                f"promote() before ingesting")

    def send(self, stream_id: str, data, timestamp: Optional[int] = None) -> None:
        self._check_not_standby()
        with self._lock:
            self._send_locked(stream_id, data, timestamp)
        self._drain_async_outbox()
        self._flush_sink_outbox()

    def send_columnar(self, stream_id: str, columns: dict,
                      timestamps=None) -> None:
        """Columnar micro-batch ingest (see InputHandler.send_batch).
        The whole array set becomes ONE EventBatch dispatched through the
        same junction path as row-wise send; rows previously buffered via
        `send` merge AHEAD of the columnar segment in that batch (the
        builder adopts the arrays zero-copy — batch.py append_columnar —
        so arrival order is preserved without a split micro-batch)."""
        self._check_not_standby()
        from .schema import dtype_of as _dtype_of
        schema = self.schemas.get(stream_id)
        if schema is None:
            raise PlanError(f"unknown stream {stream_id!r}")
        attrs = schema.attributes
        missing = [a.name for a in attrs if a.name not in columns]
        if missing:
            raise ValueError(
                f"stream {stream_id!r}: send_batch missing columns {missing}")
        with self.stats.stage("ingest") as _sp:
            cols: dict = {}
            to_encode: list = []
            n = None
            for a in attrs:
                v = columns[a.name]
                if a.type == qast.AttrType.STRING:
                    arr = np.asarray(v)
                    if arr.dtype.kind in "iu":          # pre-encoded dict codes
                        arr = arr.astype(np.int32, copy=False)
                    else:           # str values: encode under the lock
                        to_encode.append(a.name)  # (StringTable is shared)
                else:
                    arr = np.asarray(v, dtype=_dtype_of(a.type))
                if arr.ndim != 1:
                    raise ValueError(
                        f"stream {stream_id!r}: column {a.name!r} must be a "
                        f"1-d array/list of values, got shape {arr.shape}")
                rows_in = arr.shape[0]
                if n is None:
                    n = rows_in
                elif rows_in != n:
                    raise ValueError(
                        f"stream {stream_id!r}: column {a.name!r} has "
                        f"{rows_in} rows, expected {n}")
                cols[a.name] = arr
            if not n:
                return
            if self.stats.enabled:   # row count known only at span close
                _sp.events = n       # (guard: _NOOP is a shared singleton)
            if timestamps is None:
                ts = None
            else:
                ts = np.atleast_1d(np.asarray(timestamps, dtype=np.int64))
                if ts.shape[0] == 1 and n > 1:
                    ts = np.full(n, int(ts[0]), dtype=np.int64)
                if ts.shape[0] != n:
                    raise ValueError(
                        f"stream {stream_id!r}: {ts.shape[0]} timestamps for "
                        f"{n} rows")
        with self._lock:
            for name in to_encode:      # shared-table writes: locked
                # vectorized: the dict is consulted once per DISTINCT value
                cols[name] = self.strings.encode_many(cols[name])
            if ts is None:
                ts = np.full(n, self.now_ms(), dtype=np.int64)
            b = self._builders.get(stream_id)
            if b is None:
                b = self._builders[stream_id] = BatchBuilder(
                    schema, self.strings, self.batch_capacity)
            seqs = np.arange(self._seq + 1, self._seq + 1 + n,
                              dtype=np.int64)
            self._seq += n
            if self._playback and timestamps is not None:
                # advance the event-time clock (row-path advance()) by the
                # batch MAXIMUM: an unsorted timestamp array must not
                # rewind event time (ts[-1] could).  Wall-stamped batches
                # must NOT anchor playback time.
                self._clock_ms = int(ts.max())
            b.append_columnar(ts, cols, seqs)
            batch = self._freeze(stream_id, b)
            if self._async and self._ingest_q is not None:
                # async mode: older batches may still sit in the ingest
                # queue — stage through the same outbox so FIFO holds
                self._async_outbox.append((stream_id, batch))
            else:
                self._pending.append((stream_id, batch))
                self._drain()
        self._drain_async_outbox()
        self._flush_sink_outbox()

    def _drain_async_outbox(self) -> None:
        """Enqueue batches staged by _send_locked — outside the lock, so a
        full (bounded) queue blocks the producer without wedging the
        worker."""
        if not self._async_outbox:
            return
        # pop+put under a dedicated mutex so two producers can't reorder
        # staged batches (the worker never takes this mutex — no deadlock)
        with self._outbox_mutex:
            while True:
                try:
                    item = self._async_outbox.pop(0)
                except IndexError:
                    return
                # bounded-queue backpressure is deliberate: a full queue
                # stalls producers, never the worker (which drains it
                # without ever taking this mutex — no deadlock)
                # lint: allow (backpressure by design; worker never locks this)
                self._ingest_q.put(item)

    def _send_locked(self, stream_id: str, data, timestamp: Optional[int]) -> None:
        schema = self.schemas[stream_id]
        b = self._builders.get(stream_id)
        if b is None:
            b = self._builders[stream_id] = BatchBuilder(schema, self.strings,
                                                         self.batch_capacity)
        def advance(ts: int) -> int:
            if self._playback:
                self._clock_ms = ts
            return ts

        def nseq() -> int:
            self._seq += 1
            return self._seq

        if self.max_batch_latency_s is not None and not len(b):
            self._builder_t0[stream_id] = time.perf_counter()
        if isinstance(data, Event):
            b.append(advance(data.timestamp if timestamp is None else timestamp),
                     data.data, nseq())
        elif data and isinstance(data, (list,)) and isinstance(data[0], (tuple, list, Event)):
            for row in data:
                if isinstance(row, Event):
                    b.append(advance(row.timestamp), row.data, nseq())
                else:
                    b.append(advance(self.now_ms() if timestamp is None else timestamp),
                             row, nseq())
        else:
            ts = self.now_ms() if timestamp is None else timestamp
            if timestamp is not None:
                advance(ts)
            b.append(ts, tuple(data), nseq())
        due = (self.max_batch_latency_s is not None and len(b)
               and time.perf_counter() - self._builder_t0.get(stream_id, 0.0)
               >= self.max_batch_latency_s)
        if b.full or due:
            if self._async and self._ingest_q is not None:
                # stage; the public entry enqueues AFTER releasing the lock
                # (a blocking put under the lock would deadlock against the
                # worker, which needs the lock to process)
                self._async_outbox.append((stream_id,
                                           self._freeze(stream_id, b)))
            else:
                self.flush()

    # -- dispatch ------------------------------------------------------------

    def _freeze(self, stream_id: str, b: BatchBuilder) -> EventBatch:
        """Freeze one builder; under an SLO controller the frozen batch is
        stamped with its first-append wall time so _drain can feed the
        controller an end-to-end (wait + processing) latency sample.

        Durability hook: every frozen ingest batch (this is where
        externally admitted frames are born — derived emissions bypass
        the builders) appends to the WAL, write-ahead of processing,
        getting its per-stream monotonic frame seq here.  A failed
        append propagates: the frame must not be processed with no
        durable record (the net feed path captures it whole into the
        ErrorStore; direct senders see the error)."""
        # frame tracing: a net-fed frame carries its handle in the
        # thread-local scope (producer-stamped or admission-sampled);
        # anything else — direct sends, REST rows — makes its sampling
        # decision here, where every externally admitted frame is born
        h = getattr(self._trace_tls, "handle", None)
        if h is None and self.tracing is not None:
            h = self.tracing.begin_frame(stream_id)
        t0f = time.perf_counter() if h is not None else 0.0
        batch = b.freeze_and_clear()
        if h is not None:
            batch.__dict__["_trace"] = h
        if self.wal is not None and not self._wal_replaying:
            try:
                t0w = time.perf_counter() if h is not None else 0.0
                seq = self.wal.append(stream_id, batch.timestamps,
                                      batch.columns, self.strings,
                                      schema=batch.schema)
                if h is not None:
                    # the trace rides the WAL plane's frame identity:
                    # the per-stream durable seq names this frame
                    h.mark("wal.append", t0w, time.perf_counter() - t0w,
                          stream=stream_id, seq=seq)
            except BaseException as e:
                # the builder is already cleared: rows buffered by
                # EARLIER successful sends ride this frozen batch, so a
                # propagating append error alone would strand them —
                # capture the whole batch, replayable, and mark the
                # exception so the net feed path doesn't capture the
                # same frame a second time (a double entry would
                # double-ingest on replay)
                rows = [(int(ts), row) for ts, row in
                        zip(batch.timestamps, batch.rows(self.strings))]
                self.error_store.add(stream_id, "wal.append", e,
                                     self.now_ms(), events=rows)
                self.stats.on_fault(stream_id, "wal.append")
                e._wal_captured = True
                raise
        if h is not None:
            h.mark("freeze", t0f, time.perf_counter() - t0f,
                  stream=stream_id, events=batch.n)
        if self.slo is not None:
            t0 = self._builder_t0.pop(stream_id, None)
            batch.__dict__["_slo_t0"] = \
                t0 if t0 is not None else time.perf_counter()
        return batch

    def _apply_batch_target(self, n: int) -> None:
        """Apply an SLO-controller batch decision AT A FLUSH BOUNDARY:
        future builders freeze at the new capacity and plans learn the
        hint through their regeometry() hook.  Batches already frozen or
        in flight are untouched — only where future batch boundaries
        fall changes, which the geometry-invariance differentials prove
        is output-invariant (faults.split_batch parity, PR 4)."""
        n = max(1, int(n))
        self.batch_capacity = n
        # lint: allow (called from _drain at a flush boundary: lock held)
        for b in self._builders.values():
            b.capacity = n
        for p in self._plans:
            rg = getattr(p, "regeometry", None)
            if rg is not None:
                rg(batch_hint=n)

    def flush(self) -> None:
        """Drain all pending builders through the compiled plans.  In
        @app:async mode this is the barrier: leftovers enqueue to the
        ingest worker and the call returns once the queue is empty (all
        callbacks delivered).  Must NOT be called while holding the
        runtime lock in async mode (the worker needs it) — internal
        callers use _async_barrier() before locking."""
        if self._async and self._ingest_q is not None:
            self._async_barrier()
            with self._lock:
                self._flush_plan_pipelines()
            self._flush_sink_outbox()
            return
        with self._lock:
            for sid, b in self._builders.items():
                if len(b):
                    self._pending.append((sid, self._freeze(sid, b)))
            self._drain()
            self._flush_plan_pipelines()
        self._flush_sink_outbox()

    def _flush_plan_pipelines(self) -> None:
        """Materialize device results still in flight in pipelined plans
        (@app:devicePipeline defers output delivery by up to D batches);
        flush() is the barrier where every produced event is delivered."""
        guard = 0
        while True:
            guard += 1
            if guard > 100_000:     # same bound as _drain: an insert-into
                raise RuntimeError(  # cycle through a pipelined plan
                    "runaway stream recursion (insert-into cycle?)")
            progressed = False
            for plan in self._plans:
                for ob in self._guarded_collect(plan, "flush_pending"):
                    self._emit(plan, ob)
                    progressed = True
            if not progressed and not self._pending:
                return
            self._drain()

    def _async_barrier(self) -> None:
        import queue as _queue
        owned = getattr(self._lock, "_is_owned", lambda: False)()
        if owned and self._enforce_order:
            # @app:enforceOrder: the (single) worker may have POPPED a
            # batch and be blocked on the lock we hold — draining the
            # queue or builders inline would process newer batches first.
            # Surface latched errors and return: the nested reader sees
            # state as-of now; the queued tail flushes, in order, after
            # we release (concurrent ingest has no defined serialization
            # against a nested query/snapshot anyway).
            if self._ingest_err is not None:
                err, self._ingest_err = self._ingest_err, None
                raise err
            return
        if owned:
            # the caller holds the runtime lock (query()/snapshot()/
            # set_time() nested flush): the worker can't run, so drain the
            # queue inline ourselves — FIFO first, then builder leftovers —
            # preserving order without deadlocking on queue.join()
            while True:
                try:
                    item = self._ingest_q.get_nowait()
                except _queue.Empty:
                    break
                try:
                    if item is not None:
                        sid, batch = item
                        self._pending.append((sid, batch))
                        self._drain()
                finally:
                    self._ingest_q.task_done()
            # lint: allow (owned branch: _is_owned() proved we hold the lock)
            for sid, b in self._builders.items():
                if len(b):
                    self._pending.append((sid, self._freeze(sid, b)))
            self._drain()
            if self._ingest_err is not None:
                err, self._ingest_err = self._ingest_err, None
                raise err
            return
        with self._lock:
            leftovers = [(sid, self._freeze(sid, b))
                         for sid, b in self._builders.items() if len(b)]
        self._async_outbox.extend(leftovers)
        self._drain_async_outbox()
        self._ingest_q.join()
        if self._ingest_err is not None:
            err, self._ingest_err = self._ingest_err, None
            raise err

    def _flush_sink_outbox(self) -> None:
        """Deliver staged sink payloads outside the runtime lock.  When
        called from a nested frame the outer frame may still hold the RLock;
        the outermost public entry always ends with an unlocked flush.
        The net feed path DEFERS delivery past its feed-vs-retire gate
        (thread-local `defer_sink`): a sink retry backoff must never
        stall an undeploy waiting on the gate."""
        if getattr(self._trace_tls, "defer_sink", 0):
            return                      # the gate holder flushes after
        prof = self.profiler
        while True:
            try:        # pop-then-use: safe vs the scheduler pump thread
                fn, events, h = self._sink_outbox.pop(0)
            except IndexError:
                return
            _st0 = time.perf_counter() if prof is not None else 0.0
            try:
                if h is None:
                    fn(events)
                    continue
                # deliver under the originating frame's trace scope so
                # the sink records its publish span on the right tree
                # even when the flush happens on the scheduler/ingest
                # thread
                prev = self._set_trace(h)
                try:
                    fn(events)
                finally:
                    self._trace_tls.handle = prev
            finally:
                if prof is not None:
                    try:
                        n = len(events)
                    except TypeError:
                        n = 0
                    prof.note("_sink", "sink_egress",
                              time.perf_counter() - _st0, events=n)

    def _drain(self) -> None:
        guard = 0
        prof = self.profiler
        while True:
            guard += 1
            if guard > 100_000:
                raise RuntimeError("runaway stream recursion (insert-into cycle?)")
            if not self._pending:
                # multi-input plans (patterns/sequences/joins) buffer events
                # per stream and merge by global seq once the round settles.
                # The finalize pass is a dispatch round: every plan's device
                # blocks launch before the first blocking D2H pull, so N
                # plans overlap on device instead of serializing
                # build -> compute -> readback per plan.
                progressed = False
                for plan in self._plans:
                    plan.begin_dispatch_round()
                    pipe = getattr(plan, "_pipe", None)
                    if pipe is not None:
                        # finalize-round entries merge several batches:
                        # no single origin to attribute faults to
                        pipe.origin = None
                for plan in self._plans:
                    try:
                        if prof is not None:
                            with prof.round(plan.name):
                                obs = plan.finalize()
                        else:
                            obs = plan.finalize()
                    except Exception as e:
                        obs = self._recover_finalize(plan, e)
                        if obs is None:
                            raise
                    for ob in obs:
                        self._emit(plan, ob)
                        progressed = True
                for plan in self._plans:
                    for ob in self._guarded_collect(plan):
                        self._emit(plan, ob)
                        progressed = True
                if not self._pending and not progressed:
                    return
                if not self._pending:
                    continue
            sid, batch = self._pending.pop(0)
            if self.slo is not None and self.slo.adaptive and batch.n >= 2 \
                    and batch.n > 2 * self.batch_capacity:
                # oversized ingest (a columnar send bigger than the SLO
                # controller's current target): split with the PR-4
                # halving machinery — output-invariant by the same parity
                # argument as the degradation ladder — so one giant batch
                # can't blow the latency target
                from .faults import split_batch
                t0b = batch.__dict__.get("_slo_t0")
                halves = split_batch(batch)
                for h in halves:
                    if t0b is not None:
                        h.__dict__["_slo_t0"] = t0b
                self._pending[:0] = [(sid, h) for h in halves]
                continue
            # the stream timer opens a batch-trace scope and feeds the
            # per-stream latency histogram (one clock read per batch);
            # a traced frame's id rides into the histogram as the
            # bucket exemplar (`/metrics` OpenMetrics exemplars)
            h_tr = batch.__dict__.get("_trace")
            # batch wall = the profiler's coverage denominator: rounds +
            # scatter must attribute >= ~90% of this (docs/OBSERVABILITY.md)
            _pt0 = time.perf_counter() if prof is not None else 0.0
            with self.stats.time_stream(
                    sid, batch.n,
                    trace_id=None if h_tr is None else h_tr.trace_id):
                cbs_b = self._batch_callbacks.get(sid, ())
                cbs_s = self._stream_callbacks.get(sid, ())
                if cbs_b or cbs_s:
                    # scatter under the frame's trace scope: the sink
                    # stage callback (io.build_io) snapshots the active
                    # handle into its outbox entry, so egress spans land
                    # on this frame's tree even though publish happens
                    # later, outside the lock, possibly on another thread
                    prev_tr = self._set_trace(h_tr) \
                        if h_tr is not None else None
                    try:
                        with self.stats.stage("scatter", events=batch.n):
                            for cb in cbs_b:
                                cb(batch)
                            for cb in cbs_s:  # junction callbacks: each
                                cb(self._decode(batch))  # gets its own list
                    finally:
                        if h_tr is not None:
                            self._trace_tls.handle = prev_tr
                fault_err = None
                subs = self._subscribers.get(sid, ())
                # dispatch round: every subscribed plan dispatches its
                # device block for this batch before any plan blocks on a
                # result pull (collect below) — cross-plan overlap
                for plan in subs:
                    plan.begin_dispatch_round()
                    pipe = getattr(plan, "_pipe", None)
                    if pipe is not None:
                        # entries pushed while this batch is processed
                        # belong to it: fault attribution under pipelining
                        pipe.origin = (sid, batch)
                for plan in subs:
                    if self._debugger is not None:
                        self._debugger.check_in(plan, batch)
                    t0d = time.perf_counter() if h_tr is not None else 0.0
                    try:
                        if prof is not None:
                            with prof.round(plan.name, batch.n):
                                if self.stats.enabled:
                                    with self.stats.time_plan(plan.name,
                                                              batch.n):
                                        obs = plan.process(sid, batch)
                                else:
                                    obs = plan.process(sid, batch)
                        elif self.stats.enabled:
                            with self.stats.time_plan(plan.name, batch.n):
                                obs = plan.process(sid, batch)
                        else:
                            obs = plan.process(sid, batch)
                    except Exception as e:
                        obs = self._recover_process(plan, sid, batch, e)
                        if obs is None:
                            if self.fault_action(sid) is None:
                                raise
                            fault_err = e    # route once per batch, below
                            continue
                    if h_tr is not None:
                        h_tr.mark("dispatch", t0d,
                                 time.perf_counter() - t0d, plan=plan.name)
                        for ob in obs:
                            # derived emissions inherit the frame's trace
                            # so downstream drains + sink egress stay on
                            # one connected tree
                            ob.batch.__dict__.setdefault("_trace", h_tr)
                    if self._debugger is not None:
                        self._debugger.check_out(plan, obs)
                    for ob in obs:
                        self._emit(plan, ob)
                for plan in subs:
                    try:
                        if prof is not None:
                            with prof.round(plan.name):
                                obs = plan.collect_ready()
                        else:
                            obs = plan.collect_ready()
                    except Exception as e:
                        # pipelined entries carry their origin batch: a
                        # depth-D materialization failure routes the batch
                        # it BELONGS to (which may be D batches old), so
                        # @OnError stays exact under @app:devicePipeline
                        origin = getattr(e, "fault_origin", None)
                        if origin is not None:
                            osid, obatch = origin
                            if obatch is batch:
                                if self.fault_action(sid) is None:
                                    raise
                                fault_err = fault_err or e
                                continue
                            if not self._handle_batch_fault(osid, obatch, e):
                                raise
                            continue
                        depth = getattr(getattr(plan, "_pipe", None),
                                        "depth", 0)
                        if depth or self.fault_action(sid) is None:
                            raise
                        fault_err = fault_err or e
                        continue
                    if self._debugger is not None and obs:
                        # pipelined plans deliver through the dispatch
                        # round's collect, not process(): the OUT
                        # breakpoint must see these too
                        self._debugger.check_out(plan, obs)
                    for ob in obs:
                        self._emit(plan, ob)
                if fault_err is not None:
                    if not self._handle_batch_fault(sid, batch, fault_err):
                        raise fault_err
            if prof is not None:
                prof.note_batch(time.perf_counter() - _pt0, batch.n)
                prof.maybe_roll()
            if self.slo is not None:
                # one end-to-end latency sample per dispatched batch; AIMD
                # decisions land between batches — a flush boundary — so
                # geometry never changes under a batch in flight
                now = time.perf_counter()
                t0b = batch.__dict__.get("_slo_t0")
                if t0b is not None:
                    self.slo.observe(now - t0b)
                dec = self.slo.maybe_decide(now)
                if dec is not None:
                    if int(dec["batch"]) != self.batch_capacity:
                        self._apply_batch_target(int(dec["batch"]))
                    if self.admission:
                        # lower admission BEFORE latency collapses: the
                        # serving plane's token buckets scale by the
                        # controller's admission factor (docs/SERVING.md).
                        # list(): net connection threads insert new
                        # controllers at HELLO time, concurrently
                        f = dec.get("admission_factor", 1.0)
                        for ctrl in list(self.admission.values()):
                            ctrl.set_rate_factor(f)

    # -- fault handling ------------------------------------------------------

    def fault_action(self, sid: str) -> Optional[str]:
        """The @OnError action configured for a stream (None = fail-fast)."""
        return self._onerror.get(sid)

    def inject(self, point: str, detail: str = "") -> None:
        """Fault-injection check (no-op unless a faults.FaultInjector is
        armed on `rt.fault_injector`)."""
        inj = self.fault_injector
        if inj is not None:
            inj.check(point, detail)

    def _ladder(self, plan) -> "FaultLadder":
        from .faults import FaultLadder
        lad = self._ladders.get(plan.name)
        if lad is None:
            lad = self._ladders[plan.name] = FaultLadder()
        return lad

    def _guarded_collect(self, plan, fn_name: str = "collect_ready") -> list:
        """collect_ready/flush_pending with origin-attributed fault
        routing: a pipelined entry that fails to materialize routes the
        batch it was dispatched for (per its stream's @OnError action)
        while later entries keep flowing."""
        prof = self.profiler
        try:
            if prof is not None:
                with prof.round(plan.name):
                    return getattr(plan, fn_name)()
            return getattr(plan, fn_name)()
        except Exception as e:
            origin = getattr(e, "fault_origin", None)
            if origin is None or not self._handle_batch_fault(
                    origin[0], origin[1], e):
                raise
            return []

    def _handle_batch_fault(self, sid: str, batch: EventBatch, err) -> bool:
        """Dispose of one failed batch per the stream's @OnError action.
        Returns False when the error must propagate (no action, or
        action 'wait' — which is handled at the retry site)."""
        action = self.fault_action(sid)
        if action is None or action == "wait":
            return False
        self.stats.on_fault(sid, action)
        if action == "log":
            import logging
            logging.getLogger("siddhi_tpu.faults").error(
                "stream %r: dropping results of a %d-event batch per "
                "@OnError(action='log'): %s: %s",
                sid, batch.n, type(err).__name__, err)
            return True
        if action == "store":
            rows = [(int(ts), row) for ts, row in
                    zip(batch.timestamps, batch.rows(self.strings))]
            self.error_store.add(sid, "dispatch", err, self.now_ms(),
                                 events=rows)
            return True
        return self._route_fault_batch(sid, batch, err)

    def _recover_process(self, plan, sid: str, batch: EventBatch, err):
        """Recovery for a plan.process failure: the degradation ladder
        for resource exhaustion on retryable device plans, blocking
        retry for @OnError(action='wait').  Returns the recovered
        OutputBatches, or None when unrecovered (caller falls back to
        @OnError disposition / raise)."""
        from .faults import is_resource_error
        if is_resource_error(err) and getattr(plan, "retryable_process",
                                              False):
            return self._ladder_process(plan, sid, batch, err)
        if self.fault_action(sid) == "wait":
            return self._wait_retry(plan, sid, batch, err)
        return None

    def _ladder_process(self, plan, sid: str, batch: EventBatch, err):
        """Degradation ladder, process-dispatching plans: halve the batch
        (the device pad geometry derives from batch.n, so a retry runs at
        half the footprint); after `quarantine_after` CONSECUTIVE
        failures, quarantine the plan onto the interpreter path and feed
        it the still-unprocessed pieces — no event is lost or doubled."""
        from .faults import is_resource_error, split_batch
        lad = self._ladder(plan)
        lad.fail(err)
        if batch.n >= 2:
            lad.halvings += 1
            stack = split_batch(batch)
        else:
            stack = [batch]
        out: list = []
        while stack:
            if lad.consecutive >= self.quarantine_after:
                twin = self._try_quarantine(plan, err)
                if twin is None:
                    return None
                for b in stack:
                    out.extend(twin.process(sid, b))
                return out
            b = stack.pop(0)
            try:
                obs = plan.process(sid, b)
            except Exception as e:
                if not is_resource_error(e):
                    raise
                err = e
                lad.fail(e)
                if b.n >= 2:
                    lad.halvings += 1
                    stack[:0] = split_batch(b)
                else:
                    stack.insert(0, b)
                continue
            lad.ok()
            out.extend(obs)
            # materialize before the next retry dispatch: recovery can
            # re-dispatch several times inside ONE held dispatch round,
            # and stacking those in flight would exceed the PadPool's
            # rotation guarantee (an in-flight entry's upload pad must
            # not be refilled before the device consumed it)
            pipe = getattr(plan, "_pipe", None)
            if pipe is not None and len(pipe):
                out.extend(plan.flush_pending())
        return out

    def _recover_finalize(self, plan, err):
        """Degradation ladder, finalize-dispatching plans (patterns,
        joins — they buffer per stream and dispatch the merged flush):
        halve the flush (two finalize rounds are equivalent to the events
        arriving in two flushes), then quarantine.  Requires the plan to
        restore its input buffer on a finalize failure
        (retryable_finalize contract)."""
        from .faults import is_resource_error, split_buffered
        if not is_resource_error(err) \
                or not getattr(plan, "retryable_finalize", False) \
                or not getattr(plan, "_finalize_retry_ok", True):
            return None
        lad = self._ladder(plan)
        lad.fail(err)
        bufs = list(getattr(plan, "_buffered", ()))
        plan._buffered = []
        halves = split_buffered(bufs)
        if halves:
            lad.halvings += 1
            work = halves
        else:
            work = [bufs] if bufs else []
        out: list = []
        while work:
            if lad.consecutive >= self.quarantine_after:
                twin = self._try_quarantine(plan, err)
                if twin is None:
                    # hand the events back so nothing is silently lost
                    plan._buffered = [sb for chunk in work for sb in chunk]
                    return None
                for chunk in work:
                    for s, b in chunk:
                        out.extend(twin.process(s, b))
                out.extend(twin.finalize())
                return out
            chunk = work.pop(0)
            plan._buffered = chunk
            try:
                obs = plan.finalize()
            except Exception as e:
                if not is_resource_error(e) \
                        or not getattr(plan, "_finalize_retry_ok", True):
                    raise
                err = e
                lad.fail(e)
                chunk = list(plan._buffered)    # restored by the plan
                plan._buffered = []
                halves = split_buffered(chunk)
                if halves:
                    lad.halvings += 1
                    work[:0] = halves
                else:
                    work.insert(0, chunk)
                continue
            lad.ok()
            out.extend(obs)
            # same in-flight bound as _ladder_process: one recovery
            # dispatch at a time, materialized before the next retry
            pipe = getattr(plan, "_pipe", None)
            if pipe is not None and len(pipe):
                out.extend(plan.flush_pending())
        return out

    def _wait_retry(self, plan, sid: str, batch: EventBatch, err):
        """@OnError(action='wait'): block ingest (we hold the runtime
        lock) retrying the failed work with backoff until the configured
        deadline, then give up loudly."""
        from .faults import BackoffPolicy
        timeout = self._onerror_wait.get(sid, 10.0)
        deadline = time.monotonic() + timeout
        self.stats.on_fault(sid, "wait")
        policy = BackoffPolicy(max_tries=1_000_000,
                               base_delay_s=min(0.02, timeout / 16),
                               max_delay_s=max(timeout / 8, 0.02), seed=0)
        for delay in policy.delays():
            if time.monotonic() + delay > deadline:
                break
            # lint: allow (@OnError(action='wait') blocks ingest by contract)
            time.sleep(delay)
            try:
                return plan.process(sid, batch)
            except Exception as e:
                err = e
        raise RuntimeError(
            f"{sid}: @OnError(action='wait') gave up after {timeout:.3g}s: "
            f"{type(err).__name__}: {err}") from err

    def _try_quarantine(self, plan, err):
        """Swap a failing device plan for its interpreter twin
        (byte-identical semantics — the parity suites assert it).  The
        twin takes over from the CURRENT point in the stream: results
        already delivered stay delivered; retained device window/tail
        contents from before the quarantine are sacrificed for forward
        progress (documented in docs/RELIABILITY.md).  Returns None when
        no interpreter twin exists for this plan shape."""
        import warnings
        try:
            twin = self._build_twin(plan)
        except Exception as e:
            warnings.warn(
                f"plan {plan.name!r}: interpreter quarantine unavailable "
                f"({type(e).__name__}: {e}); propagating the device error",
                RuntimeWarning)
            return None
        # deliver what's still materializable in flight, then discard
        pipe = getattr(plan, "_pipe", None)
        if pipe is not None:
            try:
                for ob in plan.flush_pending():
                    self._emit(plan, ob)
            except Exception as e2:
                origin = getattr(e2, "fault_origin", None)
                if origin is None or not self._handle_batch_fault(
                        origin[0], origin[1], e2):
                    self.error_store.add(
                        plan.name, "quarantine.flush", e2, self.now_ms())
            pipe.take_all()
        self._swap_plan(plan, twin)
        lad = self._ladder(plan)
        lad.quarantined = True
        self.placement.demote(
            plan.name, "D-QUARANTINE",
            f"degradation ladder quarantined the plan onto the "
            f"interpreter path after {lad.consecutive} consecutive "
            f"device dispatch failures", cause=err,
            alternative=f"device-{type(plan).__name__}")
        self._degraded.append({
            "plan": plan.name, "at_ms": self.now_ms(),
            "after_failures": lad.failures,
            "error": f"{type(err).__name__}: {err}"})
        if self.tracing is not None:
            # nonblocking enqueue (we hold the runtime lock here): the
            # dump itself is built on the siddhi-trace-export thread
            self.tracing.trigger(
                "quarantine", f"plan {plan.name!r}: "
                              f"{type(err).__name__}: {err}")
        warnings.warn(
            f"plan {plan.name!r} quarantined onto the interpreter path "
            f"after {lad.consecutive} consecutive device dispatch "
            f"failures ({type(err).__name__}: {err})", RuntimeWarning)
        return twin

    def _swap_plan(self, plan, twin) -> None:
        """Replace `plan` with `twin` everywhere the runtime holds it
        (plan list, name index, stream subscriptions), preserving the
        callback identity and table writer."""
        twin.callback_name = getattr(plan, "callback_name", plan.name)
        twin.table_writer = plan.table_writer
        self._plans[self._plans.index(plan)] = twin
        self._plan_by_name[plan.name] = twin
        for lst in self._subscribers.values():
            for j, p in enumerate(lst):
                if p is plan:
                    lst[j] = twin
        for s in twin.input_streams:
            if twin not in self._subscribers[s]:
                self._subscribers[s].append(twin)

    def _build_twin(self, plan):
        """Construct the interpreter-path twin of a device plan from the
        (normalized) query AST it was planned from."""
        q = plan._q_ast
        if q is None:
            raise PlanError(f"plan {plan.name!r} has no source query AST")
        inp = q.input
        from ..interp.expr import udf_scope
        with udf_scope(getattr(self, "udfs", None)):
            if isinstance(inp, qast.JoinInputStream):
                from ..interp.joins import InterpJoinQueryPlan
                return InterpJoinQueryPlan(plan.name, self, q, inp,
                                           plan.output_target)
            if isinstance(inp, qast.StateInputStream):
                from ..interp.engine import InterpPatternQueryPlan
                return InterpPatternQueryPlan(plan.name, self, q, inp,
                                              plan.output_target)
            from ..interp.engine import InterpSingleQueryPlan
            return InterpSingleQueryPlan(plan.name, self, q, inp,
                                         plan.output_target)

    def _route_fault_batch(self, sid: str, batch: EventBatch, err) -> bool:
        """@OnError(action='stream'): reroute a failing batch's events into
        `!sid` with the error message (reference: StreamJunction fault
        routing via FaultStreamEventConverter)."""
        fault_id = "!" + sid
        fs = self.schemas.get(fault_id)
        if fs is None:
            return False
        msg = f"{type(err).__name__}: {err}"
        bb = BatchBuilder(fs, self.strings)
        for ts, row in zip(batch.timestamps, batch.rows(self.strings)):
            bb.append(int(ts), (*row, msg), self._seq + 1)
            self._seq += 1
        self._pending.append((fault_id, bb.freeze()))
        return True

    def _route_fault_rows(self, sid: str, rows: list, msg: str,
                          raw=None) -> None:
        """Fault entry for errors before decoding (source mapper failures):
        attributes are null, `_error` carries the message."""
        fault_id = "!" + sid
        fs = self.schemas.get(fault_id)
        if fs is None:
            raise RuntimeError(
                f"{sid}: {msg} (no @OnError fault stream; annotate the "
                f"stream with @OnError(action='stream') — or use "
                f"action='store' to capture into the replayable ErrorStore, "
                f"'log' to log-and-drop, 'wait' to block-and-retry)")
        with self._lock:
            bb = BatchBuilder(fs, self.strings)
            n_attrs = len(fs.attributes) - 1
            def nseq() -> int:
                self._seq += 1
                return self._seq
            if rows:
                for ts, row in rows:
                    bb.append(self.now_ms() if ts is None else ts,
                              (*row, msg), nseq())
            else:
                bb.append(self.now_ms(), (*([None] * n_attrs), msg), nseq())
            self._pending.append((fault_id, bb.freeze()))
            self._drain()

    def _emit(self, plan: QueryPlan, ob: OutputBatch) -> None:
        if ob.batch.n == 0 and not ob.is_signal:
            return
        cb_name = getattr(ob, "callback_name", None) \
            or getattr(plan, "callback_name", plan.name)
        cbs = self._query_callbacks.get(cb_name, ())
        if cbs:
            with self.stats.stage("scatter", events=ob.batch.n):
                ts_last = int(ob.batch.timestamps[-1]) if ob.batch.n else 0
                for cb in cbs:              # fresh Event list per callback:
                    events = self._decode(ob.batch)   # mutation-safe
                    if ob.is_expired:
                        cb(ts_last, None, events)
                    else:
                        cb(ts_last, events, None)
        # table targets route through the plan's table writer (reference:
        # OutputParser-chosen Insert/Update/Delete/UpdateOrInsert callbacks)
        if plan.table_writer is not None:
            plan.table_writer.apply(ob.batch)
            return
        # named-window targets feed the shared window, whose republished
        # emissions recurse through _emit as plain stream batches
        # (reference: InsertIntoWindowCallback -> Window.add)
        nw = self.named_windows.get(ob.target)
        if nw is not None and plan is not nw:
            for ob2 in nw.insert(ob.batch):
                self._emit(nw, ob2)
            return
        # plans emit only what events_for selects; everything with a target is
        # inserted (expired events become current on entering the next stream,
        # reference: InsertIntoStreamCallback)
        if ob.target is not None:
            # derived events arrive "now": stamp global seqs so downstream
            # multi-input plans (patterns/joins) merge them in true order
            n = ob.batch.n
            ob.batch.seqs = np.arange(self._seq + 1, self._seq + 1 + n,
                                      dtype=np.int64)
            self._seq += n
            self._pending.append((ob.target, ob.batch))

    def _decode(self, batch: EventBatch) -> list:
        rows = batch.rows(self.strings)
        return [Event(int(ts), row) for ts, row in zip(batch.timestamps, rows)]

    # -- persistence (full snapshot; reference SiddhiAppRuntime.persist:595) --

    def snapshot(self) -> dict:
        if self._async and self._ingest_q is not None:
            self._async_barrier()
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        self.flush()
        return {
            "strings": self.strings.state(),
            "plans": {p.name: p.state_dict() for p in self._plans},
            "tables": {k: t.state_dict() for k, t in self.tables.items()},
            "clock": self._clock_ms,
            # the global arrival counter must survive: plans order and
            # dedup by seq (chunked replay compares against the last
            # emitted completion seq — a restarted counter re-suppresses)
            "seq": self._seq,
            # quarantined plans: their state above is in the interpreter
            # twin's format — restore must re-quarantine before loading
            "degraded": list(self._degraded),
            # per-stream durable watermark: the last WAL frame seq this
            # snapshot's state already reflects (flush() above applied
            # every appended frame).  Recovery replays strictly past it.
            "wal": self.wal.watermark() if self.wal is not None else None,
        }

    def restore(self, snap: dict) -> None:
        # under the runtime lock: a restore on a STARTED runtime races
        # the scheduler pump's timer fires and any concurrent ingest —
        # plan state must never be half-swapped under a live _drain
        # (surfaced by the SL03 lockset self-analysis, docs/ANALYSIS.md)
        with self._lock:
            self._restore_locked(snap)

    def _restore_locked(self, snap: dict) -> None:
        self.strings.restore(snap["strings"])
        # a snapshot taken AFTER a quarantine carries that plan's state in
        # the interpreter twin's format: swap the live device plan for a
        # fresh twin first, so load_state_dict meets matching state
        for rec in snap.get("degraded", ()):
            plan = self._plan_by_name.get(rec.get("plan"))
            if plan is None or type(plan).__name__.startswith("Interp"):
                if rec not in self._degraded:
                    self._degraded.append(rec)
                continue
            try:
                twin = self._build_twin(plan)
            except Exception as e:
                import warnings
                warnings.warn(
                    f"restore: plan {rec.get('plan')!r} was quarantined in "
                    f"this snapshot but no interpreter twin could be built "
                    f"({e}); its state is skipped", RuntimeWarning)
                snap = {**snap, "plans": {k: v for k, v in
                                          snap["plans"].items()
                                          if k != rec.get("plan")}}
                continue
            self._swap_plan(plan, twin)
            self._ladder(plan).quarantined = True
            self._degraded.append(rec)
        # partition groups first: they re-create lazily-cloned instance plans
        # that later entries of the snapshot refer to
        items = sorted(snap["plans"].items(),
                       key=lambda kv: not kv[0].startswith("#partition_"))
        for name, st in items:
            if name in self._plan_by_name:
                self._plan_by_name[name].load_state_dict(st)
        for k, st in snap.get("tables", {}).items():
            if k in self.tables:
                self.tables[k].load_state_dict(st)
        self._clock_ms = snap.get("clock")
        if snap.get("seq") is not None:
            self._seq = max(self._seq, int(snap["seq"]))
        # durable watermark of the restored revision (may be None on
        # pre-durability snapshots): recover() replays the WAL suffix
        # strictly past it
        self._wal_restored_watermark = snap.get("wal") or {}

    def persist(self, incremental: bool = False,
                asynchronous: bool = False) -> "Revision":
        """Write a revision to the configured persistence store.
        incremental=True writes table op-log deltas (full state for
        everything else — see persistence.py); asynchronous=True hands the
        store write to a daemon thread (AsyncSnapshotPersistor).

        Returns a structured `persistence.Revision` descriptor — still
        the revision-id string (a str subclass, so existing callers
        keep working) carrying the per-stream durable WAL watermark the
        recovery manager pairs snapshots with."""
        if self.manager is None or self.manager.persistence_store is None:
            raise RuntimeError("no persistence store configured")
        import pickle
        from .persistence import Revision
        store = self.manager.persistence_store
        self.inject("persist.save", self.app.name)
        rev = f"{self.app.name}-{time.time_ns()}"
        if incremental and hasattr(store, "save_incremental"):
            with self._lock:
                self.flush()
                wm = self.wal.watermark() if self.wal is not None else None
                deltas = {k: t.incremental_state()
                          for k, t in self.tables.items()
                          if hasattr(t, "incremental_state")}
                body = {"snapshot": {
                            "strings": self.strings.state(),
                            "plans": {p.name: p.state_dict()
                                      for p in self._plans},
                            "tables": {k: t.state_dict()
                                       for k, t in self.tables.items()
                                       if not hasattr(t, "incremental_state")},
                            "clock": self._clock_ms,
                            "wal": wm},
                        "table_deltas": deltas}
                is_full = all("full" in d for d in deltas.values()) \
                    if deltas else True
            blob = pickle.dumps(body)
            if asynchronous:
                self.persistor().persist(store.save_incremental,
                                          self.app.name, rev, blob, is_full)
            else:
                store.save_incremental(self.app.name, rev, blob, is_full)
            # the store prefixes full/delta revisions; return the LOADABLE id
            desc = Revision(("F-" if is_full else "I-") + rev,
                            watermark=wm, durability=self.durability,
                            incremental=True)
            self._wal_snapshot_barrier(wm, asynchronous)
            self.last_revision_descriptor = desc
            return desc
        snap = self.snapshot()
        wm = snap.get("wal")
        blob = pickle.dumps(snap)
        if asynchronous:
            self.persistor().persist(store.save, self.app.name, rev, blob)
        else:
            store.save(self.app.name, rev, blob)
        desc = Revision(rev, watermark=wm, durability=self.durability)
        self._wal_snapshot_barrier(wm, asynchronous)
        self.last_revision_descriptor = desc
        return desc

    def _wal_snapshot_barrier(self, wm, asynchronous: bool) -> None:
        """After a revision write: fsync the log (the 'batch' policy's
        snapshot barrier), then — for SYNCHRONOUS writes only, where
        the revision is already durable — seal the open segment and
        truncate sealed segments entirely at-or-below the watermark.
        An asynchronous revision is not durable until persistor().wait()
        returns, so its log suffix must survive it."""
        if self.wal is None or wm is None:
            return
        store = self.manager.persistence_store if self.manager else None
        # truncation hands the watermark's frames over to the snapshot,
        # so the snapshot must outlive a crash: an in-memory store's
        # revisions die with the process — deleting disk segments
        # behind one would lose fsync-ACK'd frames for good
        store_durable = bool(getattr(store, "durable",
                                     getattr(store, "dir", None)))
        try:
            self.wal.barrier()
            if not asynchronous and store_durable:
                self.wal.rotate()
                self.wal.truncate(wm)
        except Exception as e:
            # housekeeping must not fail a SUCCESSFUL snapshot: kept
            # segments are merely redundant (recovery skips them via
            # the watermark), and the pre-watermark log tail the
            # barrier could not sync is superseded by the snapshot —
            # warn + carry on, the next barrier retries
            import warnings
            warnings.warn(
                f"WAL snapshot barrier incomplete "
                f"({type(e).__name__}: {e}); sealed segments kept, "
                f"next snapshot retries", RuntimeWarning)

    def persistor(self):
        """The async snapshot persistor: .wait() joins outstanding
        writes, .errors lists write failures (a rev id returned by
        persist(asynchronous=True) is not durable until wait() returns
        with no errors)."""
        if getattr(self, "_async_persistor", None) is None:
            from .persistence import AsyncSnapshotPersistor
            self._async_persistor = AsyncSnapshotPersistor()
        return self._async_persistor

    def persist_every(self, interval_s: float, incremental: bool = False):
        """Periodic persistence; returns a handle with .stop()."""
        from .persistence import PeriodicPersistence
        return PeriodicPersistence(self, interval_s, incremental)

    def _apply_incremental_blob(self, body: dict) -> None:
        snap = body["snapshot"]
        self.restore({**snap, "tables": dict(snap.get("tables", {}))})
        for k, delta in body.get("table_deltas", {}).items():
            if k in self.tables:
                self.tables[k].apply_incremental(delta)

    def restore_revision(self, rev: str) -> None:
        import pickle
        data = self.manager.persistence_store.load(self.app.name, rev)
        body = pickle.loads(data)
        if isinstance(body, dict) and "table_deltas" in body:
            self._apply_incremental_blob(body)   # incremental-format revision
        else:
            self.restore(body)
        self.restored_revision = rev

    def restore_last_state(self) -> None:
        import pickle
        store = self.manager.persistence_store
        chain = store.restore_chain(self.app.name) \
            if hasattr(store, "restore_chain") else None
        candidates = None
        if chain is not None:
            # prefer whichever is NEWER: the incremental chain or a plain
            # full snapshot written later in the same store (the chain is
            # already corruption-filtered — restore_chain skips
            # unpicklable blobs and falls back to an earlier full)
            from .persistence import _rev_time
            base, deltas, chain_time = chain
            plain = [r for r in getattr(store, "revisions")(self.app.name)
                     if not r.startswith(("F-", "I-"))]
            if not plain or _rev_time(plain[-1]) < chain_time:
                self._apply_incremental_blob(pickle.loads(base))
                for d in deltas:
                    self._apply_incremental_blob(pickle.loads(d))
                return
            candidates = plain
        if candidates is None:
            if hasattr(store, "revisions"):
                # an 'I-' delta is never standalone-restorable (its table
                # op-logs assume the base full's state) — the walk-back
                # considers only plain and 'F-' full revisions
                candidates = [r for r in store.revisions(self.app.name)
                              if not r.startswith("I-")]
            else:
                rev = store.last_revision(self.app.name)
                candidates = [rev] if rev is not None else []
        # a corrupt/truncated newest revision must not brick recovery:
        # walk back to the newest LOADABLE revision, counting skips
        for rev in reversed(candidates):
            try:
                self.restore_revision(rev)
                return
            except (pickle.PickleError, EOFError, ValueError) as e:
                import warnings
                self.restore_skipped = getattr(self, "restore_skipped", 0) + 1
                warnings.warn(
                    f"persistence: revision {rev!r} is corrupt "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"previous revision", RuntimeWarning)

    # -- durability: WAL + exactly-once crash recovery -----------------------

    def _wal_directory(self) -> Optional[str]:
        """Resolve the WAL directory: the @app:durability `dir=`
        element, else under a file-backed persistence store, else
        $SIDDHI_WAL_DIR — None when nowhere durable exists."""
        import os
        if self._wal_dir_opt:
            return self._wal_dir_opt
        safe = self.app.name.replace(os.sep, "_") or "_app"
        store = self.manager.persistence_store if self.manager else None
        base = getattr(store, "dir", None)
        if base:
            return os.path.join(base, safe, "wal")
        env = os.environ.get("SIDDHI_WAL_DIR")
        if env:
            return os.path.join(env, safe)
        return None

    def _open_wal(self):
        """Open (or create) the app's write-ahead log.  Resolution
        failure disables durability LOUDLY (warning + a reason in the
        statistics()/explain() durability block) — never silently."""
        if self.durability == "off" or self.wal is not None:
            return self.wal
        d = self._wal_directory()
        if d is None:
            import warnings
            self._wal_disabled_reason = (
                "no WAL directory: configure a file persistence store, "
                "@app:durability(dir='...'), or $SIDDHI_WAL_DIR")
            warnings.warn(
                f"@app:durability({self.durability!r}) on "
                f"{self.app.name!r} is DISABLED — "
                f"{self._wal_disabled_reason}", RuntimeWarning)
            return None
        from .wal import WriteAheadLog
        tr = self.tracing
        self.wal = WriteAheadLog(d, policy=self.durability,
                                 segment_bytes=self._wal_segment_bytes,
                                 inject=self.inject,
                                 armed=lambda:
                                 self.fault_injector is not None,
                                 on_stall=None if tr is None else
                                 (lambda dt: tr.trigger(
                                     "wal_stall",
                                     f"durability barrier took "
                                     f"{dt * 1e3:.1f}ms")))
        # seq continuity past what the disk scan can see: truncation
        # behind a snapshot barrier may have emptied the log, so floor
        # the counters with the restored watermark (crash recovery) and
        # with the previous generation's counters (shutdown/start cycle
        # in one process) — new frames must number PAST everything a
        # snapshot already claims, or the next recovery skips them
        self.wal.floor_seqs(getattr(self, "_wal_restored_watermark",
                                    None))
        prev = getattr(self, "_wal_closed", None)
        if prev is not None:
            self.wal.floor_seqs(prev.seqs)
        return self.wal

    def durability_report(self) -> dict:
        """The ONE durability observability block, shared verbatim by
        `statistics()["durability"]` and `rt.explain()["durability"]`:
        sync policy, whether the log is LIVE (the silently-lost alert
        signal — after shutdown the closed generation's counters still
        report but `enabled` reads False), WAL gauges, the disabled
        reason when resolution failed, and the last recovery report."""
        d = {"policy": self.durability}
        if self.durability == "off":
            return d
        live = self.wal
        wal = live or getattr(self, "_wal_closed", None)
        d["enabled"] = live is not None
        if wal is not None:
            d["wal_dir"] = wal.dir
            d.update(wal.metrics())
        else:
            reason = getattr(self, "_wal_disabled_reason", None)
            if reason:
                d["reason"] = reason
        if self._wal_recovery is not None:
            d["recovery"] = dict(self._wal_recovery)
        if getattr(self, "_promote_report", None) is not None:
            d["promotion"] = dict(self._promote_report)
        return d

    def recover(self) -> dict:
        """Crash/redeploy recovery, exactly-once: restore the newest
        loadable snapshot revision (when a persistence store is
        configured), open the WAL — healing any torn tail back to the
        last valid record — and replay its suffix, skipping frames
        at-or-below the restored per-stream watermark.  Zero duplicates
        (the watermark skip), zero loss (every durable frame past it
        re-feeds; a frame that fails to feed captures whole into the
        ErrorStore).  Returns — and keeps, for statistics()/explain() —
        a recovery report.  Idempotent: once the log is open (a prior
        recover(), or a disabled-loudly attempt) the call returns the
        previous report without re-replaying — a second replay of an
        open log would double-apply this run's own appends."""
        from .batch import rows_of_columns
        if self.wal is not None or self._wal_recovery is not None:
            return dict(self._wal_recovery or {})
        t0 = time.perf_counter()
        report = {"restored_revision": None, "watermark": {},
                  "replayed_frames": 0, "replayed_events": 0,
                  "skipped_frames": 0, "failed_frames": 0,
                  "corrupt_skipped": 0, "recovery_s": 0.0}
        store = self.manager.persistence_store if self.manager else None
        already = getattr(self, "_wal_restored_watermark", None)
        if already is not None:
            # the caller restored a revision of their choosing (manual
            # restore_revision/restore_last_state): honor it — replay
            # past ITS watermark instead of re-restoring the newest
            report["restored_revision"] = getattr(
                self, "restored_revision", None)
            report["watermark"] = dict(already)
        elif store is not None and store.last_revision(self.app.name) \
                is not None:
            self._wal_restored_watermark = None
            self.restore_last_state()
            wm = getattr(self, "_wal_restored_watermark", None)
            if wm is not None:          # at least one revision applied
                report["restored_revision"] = getattr(
                    self, "restored_revision",
                    str(store.last_revision(self.app.name)))
                report["watermark"] = dict(wm)
        wal = self._open_wal()
        if wal is not None:
            wm = report["watermark"]
            self._wal_replaying = True
            try:
                def _capture(stream, schema, ts, cols, err):
                    # a durable frame must never vanish: capture whole
                    # (schema drift / dropped stream on redeploy — the
                    # record may not even decode against the NEW
                    # schema, so fall back to its own column order)
                    report["failed_frames"] += 1
                    try:
                        rows = rows_of_columns(schema, ts, cols,
                                               self.strings)
                    except Exception:
                        names = sorted(cols)
                        arrs = [np.asarray(cols[n]).tolist()
                                for n in names]
                        rows = list(zip(
                            np.asarray(ts).tolist(),
                            (tuple(r) for r in zip(*arrs))))
                    self.error_store.add(stream, "wal.replay", err,
                                         self.now_ms(), events=rows)

                for stream, seq, ts, cols in wal.replay():
                    if seq <= wm.get(stream, 0):
                        report["skipped_frames"] += 1
                        continue
                    schema = self.schemas.get(stream)
                    if schema is None:
                        _capture(stream, None, ts, cols,
                                 f"stream {stream!r} no longer exists "
                                 f"in the redeployed app")
                        continue
                    try:
                        self.send_columnar(stream, cols, ts)
                    except Exception as e:
                        _capture(stream, schema, ts, cols, e)
                        continue
                    report["replayed_frames"] += 1
                    report["replayed_events"] += int(
                        np.asarray(ts).shape[0])
            finally:
                self._wal_replaying = False
            self.flush()
            report["corrupt_skipped"] = wal.corrupt_skipped
        report["recovery_s"] = round(time.perf_counter() - t0, 6)
        self._wal_recovery = report
        return report


class InMemoryPersistenceStore:
    """reference: core:util/persistence/InMemoryPersistenceStore.java"""

    # revisions die with the process: the WAL snapshot barrier must
    # NOT truncate segments behind one
    durable = False

    def __init__(self):
        self._data: dict = defaultdict(dict)
        self._order: dict = defaultdict(list)

    def save(self, app: str, revision: str, blob: bytes) -> None:
        self._data[app][revision] = blob
        self._order[app].append(revision)

    def load(self, app: str, revision: str) -> bytes:
        return self._data[app][revision]

    def last_revision(self, app: str) -> Optional[str]:
        revs = self._order[app]
        return revs[-1] if revs else None


class SiddhiManager:
    """reference: core:SiddhiManager.java:45

    `isolated_broker=True` scopes inMemory source/sink topics to this
    manager (its `.broker`); the default matches the reference's
    process-global InMemoryBroker (same-named topics cross-deliver
    between managers — use isolation when embedding several apps).

    `allow_scripts=False` rejects apps containing `define function f[python]`
    at build time.  Script UDFs execute with full interpreter privileges
    (same trust model as the reference's Script.java engines running inside
    the JVM): app text is TRUSTED input.  Disable scripts when deploying
    apps from untrusted authors (e.g. via the REST service)."""

    def __init__(self, isolated_broker: bool = False,
                 allow_scripts: bool = True):
        self.allow_scripts = allow_scripts
        # persistent XLA kernel cache (backend-keyed dir; best-effort)
        from .. import _enable_kernel_cache
        _enable_kernel_cache()
        # entry-point extension discovery (once per process; reference:
        # SiddhiExtensionLoader scans the classpath at manager creation)
        from ..extension import discover_extensions
        discover_extensions()
        self.persistence_store = None
        self.config_manager = None      # ConfigManager SPI (core/config.py)
        self._runtimes: dict = {}
        self.broker = None
        if isolated_broker:
            from .io import Broker
            self.broker = Broker()
        # HA interception SPI (reference: SourceHandlerManager /
        # SinkHandlerManager registered on SiddhiManager): factories
        # producing a handler per source/sink at build time
        self.source_handler_factory = None
        self.sink_handler_factory = None

    def set_source_handler_factory(self, factory) -> None:
        self.source_handler_factory = factory

    def set_sink_handler_factory(self, factory) -> None:
        self.sink_handler_factory = factory

    def create_app_runtime(self, app: Union[str, qast.SiddhiApp]) -> SiddhiAppRuntime:
        parse_s = 0.0
        if isinstance(app, str):
            t0 = time.perf_counter()
            app = parse(app)
            parse_s = time.perf_counter() - t0
        rt = SiddhiAppRuntime(app, self)
        if parse_s:
            # measured before the runtime (and its stats manager) existed
            rt.stats.note_stage("parse", parse_s)
        self._runtimes[rt.app.name] = rt
        return rt

    createSiddhiAppRuntime = create_app_runtime

    def set_persistence_store(self, store) -> None:
        self.persistence_store = store

    def set_config_manager(self, cm) -> None:
        self.config_manager = cm

    def persist(self) -> None:
        for rt in self._runtimes.values():
            rt.persist()

    def restore_last_state(self) -> None:
        for rt in self._runtimes.values():
            rt.restore_last_state()

    def validate_app(self, app: Union[str, qast.SiddhiApp]) -> None:
        """Compile-check an app without registering a runtime."""
        if isinstance(app, str):
            app = parse(app)
        SiddhiAppRuntime(app, self).shutdown()

    def shutdown(self) -> None:
        for rt in list(self._runtimes.values()):
            rt.shutdown()
        self._runtimes.clear()
