"""App builder: walks the AST's execution elements and instantiates plans.

Analog of the reference's SiddhiAppParser.parse loop (reference:
core:util/parser/SiddhiAppParser.java:225-254) + QueryParser dispatch +
DefinitionParserHelper table/trigger instantiation
(core:util/parser/helper/DefinitionParserHelper.java:160).
Kept separate from runtime.py so the runtime facade stays small.
"""
from __future__ import annotations

from ..query import ast
from .planner import (FilterProjectPlan, PlanError, output_target_of,
                      selector_has_aggregators)


def build_app(rt) -> None:
    """Populate rt (SiddhiAppRuntime) with tables and plans from rt.app."""
    from ..interp.expr import (ExprError, compile_script_function, udf_scope)

    # script UDFs compile first: queries below may call them (reference:
    # SiddhiAppParser defines scripts before queries, Script.java:27).
    # Unsupported languages fail HERE, loudly — not at first use.
    rt.udfs = {}
    mgr = getattr(rt, "manager", None)
    if (rt.app.function_definitions
            and mgr is not None and not getattr(mgr, "allow_scripts", True)):
        raise PlanError(
            "script functions are disabled on this SiddhiManager "
            "(allow_scripts=False): app text is untrusted input here and "
            "[python] script bodies execute with full interpreter privileges")
    for fid, fd in rt.app.function_definitions.items():
        try:
            rt.udfs[fid.lower()] = (compile_script_function(fd),
                                    fd.return_type)
        except ExprError as e:
            raise PlanError(str(e)) from None
    with udf_scope(rt.udfs):
        _build_app_scoped(rt)


def _build_app_scoped(rt) -> None:
    from .table import InMemoryTable, TableError

    # `@app:patternFamily` names a pattern-kernel execution family
    # (seq | chunk | scan | dfa | auto — docs/PERFORMANCE.md "Plan
    # families").  Validate the NAME once here, loudly, so a typo is a
    # PlanError on EVERY path (scoped, partitioned, and fused pattern
    # plans) and never silently falls back to auto selection.  Whether
    # the family is *eligible* for a given chain is decided later by
    # each plan's eligibility analysis (ineligible -> warn + sound
    # fallback).
    from .autotune import AutotuneError, pattern_family_for
    try:
        pattern_family_for(rt)
    except AutotuneError as e:
        raise PlanError(str(e)) from None

    app = rt.app
    for tid, td in app.table_definitions.items():
        if tid in rt.schemas:
            raise PlanError(f"{tid!r} defined as both stream and table")
        try:
            from .record_table import build_record_table
            bridge = build_record_table(td, rt.strings)
            rt.tables[tid] = bridge if bridge is not None \
                else InMemoryTable(td, rt.strings)
        except TableError as e:
            raise PlanError(str(e)) from None
        except PlanError:
            raise
        except Exception as e:      # store connect failures etc.
            raise PlanError(f"table {tid!r}: {e}") from e

    from ..interp.named_window import NamedWindowRuntime
    from .schema import StreamSchema
    for wid, wd in app.window_definitions.items():
        if wid in rt.schemas or wid in rt.tables:
            raise PlanError(f"{wid!r} defined as both window and stream/table")
        nw = NamedWindowRuntime(rt, wd)
        rt.named_windows[wid] = nw
        rt.schemas[wid] = nw.schema
        rt._register_plan(nw)

    from .trigger import TriggerRuntime
    for tid, td in app.trigger_definitions.items():
        rt._register_plan(TriggerRuntime(rt, td))

    from .aggregation import AggregationRuntime
    for aid, ad in app.aggregation_definitions.items():
        if aid in rt.schemas or aid in rt.tables:
            raise PlanError(f"{aid!r} defined as both aggregation and "
                            f"stream/table/window")
        agg = AggregationRuntime(rt, ad)
        rt.aggregations[aid] = agg
        rt._register_plan(agg)

    # multi-query device batching pre-pass: >= MIN_GROUP structurally
    # identical pattern queries fuse into ONE batched kernel whose lanes
    # are the query instances (BASELINE config 5's "1k concurrent queries")
    fused: dict = {}
    if getattr(rt, "device_patterns", "auto") != "never":
        from .multi_query import MIN_GROUP, query_signature
        groups: dict = {}
        for i, elem in enumerate(app.execution_elements):
            if isinstance(elem, ast.Query):
                sig = query_signature(elem)
                if sig is not None:
                    groups.setdefault(sig, []).append(i)
        from .autotune import fused_lane_pack_for
        from .multi_query import plan_query_group
        from .nfa_device import DeviceNFAUnsupported
        for sig, idxs in groups.items():
            if len(idxs) < MIN_GROUP:
                # a LONE query was never a fusion candidate — recording
                # "group of 1 too small" for every pattern app is noise
                if len(idxs) > 1:
                    for i in idxs:
                        q = app.execution_elements[i]
                        rt.placement.demote(
                            q.name(f"query_{i}"), "D-FUSED",
                            f"structurally-identical group too small to "
                            f"fuse ({len(idxs)} < {MIN_GROUP}); planned "
                            f"individually",
                            alternative="fused-lanes")
                continue
            # fused-lane packing (@app:fusedLanes / tuning cache): cap the
            # lane count per fused kernel — a group larger than the pack
            # splits into several kernels (0 = unbounded, one kernel)
            pack = fused_lane_pack_for(rt, sig)
            if pack and pack >= MIN_GROUP:
                slices = [idxs[j:j + pack]
                          for j in range(0, len(idxs), pack)]
                if len(slices) > 1 and len(slices[-1]) < MIN_GROUP:
                    slices[-2].extend(slices.pop())   # tail too small to
            else:                                     # fuse on its own
                slices = [idxs]
            for sub in slices:
                qs = [app.execution_elements[i] for i in sub]
                names = [q.name(f"query_{i}") for q, i in zip(qs, sub)]
                try:
                    plan = plan_query_group(rt, qs, names)
                except DeviceNFAUnsupported as e:
                    for nm in names:
                        rt.placement.demote(
                            nm, "D-FUSED",
                            "fused multi-query lane kernel unavailable "
                            "for this group; queries planned individually",
                            cause=e, alternative="fused-lanes")
                    break
                # the tuning cache keys fused plans by the GROUP shape
                # signature (autotune.plan_signature) — the fused query
                # AST never flows through attach_table_writer
                plan._group_sig = sig
                rt._register_plan(plan)
                for i in sub:
                    fused[i] = plan

    for i, elem in enumerate(app.execution_elements):
        if i in fused:
            continue
        if isinstance(elem, ast.Query):
            plan = plan_query(rt, elem, default_name=f"query_{i}")
            rt._register_plan(plan)
        elif isinstance(elem, ast.Partition):
            plan_partition(rt, elem, index=i)
        else:
            raise PlanError(f"unknown execution element {type(elem).__name__}")


def attach_table_writer(rt, plan, q: ast.Query, name: str):
    """If the query's target is a table, build the matching write-side
    callback (reference: OutputParser.java:117-220 chooses the
    Insert/Update/Delete/UpdateOrInsert table callback)."""
    from .table import TableError, make_table_writer

    target = plan.output_target
    if isinstance(q.output, (ast.UpdateTable, ast.DeleteFrom,
                             ast.UpdateOrInsertTable)):
        if target not in rt.tables:
            raise PlanError(
                f"query {name!r}: {type(q.output).__name__} target "
                f"{target!r} is not a defined table")
    if target is not None and target in rt.tables:
        try:
            plan.table_writer = make_table_writer(
                q.output, rt.tables[target], plan.out_schema)
        except TableError as e:
            raise PlanError(f"query {name!r}: {e}") from None
    # keep the (normalized) source AST: the fault layer rebuilds the plan
    # on the interpreter path from it when a device plan is quarantined
    # (runtime._build_twin)
    plan._q_ast = q
    return plan


def _normalize_fault_inputs(node, rt, name: str):
    """Rewrite every `!S` input reference (single streams, join sides,
    pattern state elements) to the registered "!S" fault schema."""
    import dataclasses
    if isinstance(node, ast.SingleInputStream):
        if not node.is_fault:
            return node
        fid = "!" + node.stream_id
        if fid not in rt.schemas:
            raise PlanError(f"query {name!r}: stream {node.stream_id!r} has "
                            f"no fault stream; annotate it with "
                            f"@OnError(action='stream')")
        return dataclasses.replace(node, stream_id=fid, is_fault=False)
    if isinstance(node, ast.JoinInputStream):
        return dataclasses.replace(
            node, left=_normalize_fault_inputs(node.left, rt, name),
            right=_normalize_fault_inputs(node.right, rt, name))
    if isinstance(node, ast.StateInputStream):
        return dataclasses.replace(
            node, state=_normalize_fault_inputs(node.state, rt, name))
    if isinstance(node, (ast.StreamStateElement, ast.AbsentStreamStateElement)):
        return dataclasses.replace(
            node, stream=_normalize_fault_inputs(node.stream, rt, name))
    if isinstance(node, ast.CountStateElement):
        return dataclasses.replace(
            node, stream=_normalize_fault_inputs(node.stream, rt, name))
    if isinstance(node, ast.LogicalStateElement):
        return dataclasses.replace(
            node, left=_normalize_fault_inputs(node.left, rt, name),
            right=_normalize_fault_inputs(node.right, rt, name))
    if isinstance(node, ast.NextStateElement):
        return dataclasses.replace(
            node, state=_normalize_fault_inputs(node.state, rt, name),
            next=_normalize_fault_inputs(node.next, rt, name))
    if isinstance(node, ast.EveryStateElement):
        return dataclasses.replace(
            node, state=_normalize_fault_inputs(node.state, rt, name))
    return node


def plan_query(rt, q: ast.Query, default_name: str):
    """Compile one query into a plan.  Re-enters udf_scope: partition
    groups call this lazily (first event per key), long after build_app's
    scope has exited — script functions must still resolve."""
    from ..interp.expr import udf_scope
    with udf_scope(getattr(rt, "udfs", None)):
        return _plan_query_scoped(rt, q, default_name)


def _plan_query_scoped(rt, q: ast.Query, default_name: str):
    import dataclasses
    name = q.name(default_name)
    target = output_target_of(q)
    inp = _normalize_fault_inputs(q.input, rt, name)
    if inp is not q.input:
        q = dataclasses.replace(q, input=inp)

    if isinstance(inp, ast.SingleInputStream):
        if inp.stream_id in rt.tables:
            raise PlanError(
                f"query {name!r}: cannot stream from table "
                f"{inp.stream_id!r}; use a join or an on-demand (store) query")
        if inp.stream_id not in rt.schemas:
            raise PlanError(f"query {name!r}: unknown input stream {inp.stream_id!r}")
        schema = rt.schemas[inp.stream_id]
        has_window = inp.window is not None
        has_agg = selector_has_aggregators(q.selector) or q.selector.group_by
        # reading from a named window with expired/all output needs the
        # host path's expired-stream subscription
        nw_needs_host = (inp.stream_id in rt.named_windows
                         and q.output.events_for != ast.OutputEventsFor.CURRENT)
        # TPU windowed-aggregation path (length/time/lengthBatch windows
        # with sum/count/avg/min/max): one fused device step per batch
        dw_mode = rt.device_windows
        if has_window and has_agg and dw_mode != "never":
            from .window_device import DeviceWindowAggPlan, DeviceWindowUnsupported
            try:
                return attach_table_writer(rt, DeviceWindowAggPlan(
                    name, rt, q, inp, target), q, name)
            except DeviceWindowUnsupported as e:
                if dw_mode == "always":
                    raise PlanError(f"query {name!r}: deviceWindows=always "
                                    f"but unsupported: {e}")
                rt.placement.demote(name, "D-WINDOW", str(e), cause=e,
                                    alternative="device-window")
        # TPU fast path: stateless filter/project with device-typed columns
        if (not has_window and not has_agg and q.rate is None and not nw_needs_host
                and rt.device_filters != "never"
                and isinstance(q.output, (ast.InsertInto, ast.ReturnAction))
                and not any(isinstance(h, ast.StreamFunction) for h in inp.handlers)):
            try:
                filters = [f.expr for f in inp.filters]
                from .autotune import pipeline_depth_for
                return attach_table_writer(rt, FilterProjectPlan(
                    name, schema, inp.alias, filters, q.selector, rt.strings,
                    target, q.selector.limit, q.selector.offset,
                    events_for=q.output.events_for,
                    pipeline_depth=pipeline_depth_for(rt, "filter", q)),
                    q, name)
            except PlanError:
                raise
            except Exception as e:
                # host-only functions etc. -> sequential backend.  NOT
                # silent: PR 5 found a whole query class demoted through
                # this exact handler — the cause must reach explain()
                rt.placement.demote(
                    name, "D-FILTER",
                    "device filter/projection lowering failed; host "
                    "interpreter handles this query",
                    cause=e, alternative="device-filter")
        elif not rt.placement.for_query(name):
            # the stateless fast path never applied: account for WHY the
            # query lands on the host (the window branch above recorded
            # its own reason when it was attempted and rejected)
            rule, why = _interp_shape_reasons(rt, q, inp, has_window,
                                              has_agg, nw_needs_host,
                                              dw_mode)
            rt.placement.demote(name, rule, why, alternative="device")
        from ..interp.engine import InterpSingleQueryPlan
        return attach_table_writer(
            rt, InterpSingleQueryPlan(name, rt, q, inp, target), q, name)

    if isinstance(inp, ast.JoinInputStream):
        mode = getattr(rt, "device_joins", "auto")
        if mode != "never":
            from .join_device import DeviceJoinPlan, DeviceJoinUnsupported
            try:
                return attach_table_writer(
                    rt, DeviceJoinPlan(name, rt, q, inp, target), q, name)
            except DeviceJoinUnsupported as e:
                if mode == "always":
                    raise PlanError(
                        f"query {name!r}: @app:deviceJoins('always') but "
                        f"the shape is host-only: {e}")
                rt.placement.demote(name, "D-JOIN", str(e), cause=e,
                                    alternative="device-join")
        else:
            rt.placement.demote(name, "D-POLICY",
                                "@app:deviceJoins('never')",
                                alternative="device-join")
        from ..interp.joins import InterpJoinQueryPlan
        return attach_table_writer(
            rt, InterpJoinQueryPlan(name, rt, q, inp, target), q, name)

    if isinstance(inp, ast.StateInputStream):
        mode = getattr(rt, "device_patterns", "auto")
        if mode == "always":
            from .pattern_plan import DevicePatternPlan
            return attach_table_writer(rt, DevicePatternPlan(
                name, rt, q, inp, target, slots=rt.device_slots), q, name)
        if mode == "prefer":
            from .nfa_device import DeviceNFAUnsupported
            from .pattern_plan import DevicePatternPlan
            try:
                return attach_table_writer(rt, DevicePatternPlan(
                    name, rt, q, inp, target, slots=rt.device_slots), q, name)
            except DeviceNFAUnsupported as e:
                rt.placement.demote(name, "D-PATTERN", str(e), cause=e,
                                    alternative="device-pattern")
        if mode == "auto":
            # P=1 on a remote chip loses to the host matcher; the
            # partition planner routes partitioned patterns here
            rt.placement.demote(
                name, "D-POLICY",
                "devicePatterns='auto': unpartitioned patterns run the "
                "host matcher (a P=1 kernel loses to the host on a "
                "tunneled chip); partition the query to take the device "
                "lane axis, or force @app:devicePatterns('prefer')",
                alternative="device-pattern")
        elif mode == "never":
            rt.placement.demote(name, "D-POLICY",
                                "@app:devicePatterns('never')",
                                alternative="device-pattern")
        from ..interp.engine import InterpPatternQueryPlan
        return attach_table_writer(
            rt, InterpPatternQueryPlan(name, rt, q, inp, target), q, name)

    raise PlanError(f"query {name!r}: input type {type(inp).__name__} not yet supported")


def _interp_shape_reasons(rt, q: ast.Query, inp, has_window: bool,
                          has_agg: bool, nw_needs_host: bool,
                          dw_mode: str) -> tuple:
    """(rule_id, reason) for a single-stream query that reached the host
    interpreter without any device-plan attempt — the placement plane's
    answer to "why is this query not on the device?".  Policy opt-outs
    (annotations/env) report as D-POLICY; everything else is a shape
    gate (D-SHAPE)."""
    reasons, policy = [], []
    if has_window and has_agg and dw_mode == "never":
        policy.append("@app:deviceWindows('never')")
    if has_window and not has_agg:
        reasons.append("window without device-supported aggregation "
                       "(host window operators)")
    if has_agg and not has_window:
        reasons.append("aggregation without a window "
                       "(host running aggregators)")
    if nw_needs_host:
        reasons.append("named-window expired/all output needs the host "
                       "expired-stream subscription")
    if q.rate is not None:
        reasons.append("output rate limiting is host-only")
    if any(isinstance(h, ast.StreamFunction) for h in inp.handlers):
        reasons.append("stream functions are host-only")
    if not isinstance(q.output, (ast.InsertInto, ast.ReturnAction)):
        reasons.append(f"{type(q.output).__name__} table output runs on "
                       f"the host path")
    if (not reasons and not policy and rt.device_filters == "never"):
        policy.append("@app:deviceFilters('never')")
    if reasons:
        return "D-SHAPE", "; ".join(reasons)
    if policy:
        return "D-POLICY", "; ".join(policy)
    return "D-SHAPE", "query shape has no device plan family"


def plan_partition(rt, p: ast.Partition, index: int) -> None:
    from .partition import plan_partition as _pp
    _pp(rt, p, index)
