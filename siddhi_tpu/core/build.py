"""App builder: walks the AST's execution elements and instantiates plans.

Analog of the reference's SiddhiAppParser.parse loop (reference:
core:util/parser/SiddhiAppParser.java:225-254) + QueryParser dispatch.
Kept separate from runtime.py so the runtime facade stays small.
"""
from __future__ import annotations

from ..query import ast
from .planner import (FilterProjectPlan, PlanError, output_target_of,
                      selector_has_aggregators)


def build_app(rt) -> None:
    """Populate rt (SiddhiAppRuntime) with plans from rt.app."""
    app = rt.app
    for i, elem in enumerate(app.execution_elements):
        if isinstance(elem, ast.Query):
            plan = plan_query(rt, elem, default_name=f"query_{i}")
            rt._register_plan(plan)
        elif isinstance(elem, ast.Partition):
            plan_partition(rt, elem, index=i)
        else:
            raise PlanError(f"unknown execution element {type(elem).__name__}")


def plan_query(rt, q: ast.Query, default_name: str):
    name = q.name(default_name)
    target = output_target_of(q)
    inp = q.input

    if isinstance(inp, ast.SingleInputStream):
        if inp.stream_id not in rt.schemas:
            raise PlanError(f"query {name!r}: unknown input stream {inp.stream_id!r}")
        if isinstance(q.output, (ast.UpdateTable, ast.DeleteFrom,
                                 ast.UpdateOrInsertTable)) \
                and target not in rt.tables:
            raise PlanError(f"query {name!r}: unknown table {target!r}")
        schema = rt.schemas[inp.stream_id]
        has_window = inp.window is not None
        has_agg = selector_has_aggregators(q.selector) or q.selector.group_by
        # TPU fast path: stateless filter/project with device-typed columns
        if (not has_window and not has_agg and q.rate is None
                and isinstance(q.output, (ast.InsertInto, ast.ReturnAction))
                and not any(isinstance(h, ast.StreamFunction) for h in inp.handlers)):
            try:
                filters = [f.expr for f in inp.filters]
                return FilterProjectPlan(
                    name, schema, inp.alias, filters, q.selector, rt.strings,
                    target, q.selector.limit, q.selector.offset,
                    events_for=q.output.events_for)
            except Exception:
                pass   # host-only functions etc. -> sequential backend
        from ..interp.engine import InterpSingleQueryPlan
        return InterpSingleQueryPlan(name, rt, q, inp, target)

    if isinstance(inp, ast.JoinInputStream):
        if inp.per is not None or inp.within is not None:
            raise PlanError(f"query {name!r}: aggregation joins "
                            f"(within/per) not yet supported")
        from ..interp.joins import InterpJoinQueryPlan
        return InterpJoinQueryPlan(name, rt, q, inp, target)

    if isinstance(inp, ast.StateInputStream):
        mode = getattr(rt, "device_patterns", "auto")
        if mode == "always":
            from .pattern_plan import DevicePatternPlan
            return DevicePatternPlan(name, rt, q, inp, target,
                                     slots=rt.device_slots)
        if mode == "auto":
            pass   # P=1 on a remote chip loses to the host matcher; the
                   # partition planner routes partitioned patterns here
        from ..interp.engine import InterpPatternQueryPlan
        return InterpPatternQueryPlan(name, rt, q, inp, target)

    raise PlanError(f"query {name!r}: input type {type(inp).__name__} not yet supported")


def plan_partition(rt, p: ast.Partition, index: int) -> None:
    from .partition import plan_partition as _pp
    _pp(rt, p, index)
