"""In-memory tables: columnar storage + index-aware condition planner.

The TPU framework's analog of the reference table tier (reference:
core:table/InMemoryTable.java:225, core:table/holder/IndexEventHolder.java:59-120,
core:util/parser/CollectionExpressionParser.java:843,
core:util/collection/operator/IndexOperator.java).

Design differences from the reference, by design:
  * storage is struct-of-arrays (one numpy array per attribute, capacity-
    doubled, tombstoned `valid` mask) instead of pooled row events in a
    HashMap — scans are vectorized numpy compares over whole columns;
  * the "compiled condition" splits into (a) primary-key O(1) dict seek,
    (b) secondary-index equality seeks (dict value -> row-id set), and
    (c) a vectorized residual mask evaluated only over candidate rows —
    the same seek-vs-scan planning CollectionExpressionParser does with
    executor objects, done here at compile time over columns;
  * strings live as int32 dictionary codes (equality = int compare;
    ordering decodes through the shared StringTable).

Duplicate primary keys are dropped with a warning, matching
IndexEventHolder.add (reference: IndexEventHolder.java:177-186).
"""
from __future__ import annotations

import warnings
from typing import Callable, Optional

import numpy as np

from ..query import ast
from ..query.ast import AttrType, CompareOp
from .schema import StreamSchema, StringTable, TIMESTAMP_DTYPE, dtype_of


class TableError(Exception):
    pass


# ---------------------------------------------------------------------------
# storage
# ---------------------------------------------------------------------------

class InMemoryTable:
    """Columnar in-memory table with primary-key map + secondary indexes."""

    def __init__(self, defn: ast.TableDefinition, strings: StringTable):
        self.defn = defn
        self.id = defn.id
        self.schema = StreamSchema(defn.id, tuple(defn.attributes))
        self.strings = strings
        self.pk_attrs: tuple[str, ...] = tuple(defn.primary_keys())
        self.index_attrs: tuple[str, ...] = tuple(
            a for a in defn.indexes() if a not in self.pk_attrs)
        for a in (*self.pk_attrs, *self.index_attrs):
            if a not in self.schema.types:
                raise TableError(f"table {self.id!r}: indexed attribute {a!r} "
                                 f"not in schema {self.schema.names}")
        self._cap = 64
        self._cols: dict[str, np.ndarray] = {
            a.name: np.zeros(self._cap, dtype=dtype_of(a.type))
            for a in defn.attributes}
        self._nulls: dict[str, np.ndarray] = {
            a.name: np.zeros(self._cap, dtype=bool) for a in defn.attributes}
        self._ts = np.zeros(self._cap, dtype=TIMESTAMP_DTYPE)
        self._valid = np.zeros(self._cap, dtype=bool)
        self._n = 0                  # high-water mark (includes tombstones)
        self._live = 0               # live row count
        self._pk: dict = {}          # pk value tuple/scalar -> row idx
        self._index: dict[str, dict] = {a: {} for a in self.index_attrs}
        # incremental-snapshot op-log (reference IndexEventHolder
        # operationChangeLog): content-addressed ops since the last full
        # snapshot; beyond ~2.1x the live size a full snapshot is cheaper
        self._oplog: list = []
        self._oplog_active = False

    # -- geometry ------------------------------------------------------------

    def __len__(self) -> int:
        return self._live

    def live_idx(self) -> np.ndarray:
        return np.flatnonzero(self._valid[:self._n])

    def _ensure(self, extra: int) -> None:
        need = self._n + extra
        if need <= self._cap:
            return
        while self._cap < need:
            self._cap *= 2
        for d in (self._cols, self._nulls):
            for k, v in d.items():
                g = np.zeros(self._cap, dtype=v.dtype)
                g[:self._n] = v[:self._n]
                d[k] = g
        for nm in ("_ts", "_valid"):
            v = getattr(self, nm)
            g = np.zeros(self._cap, dtype=v.dtype)
            g[:self._n] = v[:self._n]
            setattr(self, nm, g)

    def _maybe_compact(self) -> None:
        if self._n > 256 and self._live < self._n // 2:
            keep = self.live_idx()
            m = len(keep)
            for d in (self._cols, self._nulls):
                for k in d:
                    d[k][:m] = d[k][keep]
            self._ts[:m] = self._ts[keep]
            self._valid[:m] = True
            self._valid[m:self._n] = False
            self._n = m
            self._rebuild_indexes()

    def _rebuild_indexes(self) -> None:
        self._pk = {}
        self._index = {a: {} for a in self.index_attrs}
        for i in self.live_idx():
            i = int(i)
            if self.pk_attrs:
                self._pk[self._pk_key(i)] = i
            for a in self.index_attrs:
                self._index[a].setdefault(self._key_val(a, i), set()).add(i)

    # -- keys ----------------------------------------------------------------

    def _key_val(self, attr: str, row: int):
        if self._nulls[attr][row]:
            return None
        return self._cols[attr][row].item()

    def _pk_key(self, row: int):
        if len(self.pk_attrs) == 1:
            return self._key_val(self.pk_attrs[0], row)
        return tuple(self._key_val(a, row) for a in self.pk_attrs)

    # -- mutation ------------------------------------------------------------

    def _log(self, op) -> None:
        if self._oplog_active:
            self._oplog.append(op)

    def insert_batch(self, batch) -> None:
        """Append a batch of rows (logging for incremental snapshots
        only when active — the payload decode isn't free)."""
        if self._oplog_active:
            self._log(("ins", [int(t) for t in batch.timestamps],
                       batch.rows(self.strings)))
        self._insert_batch_impl(batch)

    def _insert_batch_impl(self, batch) -> None:
        """Append an EventBatch (same positional types as the table schema).
        Column names may differ; mapping is positional like the reference's
        stream->table event conversion."""
        if batch.n == 0:
            return
        self._ensure(batch.n)
        s = self._n
        src_attrs = batch.schema.attributes
        bn = batch.nulls or {}
        for src, dst in zip(src_attrs, self.defn.attributes):
            self._cols[dst.name][s:s + batch.n] = batch.columns[src.name]
            m = bn.get(src.name)
            self._nulls[dst.name][s:s + batch.n] = m if m is not None else False
        self._ts[s:s + batch.n] = batch.timestamps
        self._n += batch.n
        for i in range(s, s + batch.n):
            self._add_row_to_indexes(i)

    def _add_row_to_indexes(self, i: int) -> None:
        if self.pk_attrs:
            key = self._pk_key(i)
            if key in self._pk:
                warnings.warn(
                    f"table {self.id!r}: dropping row with duplicate primary "
                    f"key {key!r} (reference: IndexEventHolder.add)",
                    RuntimeWarning, stacklevel=2)
                self._valid[i] = False
                return
            self._pk[key] = i
        self._valid[i] = True
        self._live += 1
        for a in self.index_attrs:
            self._index[a].setdefault(self._key_val(a, i), set()).add(i)

    def _remove_row_from_indexes(self, i: int) -> None:
        if self.pk_attrs:
            self._pk.pop(self._pk_key(i), None)
        for a in self.index_attrs:
            s = self._index[a].get(self._key_val(a, i))
            if s is not None:
                s.discard(i)

    def delete_rows(self, idx) -> int:
        if self._oplog_active and len(idx):
            self._log(("del", [self.row_tuple(int(i)) for i in idx]))
        return self._delete_rows_impl(idx)

    def _delete_rows_impl(self, idx) -> int:
        cnt = 0
        for i in np.atleast_1d(np.asarray(idx, dtype=np.int64)):
            i = int(i)
            if self._valid[i]:
                self._remove_row_from_indexes(i)
                self._valid[i] = False
                self._live -= 1
                cnt += 1
        self._maybe_compact()
        return cnt

    def set_row_value(self, row: int, attr: str, value) -> None:
        if self._oplog_active:
            self._log(("set", self.row_tuple(int(row)), attr, value))
        self._set_row_value_impl(row, attr, value)

    def _set_row_value_impl(self, row: int, attr: str, value) -> None:
        """Write one attribute of a live row, maintaining indexes."""
        t = self.schema.type_of(attr)
        reindex = attr in self.pk_attrs or attr in self.index_attrs
        if reindex:
            self._remove_row_from_indexes(row)
        if value is None:
            self._nulls[attr][row] = True
            self._cols[attr][row] = 0
        else:
            self._nulls[attr][row] = False
            if t == AttrType.STRING:
                value = self.strings.encode(value)
            self._cols[attr][row] = value
        if reindex:
            if self.pk_attrs:
                key = self._pk_key(row)
                other = self._pk.get(key)
                if other is not None and other != row:
                    warnings.warn(
                        f"table {self.id!r}: update collides with existing "
                        f"primary key {key!r}; dropping updated row",
                        RuntimeWarning, stacklevel=2)
                    self._valid[row] = False
                    self._live -= 1
                    for a in self.index_attrs:
                        self._index[a].setdefault(
                            self._key_val(a, row), set()).discard(row)
                    return
                self._pk[key] = row
            for a in self.index_attrs:
                self._index[a].setdefault(self._key_val(a, row), set()).add(row)

    # -- reads ---------------------------------------------------------------

    def row_env(self, row: int, refs: tuple[str, ...] = ()) -> dict:
        """Decode one live row into a host-interp env fragment."""
        env = {}
        for a in self.defn.attributes:
            if self._nulls[a.name][row]:
                v = None
            else:
                v = self._cols[a.name][row].item()
                if a.type == AttrType.STRING:
                    v = self.strings.decode(int(v))
            for r in refs:
                env[f"{r}.{a.name}"] = v
        return env

    def row_ts(self, row: int) -> int:
        return int(self._ts[row])

    def row_tuple(self, row: int) -> tuple:
        out = []
        for a in self.defn.attributes:
            if self._nulls[a.name][row]:
                out.append(None)
                continue
            v = self._cols[a.name][row].item()
            if a.type == AttrType.STRING:
                v = self.strings.decode(int(v))
            elif a.type == AttrType.BOOL:
                v = bool(v)
            out.append(v)
        return tuple(out)

    def all_rows(self) -> list[tuple]:
        return [self.row_tuple(int(i)) for i in self.live_idx()]

    # -- snapshot (reference: InMemoryTable implements Snapshotable) ---------

    def incremental_state(self) -> dict:
        """Op-log delta since the last full/incremental snapshot; switches
        to a full snapshot past the 2.1x threshold (reference
        IndexEventHolder.java:74-76).  Starts op-logging on first call."""
        if not self._oplog_active:
            self._oplog_active = True
            self._oplog = []
            return {"full": self.state_dict()}
        if len(self._oplog) > max(16, int(2.1 * max(self._live, 1))):
            self._oplog = []
            return {"full": self.state_dict()}
        ops, self._oplog = self._oplog, []
        return {"ops": ops}

    def apply_incremental(self, delta: dict) -> None:
        if "full" in delta:
            self.load_state_dict(delta["full"])
            return
        from .batch import BatchBuilder
        for op in delta["ops"]:
            if op[0] == "ins":
                _tag, tss, rows = op
                bb = BatchBuilder(self.schema, self.strings)
                for ts, row in zip(tss, rows):
                    bb.append(ts, row)
                self._insert_batch_impl(bb.freeze())   # replay: no re-log
            elif op[0] == "del":
                for row in op[1]:
                    i = self._find_content_row(row)
                    if i is not None:
                        self._delete_rows_impl(np.asarray([i]))
            else:
                _tag, row, attr, value = op
                i = self._find_content_row(row)
                if i is not None:
                    self._set_row_value_impl(int(i), attr, value)

    def _find_content_row(self, row: tuple):
        for i in self.live_idx():
            if self.row_tuple(int(i)) == tuple(row):
                return int(i)
        return None

    def state_dict(self) -> dict:
        keep = self.live_idx()
        return {
            "cols": {k: v[keep] for k, v in self._cols.items()},
            "nulls": {k: v[keep] for k, v in self._nulls.items()},
            "ts": self._ts[keep],
        }

    def load_state_dict(self, st: dict) -> None:
        # a restore invalidates the delta baseline: drop the log AND force
        # the next incremental snapshot to emit a full (ops relative to the
        # restored state would replay against the wrong on-disk base)
        self._oplog = []
        self._oplog_active = False
        n = len(st["ts"])
        self._cap = max(64, int(2 ** np.ceil(np.log2(max(n, 1) + 1))))
        self._cols = {k: np.zeros(self._cap, dtype=v.dtype)
                      for k, v in st["cols"].items()}
        self._nulls = {k: np.zeros(self._cap, dtype=bool) for k in st["nulls"]}
        self._ts = np.zeros(self._cap, dtype=TIMESTAMP_DTYPE)
        self._valid = np.zeros(self._cap, dtype=bool)
        for k, v in st["cols"].items():
            self._cols[k][:n] = v
        for k, v in st["nulls"].items():
            self._nulls[k][:n] = v
        self._ts[:n] = st["ts"]
        self._valid[:n] = True
        self._n = n
        self._live = n
        self._rebuild_indexes()


# ---------------------------------------------------------------------------
# condition planner (reference: CollectionExpressionParser.java:843)
# ---------------------------------------------------------------------------

class CompiledTableCondition:
    """Index-aware compiled lookup: `candidates()` narrows via PK/secondary
    index seeks, the vectorized residual mask filters the rest."""

    def __init__(self, table: InMemoryTable,
                 pk_fns: Optional[list],          # value_fn per pk attr, or None
                 index_seeks: list,               # [(attr, value_fn)]
                 residual: Optional[Callable],    # fn(idx, env) -> bool mask
                 always_false: bool = False):
        self.table = table
        self.pk_fns = pk_fns
        self.index_seeks = index_seeks
        self.residual = residual
        self.always_false = always_false

    @property
    def uses_index(self) -> bool:
        return self.pk_fns is not None or bool(self.index_seeks)

    def find(self, env: dict) -> np.ndarray:
        """Matching live row indices for one probe env (table order)."""
        t = self.table
        if self.always_false or t._live == 0:
            return np.empty(0, dtype=np.int64)
        if self.pk_fns is not None:
            vals = [f(env) for f in self.pk_fns]
            # null probe matches nothing (null == null is false), matching
            # the residual-scan path's semantics
            if any(v is None for v in vals):
                return np.empty(0, dtype=np.int64)
            key = vals[0] if len(vals) == 1 else tuple(vals)
            row = t._pk.get(_normalize_key(key))
            idx = (np.empty(0, dtype=np.int64) if row is None
                   else np.asarray([row], dtype=np.int64))
        elif self.index_seeks:
            sets = []
            for attr, f in self.index_seeks:
                v = f(env)
                if v is None:
                    return np.empty(0, dtype=np.int64)
                s = t._index[attr].get(_normalize_key(v))
                if not s:
                    return np.empty(0, dtype=np.int64)
                sets.append(s)
            sets.sort(key=len)
            hit = set(sets[0])
            for s in sets[1:]:
                hit &= s
            idx = np.sort(np.fromiter(hit, dtype=np.int64, count=len(hit)))
        else:
            idx = t.live_idx()
        if len(idx) and self.residual is not None:
            m = self.residual(idx, env)
            idx = idx[np.asarray(m, dtype=bool)]
        return idx

    def contains(self, env: dict) -> bool:
        return len(self.find(env)) > 0


def _normalize_key(k):
    # numpy scalars -> python scalars so dict probes match stored keys
    if isinstance(k, tuple):
        return tuple(_normalize_key(x) for x in k)
    if isinstance(k, np.generic):
        return k.item()
    if isinstance(k, bool):
        return k
    return k


def compile_table_condition(expr: Optional[ast.Expression],
                            table=None, refs=None, stream_ctx=None,
                            **_kw):
    """Dispatch: record-store tables compile to pushdown conditions
    (reference CollectionExpressionParser vs ExpressionBuilder split)."""
    if getattr(table, "is_record", False):
        from .record_table import compile_record_condition
        return compile_record_condition(expr, table, refs, stream_ctx)
    return _compile_inmemory_condition(expr, table, refs, stream_ctx)


def _compile_inmemory_condition(expr: Optional[ast.Expression],
                            table: InMemoryTable,
                            table_refs: tuple[str, ...],
                            stream_ctx) -> CompiledTableCondition:
    """Split `on` condition into PK seek / index seeks / vectorized residual.

    table_refs: names that resolve to the table (its id plus any alias).
    stream_ctx: PyExprContext for the probing side (compile_py-compatible);
    unqualified attributes resolve stream-first, then table (reference
    resolution order for table match conditions).
    """
    from ..interp.expr import compile_py

    if expr is None or isinstance(expr, ast.Constant) and expr.value is True:
        return CompiledTableCondition(table, None, [], None)

    refs = set(table_refs) | {table.id}
    conjuncts = _flatten_and(expr)

    def is_table_var(e) -> Optional[str]:
        if not isinstance(e, ast.Variable):
            return None
        if e.stream_ref is not None:
            return e.attribute if e.stream_ref in refs else None
        # unqualified: stream side wins if it resolves there
        try:
            stream_ctx.resolve(e)
            return None
        except Exception:
            pass
        return e.attribute if e.attribute in table.schema.types else None

    def is_stream_only(e) -> bool:
        if isinstance(e, ast.Variable):
            return is_table_var(e) is None
        if isinstance(e, (ast.Math, ast.Compare, ast.And, ast.Or)):
            return is_stream_only(e.left) and is_stream_only(e.right)
        if isinstance(e, ast.Not):
            return is_stream_only(e.expr)
        if isinstance(e, ast.FunctionCall):
            return all(is_stream_only(a) for a in e.args)
        if isinstance(e, ast.IsNull):
            return e.expr is not None and is_stream_only(e.expr)
        if isinstance(e, (ast.Constant, ast.TimeConstant)):
            return True
        return False

    eq_pairs: list[tuple[str, Callable]] = []      # (table attr, value_fn)
    residual_conjs: list[ast.Expression] = []
    for c in conjuncts:
        placed = False
        if isinstance(c, ast.Compare) and c.op == CompareOp.EQ:
            for tv, sv in ((c.left, c.right), (c.right, c.left)):
                attr = is_table_var(tv)
                if attr is not None and is_stream_only(sv):
                    f, ft = compile_py(sv, stream_ctx)
                    at = table.schema.type_of(attr)
                    eq_pairs.append((attr, _key_caster(f, ft, at, table.strings)))
                    placed = True
                    break
        if not placed:
            residual_conjs.append(c)

    # PK seek only when every PK attribute is pinned by an equality
    pk_fns = None
    if table.pk_attrs:
        by_attr = {a: f for a, f in eq_pairs}
        if all(a in by_attr for a in table.pk_attrs):
            pk_fns = [by_attr[a] for a in table.pk_attrs]
            used = set(table.pk_attrs)
            leftovers = [(a, f) for a, f in eq_pairs if a not in used]
        else:
            leftovers = eq_pairs
    else:
        leftovers = eq_pairs

    index_seeks, residual_eqs = [], []
    if pk_fns is None:
        for a, f in leftovers:
            if a in table.index_attrs:
                index_seeks.append((a, f))
            else:
                residual_eqs.append((a, f))
    # non-indexed equalities fold into the vectorized residual
    residual = _compile_residual(residual_conjs, residual_eqs, table,
                                 refs, stream_ctx)
    return CompiledTableCondition(table, pk_fns, index_seeks, residual)


def _key_caster(f, ft: AttrType, at: AttrType, strings: StringTable):
    """Cast probe values to the table column's stored representation."""
    if at == AttrType.STRING:
        to_code = strings._to_code
        return lambda env: to_code.get(f(env), -1)
    if at in (AttrType.INT, AttrType.LONG):
        return lambda env: (None if (v := f(env)) is None else int(v))
    if at in (AttrType.FLOAT, AttrType.DOUBLE):
        if at == AttrType.FLOAT:
            return lambda env: (None if (v := f(env)) is None
                                else float(np.float32(v)))
        return lambda env: (None if (v := f(env)) is None else float(v))
    if at == AttrType.BOOL:
        return lambda env: (None if (v := f(env)) is None else bool(v))
    return f


def _flatten_and(e: ast.Expression) -> list:
    if isinstance(e, ast.And):
        return _flatten_and(e.left) + _flatten_and(e.right)
    return [e]


# -- vectorized residual -----------------------------------------------------

def _compile_residual(conjuncts: list, eq_pairs: list, table: InMemoryTable,
                      refs: set, stream_ctx) -> Optional[Callable]:
    fns = []
    for attr, vf in eq_pairs:
        fns.append(_eq_mask(table, attr, vf))
    for c in conjuncts:
        try:
            fns.append(_vec(c, table, refs, stream_ctx)[0])
        except _NotVectorizable:
            fns.append(_row_fallback(c, table, refs, stream_ctx))
    if not fns:
        return None

    def residual(idx, env):
        m = np.ones(len(idx), dtype=bool)
        for f in fns:
            vals, nulls = f(idx, env)
            v = np.asarray(vals, dtype=bool) if not np.isscalar(vals) \
                else np.full(len(idx), bool(vals))
            if nulls is not None:
                v = v & ~np.asarray(nulls, dtype=bool)
            m &= v
            if not m.any():
                break
        return m
    return residual


def _eq_mask(table: InMemoryTable, attr: str, value_fn):
    def f(idx, env):
        v = value_fn(env)
        if v is None:
            return np.zeros(len(idx), dtype=bool), None
        col = table._cols[attr][idx]
        return (col == v) & ~table._nulls[attr][idx], None
    return f


class _NotVectorizable(Exception):
    pass


def _vec(e: ast.Expression, table: InMemoryTable, refs: set, stream_ctx):
    """Compile expr -> fn(idx, env) -> (values, null_mask|None); table
    variables become column slices, stream-only parts scalar closures."""
    from ..interp.expr import compile_py
    from .expr import promote

    if isinstance(e, ast.Constant):
        v, t = e.value, e.type
        if t == AttrType.STRING:
            code = table.strings.encode(v)
            return (lambda idx, env: (code, None)), t, True
        return (lambda idx, env: (v, None)), t, False
    if isinstance(e, ast.TimeConstant):
        return (lambda idx, env: (e.millis, None)), AttrType.LONG, False

    if isinstance(e, ast.Variable):
        if e.stream_ref in refs or (e.stream_ref is None
                                    and not _resolves_in_stream(e, stream_ctx)
                                    and e.attribute in table.schema.types):
            attr = e.attribute
            if attr not in table.schema.types:
                raise _NotVectorizable(attr)
            t = table.schema.type_of(attr)
            def f(idx, env, attr=attr):
                nm = table._nulls[attr][idx]
                return table._cols[attr][idx], (nm if nm.any() else None)
            return f, t, True
        # stream side: scalar
        sf, st_ = compile_py(e, stream_ctx)
        if st_ == AttrType.STRING:
            to_code = table.strings._to_code
            def f(idx, env):
                v = sf(env)
                return (to_code.get(v, -1), None) if v is not None else (0, True)
            return f, st_, True    # code-typed
        def f(idx, env):
            v = sf(env)
            return (v, None) if v is not None else (0, True)
        return f, st_, False

    if isinstance(e, ast.Compare):
        lf, lt, _ = _vec(e.left, table, refs, stream_ctx)
        rf, rt, _ = _vec(e.right, table, refs, stream_ctx)
        op = e.op
        if AttrType.STRING in (lt, rt) and op not in (CompareOp.EQ, CompareOp.NEQ):
            raise _NotVectorizable("string ordering")   # row fallback decodes
        npop = {CompareOp.LT: np.less, CompareOp.LE: np.less_equal,
                CompareOp.GT: np.greater, CompareOp.GE: np.greater_equal,
                CompareOp.EQ: np.equal, CompareOp.NEQ: np.not_equal}[op]
        def f(idx, env):
            lv, ln = lf(idx, env)
            rv, rn = rf(idx, env)
            vals = npop(lv, rv)
            return vals, _merge_nulls(ln, rn)
        return f, AttrType.BOOL, False

    if isinstance(e, ast.And) or isinstance(e, ast.Or):
        lf, _, _ = _vec(e.left, table, refs, stream_ctx)
        rf, _, _ = _vec(e.right, table, refs, stream_ctx)
        npop = np.logical_and if isinstance(e, ast.And) else np.logical_or
        def f(idx, env):
            lv, ln = lf(idx, env)
            rv, rn = rf(idx, env)
            lv = _false_nulls(lv, ln)
            rv = _false_nulls(rv, rn)
            return npop(lv, rv), None
        return f, AttrType.BOOL, False

    if isinstance(e, ast.Not):
        xf, _, _ = _vec(e.expr, table, refs, stream_ctx)
        def f(idx, env):
            v, nmask = xf(idx, env)
            return np.logical_not(_false_nulls(v, nmask)), None
        return f, AttrType.BOOL, False

    if isinstance(e, ast.Math):
        lf, lt, _ = _vec(e.left, table, refs, stream_ctx)
        rf, rt, _ = _vec(e.right, table, refs, stream_ctx)
        if AttrType.STRING in (lt, rt):
            raise _NotVectorizable("string math")
        t = promote(lt, rt)
        fn = {ast.MathOp.ADD: np.add, ast.MathOp.SUB: np.subtract,
              ast.MathOp.MUL: np.multiply, ast.MathOp.DIV: np.divide,
              ast.MathOp.MOD: np.mod}[e.op]
        int_div = e.op == ast.MathOp.DIV and t in (AttrType.INT, AttrType.LONG)
        def f(idx, env):
            lv, ln = lf(idx, env)
            rv, rn = rf(idx, env)
            with np.errstate(divide="ignore", invalid="ignore"):
                v = fn(lv, rv)
                if int_div:
                    v = np.trunc(np.true_divide(lv, rv)).astype(np.int64)
            nmask = _merge_nulls(ln, rn)
            zero = (np.asarray(rv) == 0) if e.op in (ast.MathOp.DIV, ast.MathOp.MOD) else None
            return v, _merge_nulls(nmask, zero if zero is not None and np.any(zero) else None)
        return f, t, False

    if isinstance(e, ast.IsNull) and e.expr is not None \
            and isinstance(e.expr, ast.Variable):
        v = e.expr
        attr = v.attribute
        # same stream-first resolution as the Variable branch
        if v.stream_ref in refs or (v.stream_ref is None
                                    and not _resolves_in_stream(v, stream_ctx)
                                    and attr in table.schema.types):
            def f(idx, env, attr=attr):
                return table._nulls[attr][idx], None
            return f, AttrType.BOOL, False
        sf, _ = compile_py(e, stream_ctx)      # stream-side null test
        return (lambda idx, env: (bool(sf(env)), None)), AttrType.BOOL, False

    raise _NotVectorizable(type(e).__name__)


def _resolves_in_stream(var, stream_ctx) -> bool:
    try:
        stream_ctx.resolve(var)
        return True
    except Exception:
        return False


def _merge_nulls(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return np.logical_or(a, b)


def _false_nulls(v, nulls):
    v = np.asarray(v, dtype=bool)
    if nulls is not None:
        v = v & ~np.asarray(nulls, dtype=bool)
    return v


# ---------------------------------------------------------------------------
# output-side table writers (reference: core:query/output/callback/
# InsertIntoTableCallback / UpdateTableCallback / DeleteTableCallback /
# UpdateOrInsertTableCallback, chosen by OutputParser.java:117-220)
# ---------------------------------------------------------------------------

class TableWriter:
    """Applies a query's output batch to a table."""

    def apply(self, batch) -> None:
        raise NotImplementedError


class TableInsertWriter(TableWriter):
    def __init__(self, table: InMemoryTable, out_schema: StreamSchema):
        ts, os_ = table.schema, out_schema
        if len(ts.attributes) != len(os_.attributes) or any(
                a.type != b.type for a, b in zip(os_.attributes, ts.attributes)):
            raise TableError(
                f"insert into table {table.id!r}: output schema "
                f"{[(a.name, a.type.value) for a in os_.attributes]} does not "
                f"match table schema "
                f"{[(a.name, a.type.value) for a in ts.attributes]}")
        self.table = table

    def apply(self, batch) -> None:
        self.table.insert_batch(batch)


class _ConditionedWriter(TableWriter):
    """Shared machinery: per output row, evaluate the compiled `on`
    condition and act on matched table rows."""

    def __init__(self, table: InMemoryTable, out_schema: StreamSchema,
                 on: ast.Expression, set_clauses=(), strings=None):
        from ..interp.expr import PyExprContext, compile_py

        self.table = table
        self.out_schema = out_schema
        self.strings = strings or table.strings
        # stream side of the condition = the query's output row, under a
        # synthetic ref so the table id can't shadow it
        self._out_ref = f"#out#{out_schema.id}"
        sctx = PyExprContext({self._out_ref: out_schema},
                             default_ref=self._out_ref)
        self.cond = compile_table_condition(on, table, (table.id,), sctx)
        # set clauses: value exprs may reference output attrs (unqualified)
        # and table columns (qualified by table id)
        vctx = PyExprContext({self._out_ref: out_schema,
                              table.id: table.schema},
                             default_ref=self._out_ref)
        self.sets: list[tuple[str, Callable]] = []
        for sc in set_clauses:
            attr = sc.attribute.attribute
            if attr not in table.schema.types:
                raise TableError(f"set: table {table.id!r} has no "
                                 f"attribute {attr!r}")
            f, ft = compile_py(sc.value, vctx)
            self.sets.append((attr, f))
        if not set_clauses:
            # bare `update T on ...`: overwrite attributes whose names match
            # (reference: UpdateTableCallback with implicit full-row set)
            self.sets = [
                (a.name, (lambda env, _n=a.name: env.get(_n)))
                for a in table.schema.attributes if a.name in out_schema.types]

    def _row_envs(self, batch):
        names = [a.name for a in self.out_schema.attributes]
        rows = batch.rows(self.strings)
        for ts, row in zip(batch.timestamps, rows):
            env = dict(zip(names, row))
            env["__timestamp__"] = int(ts)
            yield env, row

    def _update_rows(self, idx, env) -> None:
        t = self.table
        for i in idx:
            i = int(i)
            renv = dict(env)
            renv.update(t.row_env(i, (t.id,)))
            for attr, f in self.sets:
                t.set_row_value(i, attr, f(renv))


class TableUpdateWriter(_ConditionedWriter):
    def apply(self, batch) -> None:
        for env, _row in self._row_envs(batch):
            idx = self.cond.find(env)
            self._update_rows(idx, env)


class TableDeleteWriter(_ConditionedWriter):
    def apply(self, batch) -> None:
        for env, _row in self._row_envs(batch):
            self.table.delete_rows(self.cond.find(env))


class TableUpdateOrInsertWriter(_ConditionedWriter):
    """update or insert into T: update matches, insert the arriving row
    when nothing matched (reference: UpdateOrInsertTableCallback)."""

    def __init__(self, table, out_schema, on, set_clauses=(), strings=None):
        super().__init__(table, out_schema, on, set_clauses, strings)
        # the insert half needs a schema-compatible row
        self._insertable = (
            len(table.schema.attributes) == len(out_schema.attributes)
            and all(a.type == b.type for a, b in
                    zip(out_schema.attributes, table.schema.attributes)))

    def apply(self, batch) -> None:
        from .batch import BatchBuilder
        for env, row in self._row_envs(batch):
            idx = self.cond.find(env)
            if len(idx):
                self._update_rows(idx, env)
            else:
                if not self._insertable:
                    raise TableError(
                        f"update or insert into {self.table.id!r}: output "
                        f"schema incompatible with table schema for insert")
                bb = BatchBuilder(self.table.schema, self.strings)
                bb.append(env["__timestamp__"], row)
                self.table.insert_batch(bb.freeze())


def make_table_writer(action: ast.OutputStreamAction, table,
                      out_schema: StreamSchema) -> TableWriter:
    if getattr(table, "is_record", False):
        from .record_table import make_record_table_writer
        return make_record_table_writer(action, table, out_schema)
    if isinstance(action, ast.InsertInto):
        return TableInsertWriter(table, out_schema)
    if isinstance(action, ast.UpdateTable):
        return TableUpdateWriter(table, out_schema, action.on,
                                 action.set_clauses)
    if isinstance(action, ast.DeleteFrom):
        return TableDeleteWriter(table, out_schema, action.on)
    if isinstance(action, ast.UpdateOrInsertTable):
        return TableUpdateOrInsertWriter(table, out_schema, action.on,
                                         action.set_clauses)
    raise TableError(f"unsupported table action {type(action).__name__}")


def _row_fallback(c: ast.Expression, table: InMemoryTable, refs: set,
                  stream_ctx):
    """Per-row evaluation through the host interpreter for expression forms
    the vectorizer doesn't cover (functions, string ordering, ...)."""
    from ..interp.expr import PyExprContext, compile_py

    schemas = dict(getattr(stream_ctx, "schemas", {}))
    for r in refs:
        schemas[r] = table.schema
    ctx = PyExprContext(schemas, getattr(stream_ctx, "extra", {}),
                        getattr(stream_ctx, "default_ref", None))
    ctx.tables = getattr(stream_ctx, "tables", {})
    fn, _ = compile_py(c, ctx)
    refs_t = tuple(refs)

    def f(idx, env):
        out = np.empty(len(idx), dtype=bool)
        for j, i in enumerate(idx):
            renv = dict(env)
            renv.update(table.row_env(int(i), refs_t))
            out[j] = bool(fn(renv))
        return out, None
    return f
