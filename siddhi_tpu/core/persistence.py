"""Persistence stores: file-system, incremental (base + op-log deltas),
and asynchronous write-out.

Reference: core:util/persistence/FileSystemPersistenceStore,
IncrementalFileSystemPersistenceStore.java:37,
core:util/snapshot/AsyncSnapshotPersistor.java:70,
core:event/stream/holder/SnapshotableStreamEventQueue (op-log snapshots),
core:table/holder/IndexEventHolder.java:74-76 (change-log with the 2.1x
full-snapshot threshold).

TPU-framework twist: device plan state is a handful of dense arrays, so a
full snapshot of a plan is already one host copy + pickle — the op-log
machinery pays off for TABLES, where mutation rate is low relative to
size.  Incremental revisions therefore carry table op-logs plus full
state for everything else, mirroring where the reference's incremental
path actually saves work.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Optional


class Revision(str):
    """Structured descriptor returned by `rt.persist()` — still the
    revision-id string (str subclass: every existing caller comparing
    against `store.last_revision()` keeps working), plus the fields the
    recovery manager and the service snapshot endpoint report:

      revision    the id (== str(self))
      watermark   per-stream durable WAL frame seq this revision's
                  state reflects (None when durability is off)
      durability  the app's sync policy at persist time
      incremental True for an op-log delta ('I-') / prefixed full
    """

    def __new__(cls, rev: str, watermark: Optional[dict] = None,
                durability: str = "off", incremental: bool = False):
        self = super().__new__(cls, rev)
        self.revision = rev
        self.watermark = dict(watermark) if watermark is not None else None
        self.durability = durability
        self.incremental = bool(incremental)
        return self

    def to_dict(self) -> dict:
        return {"revision": self.revision, "watermark": self.watermark,
                "durability": self.durability,
                "incremental": self.incremental}


class FileSystemPersistenceStore:
    """One file per revision under <dir>/<app>/ (reference:
    FileSystemPersistenceStore)."""

    # revisions survive a process crash: WAL truncation behind a
    # snapshot barrier may trust them (custom stores without this
    # attribute are judged by whether they expose a `dir`)
    durable = True

    def __init__(self, directory: str):
        self.dir = directory
        self.corrupt_skipped = 0    # unpicklable revisions skipped on restore
        os.makedirs(directory, exist_ok=True)

    def _app_dir(self, app: str) -> str:
        d = os.path.join(self.dir, app.replace(os.sep, "_") or "_app")
        os.makedirs(d, exist_ok=True)
        return d

    def save(self, app: str, revision: str, blob: bytes) -> None:
        path = os.path.join(self._app_dir(app), f"{revision}.snapshot")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            # fsync before publish: WAL truncation behind a snapshot
            # barrier assumes the revision SURVIVES — a power loss must
            # not leave a truncated log pointing at a ghost snapshot
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)       # atomic publish
        try:
            # the rename itself lives in the directory entry: without a
            # directory fsync a power loss can forget the publish while
            # the truncated WAL survives — the exact ghost this guards
            dfd = os.open(os.path.dirname(path), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:             # platform without dir fsync
            pass

    def load(self, app: str, revision: str) -> bytes:
        with open(os.path.join(self._app_dir(app),
                               f"{revision}.snapshot"), "rb") as f:
            return f.read()

    def revisions(self, app: str) -> list:
        d = self._app_dir(app)
        revs = [f[:-len(".snapshot")] for f in os.listdir(d)
                if f.endswith(".snapshot")]
        return sorted(revs, key=_rev_time)

    def last_revision(self, app: str) -> Optional[str]:
        revs = self.revisions(app)
        return revs[-1] if revs else None

    def clear(self, app: str) -> None:
        for r in self.revisions(app):
            os.remove(os.path.join(self._app_dir(app), f"{r}.snapshot"))


def _rev_time(rev: str) -> int:
    """Embedded time_ns of a revision id ('[FI]-<app>-<time_ns>')."""
    try:
        return int(rev.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 0


class IncrementalFileSystemPersistenceStore(FileSystemPersistenceStore):
    """Full revisions (`F-`) and incremental deltas (`I-`): restore loads
    the last full revision and replays every later delta in order
    (reference: IncrementalFileSystemPersistenceStore.java:37)."""

    def save_incremental(self, app: str, revision: str, blob: bytes,
                         is_full: bool) -> None:
        prefix = "F-" if is_full else "I-"
        self.save(app, prefix + revision, blob)

    def _load_checked(self, app: str, rev: str) -> Optional[bytes]:
        """The revision's blob, or None when it is unpicklable/truncated.
        Corruption must not brick recovery: a bad blob is skipped
        (counted + warned) and restore falls back to older revisions."""
        import warnings
        try:
            blob = self.load(app, rev)
            pickle.loads(blob)
            return blob
        except Exception as e:
            self.corrupt_skipped += 1
            warnings.warn(
                f"persistence: skipping corrupt revision {rev!r} "
                f"({type(e).__name__}: {e})", RuntimeWarning)
            return None

    def restore_chain(self, app: str) -> Optional[tuple]:
        """(full_blob, [delta_blobs...], newest_time) for the newest
        LOADABLE full revision; deltas are selected by their embedded
        timestamp, NOT by string order (the 'I-'/'F-' prefixes don't sort
        together).  Corrupt/truncated blobs — a crash mid-write of the
        newest revision — are skipped: a corrupt full falls back to the
        previous full, a corrupt delta is dropped from the chain."""
        revs = self.revisions(app)
        fulls = [r for r in revs if r.startswith("F-")]
        base_blob = None
        while fulls:
            base_blob = self._load_checked(app, fulls[-1])
            if base_blob is not None:
                break
            fulls.pop()
        if not fulls:
            return None
        base = fulls[-1]
        deltas = []     # [(rev, blob)] — validated once, blob reused
        for r in revs:
            if r.startswith("I-") and _rev_time(r) > _rev_time(base):
                blob = self._load_checked(app, r)
                if blob is not None:
                    deltas.append((r, blob))
        newest = _rev_time(deltas[-1][0] if deltas else base)
        return (base_blob, [b for _r, b in deltas], newest)


class AsyncSnapshotPersistor:
    """Fire-and-forget snapshot write-out on a daemon thread (reference:
    AsyncSnapshotPersistor.java:70).  `errors` collects write failures."""

    def __init__(self):
        self.errors: list = []
        self._threads: list = []

    def persist(self, fn, *args) -> threading.Thread:
        # prune finished writers: a caller that never wait()s must not
        # accumulate one dead Thread object per persist() forever
        self._threads = [t for t in self._threads if t.is_alive()]

        def run():
            try:
                fn(*args)
            except Exception as e:      # surfaced via .errors
                self.errors.append(e)
        t = threading.Thread(target=run, name="siddhi-persist", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def wait(self, timeout: float = 10.0) -> None:
        """Join outstanding writes; raises TimeoutError if any is still
        in flight (a caller must not conclude durability on a timeout)."""
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]
        if self._threads:
            raise TimeoutError(
                f"{len(self._threads)} snapshot write(s) still in flight")


class PeriodicPersistence:
    """Persist the runtime every `interval_s` on a daemon thread until
    stopped (the scheduler-driven persistence the reference wires via
    SiddhiContext.persistenceStore + external triggers)."""

    def __init__(self, rt, interval_s: float, incremental: bool = False):
        self.rt = rt
        self.interval_s = interval_s
        self.incremental = incremental
        self.revisions: list = []
        self.errors: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="siddhi-periodic-persist")
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.revisions.append(
                    self.rt.persist(incremental=self.incremental))
            except Exception as e:
                self.errors.append(e)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
