"""Statistics trackers + step debugger.

Reference: core:util/statistics/metrics/SiddhiStatisticsManager.java:35-85
(Codahale registry with throughput/latency/memory trackers wired into
StreamJunction.sendEvent:157 and ProcessStreamReceiver.process:88-94);
core:debugger/SiddhiDebugger.java:36-139 (per-query IN/OUT breakpoints).

Here trackers hang off the runtime's batch dispatch loop — per-batch, not
per-event, so enabled statistics cost one clock read per (stream, plan)
batch.  The debugger fires its callback synchronously at micro-batch
boundaries (the engine's natural step unit) instead of blocking a thread
on a semaphore."""
from __future__ import annotations

import time
from collections import defaultdict
from typing import Callable, Optional


class Tracker:
    __slots__ = ("events", "batches", "seconds")

    def __init__(self):
        self.events = 0
        self.batches = 0
        self.seconds = 0.0

    def as_dict(self) -> dict:
        d = {"events": self.events, "batches": self.batches}
        if self.seconds:
            d["seconds"] = self.seconds
            if self.events:
                d["latency_us_per_event"] = 1e6 * self.seconds / self.events
            d["throughput_eps"] = (self.events / self.seconds
                                   if self.seconds else None)
        return d


REPORTERS: dict = {}


def register_stats_reporter(name: str, fn, meta=None) -> None:
    """fn(app_name, report_dict) — the reporter SPI (reference:
    SiddhiStatisticsManager.java:35-85 console/JMX reporters)."""
    from ..extension import register_meta
    register_meta("stats-reporter", meta)
    REPORTERS[name.lower()] = fn


def _console_reporter(app: str, report: dict) -> None:
    import json as _json
    print(f"[siddhi-stats] {app}: {_json.dumps(report, default=str)}")


def _log_reporter(app: str, report: dict) -> None:
    import logging
    logging.getLogger("siddhi_tpu.stats").info("%s: %s", app, report)


REPORTERS["console"] = _console_reporter
REPORTERS["log"] = _log_reporter


class StatisticsManager:
    """Per-stream throughput + per-query latency (+ state memory sizing).
    `@app:statistics(reporter='console', interval='5 sec')` starts a
    periodic reporter thread (reference: @app:statistics reporter/interval,
    SiddhiAppParser.java:108-144)."""

    def __init__(self, rt):
        self.rt = rt
        self.enabled = False
        self.stream_in: dict = defaultdict(Tracker)
        self.query: dict = defaultdict(Tracker)
        self._t0 = time.perf_counter()
        self.reporter = None
        self.interval_s: float = 5.0
        self._rep_thread = None
        self._rep_stop = None

    def configure(self, reporter: str, interval_s: float) -> None:
        fn = REPORTERS.get((reporter or "console").lower())
        if fn is None:
            raise ValueError(f"unknown statistics reporter {reporter!r}; "
                             f"have {sorted(REPORTERS)}")
        self.reporter = fn
        self.interval_s = interval_s

    def start_reporting(self) -> None:
        import threading
        if self.reporter is None or self._rep_thread is not None:
            return
        self._rep_stop = threading.Event()

        def pump():
            while not self._rep_stop.wait(self.interval_s):
                try:
                    self.reporter(self.rt.app.name, self.report())
                except Exception:
                    pass
        self._rep_thread = threading.Thread(
            target=pump, name="siddhi-stats-report", daemon=True)
        self._rep_thread.start()

    def stop_reporting(self) -> None:
        if self._rep_stop is not None:
            self._rep_stop.set()
            self._rep_thread.join(timeout=2)
            self._rep_thread = None
            self._rep_stop = None

    def on_stream_batch(self, sid: str, n: int) -> None:
        t = self.stream_in[sid]
        t.events += n
        t.batches += 1

    def time_plan(self, name: str, n: int):
        """Context manager timing one plan.process batch."""
        return _PlanTimer(self.query[name], n)

    def memory_bytes(self) -> int:
        """Approximate retained state size (reference:
        ObjectSizeCalculator.java:66 — we pickle-size the snapshot)."""
        import pickle
        try:
            return len(pickle.dumps(self.rt._snapshot_locked()))
        except Exception:
            return -1

    def report(self) -> dict:
        up = time.perf_counter() - self._t0
        return {
            "uptime_s": up,
            "streams": {k: v.as_dict() for k, v in self.stream_in.items()},
            "queries": {k: v.as_dict() for k, v in self.query.items()},
        }

    def reset(self) -> None:
        self.stream_in.clear()
        self.query.clear()
        self._t0 = time.perf_counter()


class _PlanTimer:
    __slots__ = ("tracker", "n", "start")

    def __init__(self, tracker: Tracker, n: int):
        self.tracker = tracker
        self.n = n

    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.tracker.seconds += time.perf_counter() - self.start
        self.tracker.events += self.n
        self.tracker.batches += 1
        return False


class SiddhiDebugger:
    """Micro-batch-boundary breakpoints (reference: SiddhiDebugger.java:36:
    acquireBreakPoint(query, IN|OUT) + SiddhiDebuggerCallback.debugEvent).

    The callback runs synchronously inside the dispatch loop; inspect live
    state via runtime.snapshot() / runtime.tables etc. from within it."""

    IN = "in"
    OUT = "out"

    def __init__(self, rt):
        self.rt = rt
        self._breakpoints: set = set()       # (query_name, point)
        self._callback: Optional[Callable] = None

    def acquire_breakpoint(self, query_name: str, point: str = IN) -> None:
        if query_name not in self.rt._known_query_names:
            raise KeyError(f"unknown query {query_name!r}")
        self._breakpoints.add((query_name, point))

    def release_breakpoint(self, query_name: str, point: str = IN) -> None:
        self._breakpoints.discard((query_name, point))

    def release_all(self) -> None:
        self._breakpoints.clear()

    def set_callback(self, fn: Callable) -> None:
        """fn(query_name, point, events) — events are decoded host Events."""
        self._callback = fn

    # -- engine hooks --------------------------------------------------------

    def check_in(self, plan, batch) -> None:
        name = getattr(plan, "callback_name", plan.name)
        if self._callback and (name, self.IN) in self._breakpoints:
            self._callback(name, self.IN, self.rt._decode(batch))

    def check_out(self, plan, out_batches: list) -> None:
        name = getattr(plan, "callback_name", plan.name)
        if self._callback and (name, self.OUT) in self._breakpoints:
            for ob in out_batches:
                if ob.batch.n:
                    self._callback(name, self.OUT, self.rt._decode(ob.batch))
