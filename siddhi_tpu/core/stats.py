"""Back-compat shim — the statistics/debugger surface moved to
`telemetry.py`, which folds the old per-batch trackers into the full
observability layer (span tracing, latency histograms, device metrics,
Prometheus exposition).  Import from `siddhi_tpu.core.telemetry` in new
code; this module re-exports the complete public surface so existing
imports (and registered reporters) keep working against the SAME
registries."""
from .telemetry import (  # noqa: F401
    Histogram,
    PROM_LATEST,
    PipelineTracer,
    REPORTERS,
    STAGES,
    SiddhiDebugger,
    StatisticsManager,
    Tracker,
    XLA_CACHE,
    register_stats_reporter,
    render_prometheus,
)

__all__ = [
    "Histogram", "PipelineTracer", "Tracker", "StatisticsManager",
    "SiddhiDebugger", "REPORTERS", "PROM_LATEST", "STAGES", "XLA_CACHE",
    "register_stats_reporter", "render_prometheus",
]
