"""Sources, sinks, and mappers — the transport SPI.

Reference: core:stream/input/source/Source.java:42 (lifecycle +
connectWithRetry), SourceMapper.java:193, core:stream/output/sink/Sink.java,
SinkMapper, InMemorySource.java:115 / InMemorySink over the topic bus
core:util/transport/InMemoryBroker.java:121, exponential backoff
core:util/transport/BackoffRetryCounter.java:24.

Differences by design: mappers translate between wire payloads and columnar
rows (lists of tuples), not pooled event objects; a source delivers a whole
message as one micro-batch.  Extension points are plain registries
(`register_source_type` / `register_sink_type` / `register_*_mapper`) —
the Python analog of `@Extension` classpath scanning.
"""
from __future__ import annotations

import json
import time
import warnings
from collections import defaultdict
from typing import Callable, Optional

from ..query import ast
from .planner import PlanError


# ---------------------------------------------------------------------------
# in-memory topic bus (reference: InMemoryBroker.java:121)
# ---------------------------------------------------------------------------

class Broker:
    """An isolated in-memory topic bus instance.  The reference's
    InMemoryBroker is a process-global static (two apps — even in two
    SiddhiManagers — sharing a topic name cross-talk); construct a
    SiddhiManager with `isolated_broker=True` to scope topics to that
    manager instead."""

    def __init__(self):
        self._subs: dict = defaultdict(list)    # topic -> [subscriber fn]

    def publish(self, topic: str, message) -> None:
        for fn in list(self._subs.get(topic, ())):
            fn(message)

    def subscribe(self, topic: str, fn: Callable) -> Callable:
        self._subs[topic].append(fn)
        return fn

    def unsubscribe(self, topic: str, fn: Callable) -> None:
        try:
            self._subs[topic].remove(fn)
        except ValueError:
            pass

    def reset(self) -> None:
        self._subs.clear()


_DEFAULT_BROKER = Broker()


def broker_for(rt) -> Broker:
    """The bus a runtime's inMemory transports ride: the owning
    manager's isolated broker when configured, else the process-global
    default (reference semantics)."""
    mgr = getattr(rt, "manager", None)
    b = getattr(mgr, "broker", None)
    return b if b is not None else _DEFAULT_BROKER


class InMemoryBroker:
    """Process-global facade (reference: InMemoryBroker.java:121's
    static subscriber table).  Semantics are deliberately global: every
    runtime in the process shares these topics unless its manager opted
    into an isolated broker.  `reset()` clears all topics (tests)."""

    @classmethod
    def publish(cls, topic: str, message) -> None:
        _DEFAULT_BROKER.publish(topic, message)

    @classmethod
    def subscribe(cls, topic: str, fn: Callable) -> Callable:
        return _DEFAULT_BROKER.subscribe(topic, fn)

    @classmethod
    def unsubscribe(cls, topic: str, fn: Callable) -> None:
        _DEFAULT_BROKER.unsubscribe(topic, fn)

    @classmethod
    def reset(cls) -> None:
        _DEFAULT_BROKER.reset()


# ---------------------------------------------------------------------------
# mappers
# ---------------------------------------------------------------------------

class SourceMapper:
    """wire message -> list of (timestamp|None, row_tuple)."""

    def __init__(self, schema, options: dict):
        self.schema = schema
        self.options = options

    def map(self, message) -> list:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    """Message is a row tuple, a list of row tuples, or an Event
    (reference: PassThroughSourceMapper.java:80)."""

    def map(self, message) -> list:
        from .runtime import Event
        if isinstance(message, Event):
            return [(message.timestamp, message.data)]
        if isinstance(message, tuple):
            return [(None, message)]
        if isinstance(message, list):
            out = []
            for m in message:
                if isinstance(m, Event):
                    out.append((m.timestamp, m.data))
                else:
                    out.append((None, tuple(m)))
            return out
        raise ValueError(f"passThrough mapper: bad message {message!r}")


class JsonSourceMapper(SourceMapper):
    """`{"event": {attr: value, ...}}` (or a JSON list of such), matching
    the reference json mapper's default template."""

    def map(self, message) -> list:
        if isinstance(message, (str, bytes)):
            message = json.loads(message)
        msgs = message if isinstance(message, list) else [message]
        names = self.schema.names
        out = []
        for m in msgs:
            body = m.get("event", m) if isinstance(m, dict) else m
            out.append((None, tuple(body.get(n) for n in names)))
        return out


class TemplateBuilder:
    """`{{attr}}` payload templating (reference:
    core:util/transport/TemplateBuilder.java — validates placeholders
    against the schema at build time, fills per event at runtime)."""

    import re as _re
    _PH = _re.compile(r"\{\{\s*(\w+)\s*\}\}")

    def __init__(self, schema, template: str):
        self.template = template
        self._parts: list = []      # literal str | attr index
        pos = 0
        for m in self._PH.finditer(template):
            if m.start() > pos:
                self._parts.append(template[pos:m.start()])
            attr = m.group(1)
            if attr not in schema.index_of:
                raise PlanError(
                    f"@payload template references unknown attribute "
                    f"{attr!r}; stream has {list(schema.names)}")
            self._parts.append(schema.index_of[attr])
            pos = m.end()
        if pos < len(template):
            self._parts.append(template[pos:])
        if not any(isinstance(p, int) for p in self._parts):
            raise PlanError(
                f"@payload template has no {{{{attribute}}}} placeholders: "
                f"{template!r}")

    def build(self, data: tuple) -> str:
        return "".join(
            p if isinstance(p, str)
            else ("null" if data[p] is None else str(data[p]))
            for p in self._parts)


class SinkMapper:
    """events -> wire payloads (one per event)."""

    def __init__(self, schema, options: dict):
        self.schema = schema
        self.options = options
        tpl = options.get("_payload")
        self.payload = TemplateBuilder(schema, tpl) if tpl else None

    def map(self, events: list) -> list:
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, events: list) -> list:
        if self.payload is not None:
            return [self.payload.build(e.data) for e in events]
        return [e.data for e in events]


class JsonSinkMapper(SinkMapper):
    """Default `{"event": {...}}` envelope; a @payload template replaces
    it wholesale (reference json sink mapper custom-payload mode)."""

    def map(self, events: list) -> list:
        if self.payload is not None:
            return [self.payload.build(e.data) for e in events]
        names = self.schema.names
        return [json.dumps({"event": dict(zip(names, e.data))}) for e in events]


class TextSinkMapper(SinkMapper):
    """`@map(type='text')` — `attr:"value"` lines per event, or a
    @payload template (reference: siddhi-map-text TextSinkMapper
    default/custom modes).  `delimiter` joins multi-event publishes."""

    def map(self, events: list) -> list:
        names = self.schema.names
        out = []
        for e in events:
            if self.payload is not None:
                out.append(self.payload.build(e.data))
                continue
            parts = []
            for n, v in zip(names, e.data):
                if isinstance(v, str):
                    parts.append(f'{n}:"{v}"')
                elif v is None:
                    parts.append(f"{n}:null")
                else:
                    parts.append(f"{n}:{v}")
            out.append(",\n".join(parts))
        delim = self.options.get("delimiter")
        if delim and out:
            return [delim.join(out)]
        return out


class TextSourceMapper(SourceMapper):
    """`@map(type='text')` inbound: parses `attr:value` lines (quotes
    optional), coercing by schema type; a `delimiter` option splits one
    message into several events (reference: siddhi-map-text
    TextSourceMapper default mapping)."""

    def map(self, message) -> list:
        if isinstance(message, bytes):
            message = message.decode()
        text = str(message)
        delim = self.options.get("delimiter")
        chunks = text.split(delim) if delim else [text]
        out = []
        for chunk in chunks:
            vals: dict = {}
            for line in chunk.splitlines():
                line = line.strip().rstrip(",")
                if not line or ":" not in line:
                    continue
                k, v = line.split(":", 1)
                vals[k.strip()] = v.strip()
            row = []
            for a in self.schema.attributes:
                raw = vals.get(a.name)
                row.append(self._coerce(raw, a.type))
            out.append((None, tuple(row)))
        return out

    @staticmethod
    def _coerce(raw, t):
        from ..query.ast import AttrType
        if raw is None or raw == "null":
            return None
        if raw.startswith('"') and raw.endswith('"'):
            raw = raw[1:-1]
        try:
            if t in (AttrType.INT, AttrType.LONG):
                return int(float(raw))
            if t in (AttrType.FLOAT, AttrType.DOUBLE):
                return float(raw)
            if t == AttrType.BOOL:
                return str(raw).lower() in ("true", "1")
            return raw
        except (TypeError, ValueError):
            return None


SOURCE_MAPPERS: dict = {"passthrough": PassThroughSourceMapper,
                        "json": JsonSourceMapper,
                        "text": TextSourceMapper}
SINK_MAPPERS: dict = {"passthrough": PassThroughSinkMapper,
                      "json": JsonSinkMapper,
                      "text": TextSinkMapper}


def register_source_mapper(name: str, cls, meta=None) -> None:
    from ..extension import register_meta
    register_meta("source-mapper", meta)
    SOURCE_MAPPERS[name.lower()] = cls


def register_sink_mapper(name: str, cls, meta=None) -> None:
    from ..extension import register_meta
    register_meta("sink-mapper", meta)
    SINK_MAPPERS[name.lower()] = cls


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

class SourceHandler:
    """Interception point between mapper and runtime ingest (reference:
    core:stream/input/source/SourceHandler.java — the HA SPI: an
    active/passive deployment plugs a handler that forwards on the
    active node and records-and-drops on the passive one).  Return the
    (possibly transformed) rows; return None or [] to swallow."""

    def init(self, source: "Source") -> None:
        pass

    def on_rows(self, rows: list) -> Optional[list]:
        return rows

    # snapshot hooks so HA state rides the app snapshot
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d: dict) -> None:
        pass


class SinkHandler:
    """Interception point between the runtime and the sink mapper
    (reference: core:stream/output/sink/SinkHandler.java)."""

    def init(self, sink: "Sink") -> None:
        pass

    def on_events(self, events: list) -> Optional[list]:
        return events

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d: dict) -> None:
        pass


class Source:
    """Transport lifecycle (reference: Source.java:42).  Subclasses
    implement connect/disconnect; incoming payloads go through
    `self.deliver(message)`."""

    def __init__(self, rt, stream_id: str, options: dict,
                 mapper: SourceMapper):
        self.rt = rt
        self.stream_id = stream_id
        self.options = options
        self.mapper = mapper
        self.connected = False
        self.handler: Optional[SourceHandler] = None
        # telemetry: malformed messages silently dropped (logged-only)
        # vs captured into the ErrorStore — surfaced in statistics()
        # and the Prometheus exposition
        self.dropped_events = 0
        self.stored_events = 0

    # -- SPI -----------------------------------------------------------------

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    # -- runtime glue --------------------------------------------------------

    def deliver(self, message) -> None:
        """Map a wire message and feed it as one micro-batch."""
        try:
            rows = self.mapper.map(message)
        except Exception as e:
            action = self.rt.fault_action(self.stream_id)
            # log/wait (and no action) all DROP a map error — a malformed
            # payload is deterministic, there is nothing to wait out —
            # so telemetry records the true disposition, not the action
            self.rt.stats.on_fault(
                self.stream_id,
                f"source.{action}" if action in ("stream", "store")
                else "source.drop")
            if action == "stream":
                self.rt._route_fault_rows(self.stream_id, [],
                                          f"map error: {e}", raw=message)
            elif action == "store":
                # capture the raw payload for replay through this source
                # (ErrorStore.replay sees .deliver and re-feeds the
                # mapper; a still-broken payload re-captures)
                self.rt.error_store.add(
                    self.stream_id, "source.map", e, self.rt.now_ms(),
                    payloads=[message], sink=self)
                self.stored_events += 1
            else:
                # no routing configured: log and drop the malformed
                # message (reference SourceMapper does the same) instead of
                # raising into the transport and starving co-subscribers —
                # but COUNT it (dropped_events rides statistics() and
                # /metrics, so the drop is no longer invisible)
                self.dropped_events += 1
                hint = ("@OnError(action={a!r}) applies to processing "
                        "faults; map errors drop".format(a=action)
                        if action else
                        "add @OnError(action='stream') to route to a fault "
                        "stream (or 'store' to capture for replay)")
                warnings.warn(
                    f"source on {self.stream_id!r}: dropping unmappable "
                    f"message ({e}); {hint}", RuntimeWarning)
            return
        if self.handler is not None:
            rows = self.handler.on_rows(rows)
            if not rows:
                return
        with self.rt._lock:
            for ts, row in rows:
                self.rt._send_locked(self.stream_id, row, ts)
        self.rt._drain_async_outbox()
        self.rt.flush()      # async: barrier outside the lock

    def connect_with_retry(self, max_tries: int = 5,
                           base_delay_s: float = 0.05) -> None:
        """Exponential-backoff connect (reference:
        Source.connectWithRetry + BackoffRetryCounter) — unified on the
        faults.BackoffPolicy schedule shared with sink publishes."""
        import zlib
        from .faults import BackoffPolicy
        policy = BackoffPolicy(max_tries=max_tries,
                               base_delay_s=base_delay_s,
                               seed=zlib.crc32(self.stream_id.encode()))

        def attempt():
            self.rt.inject("source.connect", self.stream_id)
            self.connect()

        def on_retry(i, e, delay):
            warnings.warn(f"source {type(self).__name__} on "
                          f"{self.stream_id!r}: connect failed ({e}); "
                          f"retrying in {delay:.2f}s", RuntimeWarning)

        policy.run(attempt, on_retry=on_retry)
        self.connected = True


class InMemorySource(Source):
    """@source(type='inMemory', topic='t') (reference: InMemorySource.java:115)."""

    def connect(self) -> None:
        topic = self.options.get("topic")
        if not topic:
            raise PlanError("inMemory source needs a topic")
        self._broker = broker_for(self.rt)
        self._fn = self._broker.subscribe(topic, self.deliver)

    def disconnect(self) -> None:
        if self.connected:
            self._broker.unsubscribe(self.options.get("topic"), self._fn)


class CallbackSource(Source):
    """@source(type='callback'): a programmatic ingress handle —
    `rt.sources_for(stream)[0].deliver(msg)`; useful for tests and
    embedding."""

    def connect(self) -> None:
        pass


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

class Sink:
    """Publish-side transport with optional fault tolerance:
    `@sink(..., on.error='log'|'store'|'stream'|'wait')` arms a
    per-payload retry with exponential backoff + seeded jitter
    (faults.BackoffPolicy — the same schedule as connect_with_retry) and
    a per-sink circuit breaker.  `on.error` names the disposition once
    retries exhaust (or the breaker is open):

      log    - log and drop the payload (reference default)
      store  - capture into the runtime ErrorStore for replay
      stream - route into the "!<stream>" fault stream (falls back to
               the ErrorStore when none is defined)
      wait   - extend retries to a deadline (`retry.timeout`, default
               10 sec), then store

    Knobs: max.retries (3), retry.interval ('50 ms'), retry.max.interval
    ('5 sec'), breaker.threshold (5), breaker.reset ('5 sec').  Without
    on.error the legacy fail-fast path is kept: publish errors propagate
    to the caller."""

    def __init__(self, rt, stream_id: str, options: dict, mapper: SinkMapper):
        self.rt = rt
        self.stream_id = stream_id
        self.options = options
        self.mapper = mapper
        self.connected = False
        self.handler: Optional[SinkHandler] = None
        self.published = 0
        self.retries = 0
        self.failures = 0
        self.stored = 0
        self.on_error = (options.get("on.error") or "").lower() or None
        self.breaker = None
        self.backoff = None
        if self.on_error is not None:
            if self.on_error not in ("log", "store", "stream", "wait"):
                raise PlanError(
                    f"sink on {stream_id!r}: unknown on.error "
                    f"{self.on_error!r} (have: log | store | stream | wait)")
            import zlib
            from .faults import BackoffPolicy, CircuitBreaker
            from .runtime import _parse_interval_s

            def _iv(key, default):
                v = options.get(key)
                return _parse_interval_s(v) if v is not None else default
            deadline = _iv("retry.timeout", 10.0) \
                if self.on_error == "wait" else None
            self.backoff = BackoffPolicy(
                max_tries=(1_000_000 if self.on_error == "wait"
                           else int(options.get("max.retries", 3)) + 1),
                base_delay_s=_iv("retry.interval", 0.05),
                max_delay_s=_iv("retry.max.interval", 5.0),
                deadline_s=deadline,
                seed=zlib.crc32(f"{stream_id}/{options.get('topic', '')}"
                                .encode()))
            self.breaker = CircuitBreaker(
                failure_threshold=int(options.get("breaker.threshold", 5)),
                reset_timeout_s=_iv("breaker.reset", 5.0))

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def publish(self, payload) -> None:
        raise NotImplementedError

    def on_events(self, events: list) -> None:
        if self.handler is not None:
            events = self.handler.on_events(events)
            if not events:
                return
        payloads = self.mapper.map(events)
        if self.on_error is None:       # legacy fail-fast path
            for payload in payloads:
                self.publish_attempt(payload)
                self.published += 1
            return
        for payload in payloads:
            self._publish_guarded(payload)

    # -- guarded publish (retry + breaker + on.error) -----------------------

    def publish_attempt(self, payload) -> None:
        """One raw publish attempt through the fault-injection point
        (also the replay entry used by ErrorStore.replay).  Records a
        `sink.publish` span on the originating frame's trace: the live
        thread-local scope (set by runtime._flush_sink_outbox) for
        in-line publishes, or the resumable ctx a stored payload
        carries — so an ErrorStore replay after a breaker shed still
        lands on the SAME tree, not an orphan."""
        self.rt.inject("sink.publish", self.stream_id)
        h = getattr(getattr(self.rt, "_trace_tls", None), "handle", None)
        if h is None:
            tc = getattr(payload, "trace_ctx", None)
            tr = getattr(self.rt, "tracing", None)
            if tc is not None and tr is not None:
                h = tr.resume(*tc)
        if h is None:
            self.publish(payload)
            return
        t0 = time.perf_counter()
        try:
            self.publish(payload)
        finally:
            h.mark("sink.publish", t0, time.perf_counter() - t0,
                  sink=self.stream_id,
                  transport=getattr(self, "transport", type(self).__name__))

    def _publish_guarded(self, payload) -> None:
        if not self.breaker.allow():
            # open breaker: shed straight to the disposition instead of
            # hammering a dead transport per payload
            self._exhausted(payload, RuntimeError(
                f"circuit breaker open for sink on {self.stream_id!r}"))
            return
        err = None
        delays = self.backoff.delays()
        while True:
            try:
                self.publish_attempt(payload)
            except Exception as e:
                err = e
                self.failures += 1
                self.breaker.on_failure()
                if self.breaker.state == self.breaker.OPEN:
                    tr = getattr(self.rt, "tracing", None)
                    if tr is not None:
                        # enqueue-only (cooldown-throttled): the dump
                        # builds on the siddhi-trace-export thread
                        tr.trigger("breaker_open",
                                   f"sink on {self.stream_id!r}: "
                                   f"{type(e).__name__}: {e}")
                    break
                delay = next(delays, None)
                if delay is None:
                    break
                self.retries += 1
                time.sleep(delay)
                continue
            self.breaker.on_success()
            self.published += 1
            return
        self._exhausted(payload, err)

    def _exhausted(self, payload, err) -> None:
        rt = self.rt
        act = self.on_error
        rt.stats.on_fault(self.stream_id, f"sink.{act}")
        if act == "stream" and ("!" + self.stream_id) in rt.schemas:
            rt._route_fault_rows(self.stream_id, [],
                                 f"sink publish failed: {err}", raw=payload)
            return
        if act in ("store", "stream", "wait"):
            rt.error_store.add(self.stream_id, "sink.publish", err,
                               rt.now_ms(), payloads=[payload], sink=self)
            self.stored += 1
            return
        import logging
        logging.getLogger("siddhi_tpu.faults").error(
            "sink on %r: dropping payload after retries "
            "(@sink on.error='log'): %s: %s",
            self.stream_id, type(err).__name__, err)

    def metrics(self) -> dict:
        m = {"published": self.published, "retries": self.retries,
             "failures": self.failures, "stored": self.stored}
        if self.breaker is not None:
            m.update(self.breaker.metrics())
        return m


class DistributedSink(Sink):
    """Multi-destination fan-out (reference: DistributedTransport +
    Broadcast/RoundRobin/Partitioned DistributionStrategy,
    core:stream/output/sink/distributed/DistributionStrategy.java:107,
    MultiClientDistributedSink): one child sink per @destination, the
    strategy picks destinations per event."""

    def __init__(self, rt, stream_id, options, mapper, children,
                 strategy: str, partition_key=None, schema=None):
        super().__init__(rt, stream_id, options, mapper)
        self.children = children
        self.strategy = strategy
        self._rr = 0
        self._key_idx = None
        if strategy == "partitioned":
            if partition_key is None:
                raise PlanError(
                    f"sink on {stream_id!r}: partitioned distribution "
                    f"needs partitionKey")
            if partition_key not in schema.index_of:
                raise PlanError(
                    f"sink on {stream_id!r}: partitionKey "
                    f"{partition_key!r} not in schema {schema.names}")
            self._key_idx = schema.index_of[partition_key]

    def connect(self) -> None:
        for c in self.children:
            c.connect()
            c.connected = True

    def disconnect(self) -> None:
        for c in self.children:
            if c.connected:
                c.disconnect()
                c.connected = False

    def on_events(self, events: list) -> None:
        n = len(self.children)
        if self.strategy == "broadcast":
            for c in self.children:
                c.on_events(events)
            return
        buckets = [[] for _ in range(n)]
        for ev in events:
            if self.strategy == "roundrobin":
                i = self._rr
                self._rr = (self._rr + 1) % n
            else:
                # stable across processes (builtin hash() is salted for
                # strings): same key -> same destination, always
                import zlib
                i = zlib.crc32(repr(ev.data[self._key_idx]).encode()) % n
            buckets[i].append(ev)
        for c, evs in zip(self.children, buckets):
            if evs:
                c.on_events(evs)


class InMemorySink(Sink):
    def connect(self) -> None:
        if not self.options.get("topic"):
            raise PlanError("inMemory sink needs a topic")
        self._broker = broker_for(self.rt)

    def publish(self, payload) -> None:
        self._broker.publish(self.options["topic"], payload)


class LogSink(Sink):
    """@sink(type='log') — prints events (reference: log sink extension)."""

    def connect(self) -> None:
        pass

    def publish(self, payload) -> None:
        print(f"[{self.options.get('prefix', self.stream_id)}] {payload}")


SOURCE_TYPES: dict = {"inmemory": InMemorySource, "callback": CallbackSource}
SINK_TYPES: dict = {"inmemory": InMemorySink, "log": LogSink}


def register_source_type(name: str, cls, meta=None) -> None:
    from ..extension import register_meta
    register_meta("source", meta)
    SOURCE_TYPES[name.lower()] = cls


def register_sink_type(name: str, cls, meta=None) -> None:
    from ..extension import register_meta
    register_meta("sink", meta)
    SINK_TYPES[name.lower()] = cls


# ---------------------------------------------------------------------------
# wiring from @source/@sink annotations
# (reference: DefinitionParserHelper.addEventSource/addEventSink:309-433)
# ---------------------------------------------------------------------------

def _ann_options(a: ast.Annotation) -> dict:
    return {(k.lower() if k else f"_{i}"): v
            for i, (k, v) in enumerate(a.elements)}


def _load_net_types() -> None:
    """Lazy registration of the serving-plane transports (tcp/ws/shm
    sources, tcp/ws sinks) — importing siddhi_tpu.net registers them.
    Deferred so apps that never network pay no import cost."""
    import importlib
    try:
        importlib.import_module(".net", package=__package__.rsplit(".", 1)[0])
    except ImportError:
        pass


def build_io(rt) -> None:
    """Instantiate sources/sinks declared on stream definitions."""
    from ..query.ast import find_annotation
    for sid, sd in rt.app.stream_definitions.items():
        for a in sd.annotations:
            nm = a.name.lower()
            if nm == "source":
                opts = _ann_options(a)
                typ = opts.get("type", "").lower()
                cls = SOURCE_TYPES.get(typ)
                if cls is None:
                    _load_net_types()
                    cls = SOURCE_TYPES.get(typ)
                if cls is None:
                    raise PlanError(f"unknown source type {typ!r} on "
                                    f"{sid!r}; have {sorted(SOURCE_TYPES)}")
                mapper = _mapper_of(a, rt.schemas[sid], SOURCE_MAPPERS,
                                    PassThroughSourceMapper)
                src = cls(rt, sid, opts, mapper)
                src.config = rt.config_reader("source", typ)
                fac = getattr(rt.manager, "source_handler_factory", None) \
                    if rt.manager else None
                if fac is not None:
                    src.handler = fac()
                    src.handler.init(src)
                rt.sources.append(src)
            elif nm == "sink":
                opts = _ann_options(a)
                typ = opts.get("type", "").lower()
                cls = SINK_TYPES.get(typ)
                if cls is None:
                    _load_net_types()
                    cls = SINK_TYPES.get(typ)
                if cls is None:
                    raise PlanError(f"unknown sink type {typ!r} on "
                                    f"{sid!r}; have {sorted(SINK_TYPES)}")
                mapper = _mapper_of(a, rt.schemas[sid], SINK_MAPPERS,
                                    PassThroughSinkMapper)
                from ..query.ast import find_annotation
                dist = find_annotation(a.annotations, "distribution")
                if dist is not None:
                    # keyed elements only (the lone-positional fallback of
                    # Annotation.element would alias strategy/partitionKey)
                    def _kv(ann, key, default=None):
                        return next((v for k, v in ann.elements if k == key),
                                    default)
                    strategy = (_kv(dist, "strategy") or "roundRobin").lower()
                    if strategy not in ("broadcast", "roundrobin",
                                        "partitioned"):
                        raise PlanError(f"sink on {sid!r}: unknown "
                                        f"distribution strategy {strategy!r}")
                    dests = [d for d in dist.annotations
                             if d.name == "destination"]
                    if not dests:
                        raise PlanError(f"sink on {sid!r}: @distribution "
                                        f"needs @destination entries")
                    children = []
                    for d in dests:
                        child_opts = dict(opts)
                        child_opts.update(_ann_options(d))
                        children.append(cls(rt, sid, child_opts, mapper))
                    sink = DistributedSink(
                        rt, sid, opts, mapper, children, strategy,
                        _kv(dist, "partitionKey"), rt.schemas[sid])
                else:
                    sink = cls(rt, sid, opts, mapper)
                sink.config = rt.config_reader("sink", typ)
                fac = getattr(rt.manager, "sink_handler_factory", None) \
                    if rt.manager else None
                if fac is not None:
                    sink.handler = fac()
                    sink.handler.init(sink)
                rt.sinks.append(sink)
                # stage into the runtime's outbox instead of publishing
                # under the runtime lock (cross-runtime ABBA deadlock —
                # runtime._flush_sink_outbox delivers after release).
                # The active frame-trace handle (scatter runs under the
                # frame's scope) rides the entry so egress spans land on
                # the right tree when the outbox flushes later
                def _stage(events, _sink=sink, _rt=rt):
                    _rt._sink_outbox.append(
                        (_sink.on_events, events, _rt.current_trace()))
                rt._stream_callbacks[sid].append(_stage)


def _mapper_of(a: ast.Annotation, schema, registry: dict, default_cls):
    from ..query.ast import find_annotation
    m = find_annotation(a.annotations, "map")
    if m is None:
        return default_cls(schema, {})
    opts = _ann_options(m)
    # @payload('... {{attr}} ...') nested under @map (reference:
    # AnnotationHelper payload extraction feeding TemplateBuilder)
    pl = find_annotation(m.annotations, "payload")
    if pl is not None:
        opts["_payload"] = pl.element()
    typ = opts.get("type", "passThrough").lower()
    cls = registry.get(typ)
    if cls is None:
        raise PlanError(f"unknown mapper type {typ!r}; have {sorted(registry)}")
    return cls(schema, opts)
