"""Fault-tolerance layer: backoff, circuit breaking, the ErrorStore,
graceful-degradation bookkeeping, and the seeded fault-injection harness.

Reference surface: core:util/error/handler/* (ErrorHandlerUtils + the
ErrorStore behind `@OnError(action='STORE')`), core:util/transport/
BackoffRetryCounter.java:24 (the exponential ladder behind
Source.connectWithRetry and sink publish retries), and the
`on.error=...` sink option (SinkMapper/Sink error callbacks).

TPU-framework twist: the unit of failure is a dispatched micro-batch or
an in-flight device entry, not a single event — so recovery operates on
EventBatches (split, requeue, replay) and on whole plans (degrade the
device geometry, then quarantine the plan onto the `siddhi_tpu/interp/`
host path).  Everything here is deterministic by construction: backoff
jitter and the fault injector are seeded, so a chaos run replays
identically under the same seed (`bench.py --chaos --seed N`).
"""
from __future__ import annotations

import random
import re
import threading
import time
import zlib
from collections import defaultdict
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils.locks import new_lock


# ---------------------------------------------------------------------------
# fault classification
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Raised by FaultInjector at an armed injection point.  `kind` is
    "resource" (classified like a device OOM — drives the degradation
    ladder) or "fault" (a generic processing error — drives @OnError)."""

    def __init__(self, point: str, detail: str = "", kind: str = "fault"):
        self.point = point
        self.detail = detail
        self.kind = kind
        tag = "RESOURCE_EXHAUSTED: " if kind == "resource" else ""
        super().__init__(f"{tag}injected fault at {point}"
                         + (f" ({detail})" if detail else ""))


_RESOURCE_RE = re.compile(
    r"resource[ _]exhausted|out of memory|\boom\b|failed to allocate|"
    r"allocation failure|memory exhausted")


def is_resource_error(e: BaseException) -> bool:
    """Does this look like device resource exhaustion (XLA OOM / HBM
    pressure)?  Classification is by message: jax surfaces these as
    XlaRuntimeError/RuntimeError with a RESOURCE_EXHAUSTED status or an
    allocator message, and the exact exception type varies by backend
    and jaxlib version.  ("oom" matches on word boundaries only — an
    app-level "kaboom" must not read as an OOM.)"""
    if isinstance(e, InjectedFault):
        return e.kind == "resource"
    msg = f"{type(e).__name__}: {e}".lower()
    return _RESOURCE_RE.search(msg) is not None


# ---------------------------------------------------------------------------
# backoff (reference: BackoffRetryCounter.java:24)
# ---------------------------------------------------------------------------

class BackoffPolicy:
    """Exponential backoff with seeded jitter; the ONE retry schedule
    shared by sink publishes, source connects, and @OnError WAIT.

    `delays()` yields the sleep before each RETRY (attempt 2..max_tries);
    jitter multiplies each delay by a seeded uniform in
    [1-jitter, 1+jitter] so retries de-synchronize across sinks while a
    fixed seed keeps a chaos run reproducible.  `deadline_s` bounds the
    total schedule (the WAIT semantics): delays stop once the cumulative
    sleep would pass the deadline."""

    def __init__(self, max_tries: int = 5, base_delay_s: float = 0.05,
                 multiplier: float = 2.0, max_delay_s: float = 5.0,
                 jitter: float = 0.25, seed: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_tries = max(1, int(max_tries))
        self.base_delay_s = float(base_delay_s)
        self.multiplier = float(multiplier)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.seed = seed
        self.deadline_s = deadline_s
        self.sleep = sleep

    def delays(self):
        rng = random.Random(self.seed)
        d = self.base_delay_s
        total = 0.0
        for _ in range(self.max_tries - 1):
            j = 1.0 + self.jitter * (2.0 * rng.random() - 1.0) \
                if self.jitter else 1.0
            delay = min(d * j, self.max_delay_s)
            total += delay
            if self.deadline_s is not None and total > self.deadline_s:
                return
            yield delay
            d = min(d * self.multiplier, self.max_delay_s)

    def run(self, fn: Callable, on_retry: Optional[Callable] = None):
        """Call fn() up to max_tries times, sleeping the schedule between
        attempts; `on_retry(attempt_index, error, delay)` fires before
        each sleep.  Raises the last error when the schedule exhausts."""
        delays = self.delays()
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:
                delay = next(delays, None)
                if delay is None:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                self.sleep(delay)
                attempt += 1


# ---------------------------------------------------------------------------
# circuit breaker (per sink)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """closed -> (threshold consecutive failures) -> open -> (reset
    timeout) -> half-open -> one trial: success re-closes, failure
    re-opens.  `allow()` gates attempts; an open breaker sheds load off
    a dead transport instead of paying the full retry schedule per
    payload."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self.clock = clock
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.failures = 0
        self.opens = 0
        self._opened_at = 0.0

    def allow(self) -> bool:
        if self.state == self.OPEN:
            if self.clock() - self._opened_at >= self.reset_timeout_s:
                self.state = self.HALF_OPEN     # one probe may pass
                return True
            return False
        return True

    def on_success(self) -> None:
        self.state = self.CLOSED
        self.consecutive_failures = 0

    def on_failure(self) -> None:
        self.failures += 1
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN \
                or self.consecutive_failures >= self.failure_threshold:
            if self.state != self.OPEN:
                self.opens += 1
            self.state = self.OPEN
            self._opened_at = self.clock()

    def metrics(self) -> dict:
        return {"circuit_state": self._STATE_GAUGE[self.state],
                "circuit_opens": self.opens,
                "circuit_failures": self.failures}


# ---------------------------------------------------------------------------
# error store (reference: @OnError(action='STORE') ErrorStore + replay)
# ---------------------------------------------------------------------------

def _py(v):
    """numpy scalar -> plain python for JSON-safe entry dicts."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            pass
    return str(v)


@dataclass
class ErrorEntry:
    """One captured failure: the events (or sink payloads) it cost, the
    cause, and where it happened — enough to replay."""
    id: int
    stream_id: str
    point: str                    # dispatch | sink.publish | source.map | ...
    message: str
    timestamp_ms: int
    events: Optional[list] = None         # [(ts_ms, row_tuple), ...]
    payloads: Optional[list] = None       # mapped sink payloads
    sink: object = None                   # live Sink ref (in-memory store)
    attempts: int = 0
    replayed: bool = False

    def to_dict(self) -> dict:
        d = {"id": self.id, "stream": self.stream_id, "point": self.point,
             "error": self.message, "timestamp": int(self.timestamp_ms),
             "attempts": self.attempts, "replayed": self.replayed}
        if self.events is not None:
            d["events"] = [[int(ts), [_py(v) for v in row]]
                           for ts, row in self.events]
        if self.payloads is not None:
            d["payloads"] = [_py(p) for p in self.payloads]
        if self.sink is not None:
            d["sink"] = type(self.sink).__name__
        return d


class ErrorStore:
    """Bounded in-memory store of failed work.  `replay(rt)` re-sends
    captured events into their origin stream (and re-publishes captured
    sink payloads); replay failures re-capture, so nothing is silently
    lost.  Served by `GET/POST /siddhi/errors` (service.py)."""

    def __init__(self, capacity: int = 10_000):
        self.capacity = int(capacity)
        self.evicted = 0
        self._entries: list = []
        self._next_id = 1
        self._lock = new_lock("ErrorStore._lock")

    def __len__(self) -> int:
        # lint: allow (len() of a list is one atomic read; scrape-only)
        return len(self._entries)

    def add(self, stream_id: str, point: str, error, timestamp_ms: int,
            events: Optional[list] = None, payloads: Optional[list] = None,
            sink=None) -> ErrorEntry:
        with self._lock:
            ent = ErrorEntry(self._next_id, stream_id, point,
                             f"{type(error).__name__}: {error}"
                             if isinstance(error, BaseException) else str(error),
                             int(timestamp_ms), events=events,
                             payloads=payloads, sink=sink)
            self._next_id += 1
            self._entries.append(ent)
            while len(self._entries) > self.capacity:
                self._entries.pop(0)
                self.evicted += 1
            return ent

    def entries(self, stream_id: Optional[str] = None) -> list:
        with self._lock:
            return [e for e in self._entries
                    if stream_id is None or e.stream_id == stream_id]

    def take(self, ids: Optional[list] = None) -> list:
        """Remove and return entries (all, or just `ids`)."""
        with self._lock:
            if ids is None:
                taken, self._entries = self._entries, []
                return taken
            want = set(ids)
            taken = [e for e in self._entries if e.id in want]
            self._entries = [e for e in self._entries if e.id not in want]
            return taken

    def _readd(self, ent: ErrorEntry) -> None:
        with self._lock:
            self._entries.append(ent)
            while len(self._entries) > self.capacity:
                self._entries.pop(0)
                self.evicted += 1

    def replay(self, rt, ids: Optional[list] = None) -> dict:
        """Re-deliver captured work through the live runtime.  Event
        entries re-enter their origin stream via the normal ingest path
        (so a still-broken pipeline re-captures them); sink payload
        entries re-publish through the sink's guarded path."""
        from .runtime import Event
        taken = self.take(ids)
        replayed = failed = 0
        for ent in taken:
            try:
                if ent.events:
                    rt.send(ent.stream_id,
                            [Event(int(ts), tuple(row))
                             for ts, row in ent.events])
                if ent.payloads:
                    tgt = ent.sink
                    if tgt is None:
                        raise RuntimeError("transport no longer available")
                    for p in ent.payloads:
                        if hasattr(tgt, "publish_attempt"):   # sink payload
                            tgt.publish_attempt(p)
                        else:            # source.map capture: re-ingest
                            tgt.deliver(p)
                ent.replayed = True
                replayed += 1
            except Exception:
                ent.attempts += 1
                failed += 1
                self._readd(ent)
        rt.flush()
        return {"replayed": replayed, "failed": failed,
                "remaining": len(self)}


# ---------------------------------------------------------------------------
# graceful-degradation ladder bookkeeping (per plan)
# ---------------------------------------------------------------------------

class FaultLadder:
    """Consecutive-failure counter behind the dispatch degradation
    ladder: resource failure -> halve the work (batch/flush split, which
    halves the device pad/chunk geometry) -> after K consecutive
    failures, quarantine the plan onto the interpreter path."""

    def __init__(self):
        self.consecutive = 0
        self.failures = 0
        self.halvings = 0
        self.quarantined = False
        self.last_error = ""

    def fail(self, e: BaseException) -> None:
        self.consecutive += 1
        self.failures += 1
        self.last_error = f"{type(e).__name__}: {e}"

    def ok(self) -> None:
        self.consecutive = 0

    def metrics(self) -> dict:
        return {"dispatch_failures": self.failures,
                "dispatch_halvings": self.halvings,
                "dispatch_consecutive_failures": self.consecutive,
                "quarantined": self.quarantined}


def slice_batch(b, lo: int, hi: int):
    """View-slice an EventBatch (numpy slices are views — no copy)."""
    from .batch import EventBatch
    return EventBatch(
        b.schema, b.timestamps[lo:hi],
        {k: v[lo:hi] for k, v in b.columns.items()}, hi - lo,
        seqs=None if b.seqs is None else b.seqs[lo:hi],
        nulls=None if b.nulls is None
        else {k: v[lo:hi] for k, v in b.nulls.items()})


def split_batch(b) -> list:
    """Halve one EventBatch (the pad/chunk geometry of a re-dispatch is
    derived from batch.n, so halving the batch halves the device
    footprint)."""
    mid = b.n // 2
    return [slice_batch(b, 0, mid), slice_batch(b, mid, b.n)]


def split_buffered(bufs: list) -> Optional[list]:
    """Halve a finalize flush: [(sid, batch), ...] -> [first, second]
    buffered lists ordered by global seq, or None when nothing is left
    to split.  Feeding the halves through two finalize rounds is
    equivalent to the events arriving in two flushes — which the plans
    already handle (batch-size invariance)."""
    def first_seq(sb):
        b = sb[1]
        return int(b.seqs[0]) if b.seqs is not None and len(b.seqs) else 0
    bufs = sorted(bufs, key=first_seq)
    if len(bufs) >= 2:
        mid = len(bufs) // 2
        return [bufs[:mid], bufs[mid:]]
    if bufs and bufs[0][1].n >= 2:
        sid, b = bufs[0]
        b1, b2 = split_batch(b)
        return [[(sid, b1)], [(sid, b2)]]
    return None


# ---------------------------------------------------------------------------
# seeded fault injection
# ---------------------------------------------------------------------------

class FaultInjector:
    """Deterministic fault injection at the recovery boundaries:

      dispatch        device kernel dispatch (plans' jitted calls)
      d2h             device->host materialization (DispatchPipeline)
      sink.publish    Sink.publish attempts
      source.connect  Source.connect attempts
      persist.save    persistence store writes
      net.decode      serving-plane frame decode (net/server.py) — a
                      failure here is connection-fatal, like a corrupt
                      frame off the wire
      net.feed        serving-plane admitted-frame ingest; a failure
                      captures the whole frame into the ErrorStore
                      (zero-loss invariant, chaos-tested)
      wal.append      durability-log record write (core/wal.py) — armed
                      MID-RECORD, after the first half of the bytes hit
                      the OS, so a kill there leaves a torn tail; a
                      raised fault self-heals the file and propagates
                      (the net feed path then captures the frame whole)
      wal.fsync       the WAL's fsync call (sync-policy barriers)
      wal.truncate    snapshot-barrier segment deletion

    `counts` arms a burst: the first N checks at a point fail.  `rates`
    arms a per-check probability drawn from a per-point rng seeded from
    (seed, point) — the same seed replays the same fault schedule.
    Keys are "point" or "point@detail-substring" (target one plan/sink).
    `kinds` overrides the raised fault's classification per key; by
    default `dispatch` faults are "resource" (they exercise the
    degradation ladder) and everything else is "fault" (@OnError /
    retry paths)."""

    POINTS = ("dispatch", "d2h", "sink.publish", "source.connect",
              "persist.save", "net.decode", "net.feed",
              "wal.append", "wal.fsync", "wal.truncate",
              "repl.ship", "repl.ack", "repl.promote")

    def __init__(self, seed: int = 0, counts: Optional[dict] = None,
                 rates: Optional[dict] = None, kinds: Optional[dict] = None):
        self.seed = int(seed)
        self.counts = dict(counts or {})
        self.rates = dict(rates or {})
        self.kinds = dict(kinds or {})
        self.fired: dict = defaultdict(int)
        self.checked: dict = defaultdict(int)
        self._rngs: dict = {}
        self._lock = new_lock("FaultInjector._lock")

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultInjector":
        """'dispatch=3,sink.publish=0.5,d2h@plan=2' — integers arm
        bursts (counts), floats in (0,1) arm rates."""
        counts: dict = {}
        rates: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            v = float(val)
            if v < 1.0 and "." in val:
                rates[key] = v
            else:
                counts[key] = int(v)
        return cls(seed=seed, counts=counts, rates=rates)

    def _match(self, table: dict, point: str, detail: str):
        for key, val in table.items():
            p, _, d = key.partition("@")
            if p == point and (not d or d in (detail or "")):
                return key, val
        return None, None

    def _kind(self, key: str, point: str) -> str:
        k = self.kinds.get(key) or self.kinds.get(point)
        if k is not None:
            return k
        return "resource" if point == "dispatch" else "fault"

    def check(self, point: str, detail: str = "") -> None:
        """Raise InjectedFault when this point is armed; no-op otherwise."""
        with self._lock:
            self.checked[point] += 1
            key, n = self._match(self.counts, point, detail)
            if key is not None and self.fired[key] < n:
                self.fired[key] += 1
                raise InjectedFault(point, detail, self._kind(key, point))
            key, r = self._match(self.rates, point, detail)
            if key is not None:
                rng = self._rngs.get(key)
                if rng is None:
                    rng = self._rngs[key] = random.Random(
                        self.seed ^ zlib.crc32(key.encode()))
                if rng.random() < r:
                    self.fired[key] += 1
                    raise InjectedFault(point, detail, self._kind(key, point))

    def stats(self) -> dict:
        with self._lock:
            return {"fired": dict(self.fired), "checked": dict(self.checked)}
