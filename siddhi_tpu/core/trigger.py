"""Triggers: `define trigger T at every 5 sec | at '<cron>' | at 'start'`.

Reference: core:trigger/PeriodicTrigger.java (fixed-rate scheduler),
CronTrigger.java:22-26 (quartz), StartTrigger.java — each injects events
carrying the fire timestamp into the trigger's implicit stream
(`define stream T (triggered_time long)`).

Here a trigger is a timer-driven QueryPlan: `next_wakeup`/`on_timer`
integrate with both the virtual clock (`set_time`) and the wall-clock
scheduler pump; emissions route through the normal junction dispatch.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..query import ast
from .batch import EventBatch
from .planner import OutputBatch, PlanError, QueryPlan
from .schema import StreamSchema, TIMESTAMP_DTYPE


TRIGGER_ATTR = "triggered_time"


def trigger_schema(tid: str) -> StreamSchema:
    return StreamSchema(tid, (ast.Attribute(TRIGGER_ATTR, ast.AttrType.LONG),))


class TriggerRuntime(QueryPlan):
    def __init__(self, rt, td: ast.TriggerDefinition):
        self.rt = rt
        self.td = td
        self.name = f"#trigger_{td.id}"
        self.input_streams = ()
        self.output_target = td.id
        self.out_schema = trigger_schema(td.id)
        self._next: Optional[int] = None    # next fire time (ms), once anchored
        self._cron = None
        if td.at_cron is not None:
            from ..utils.cron import CronSchedule
            self._cron = CronSchedule(td.at_cron)

    # -- anchoring (reference: trigger.start() schedules the first fire) -----

    @property
    def anchored(self) -> bool:
        return self._next is not None

    def anchor(self, now_ms: int) -> None:
        if self.td.at_every_millis is not None:
            self._next = now_ms + self.td.at_every_millis
        elif self._cron is not None:
            self._next = self._cron.next_fire(now_ms)

    def fire_start(self, now_ms: int) -> list:
        """`at 'start'` fires exactly once when the runtime starts."""
        if not self.td.at_start:
            return []
        return [self._event_batch(now_ms)]

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        return []

    def next_wakeup(self) -> Optional[int]:
        return self._next

    def on_timer(self, now_ms: int) -> list:
        out = []
        guard = 0
        while self._next is not None and self._next <= now_ms:
            guard += 1
            if guard > 1_000_000:
                raise RuntimeError(f"trigger {self.td.id!r}: runaway catch-up")
            fire = self._next
            out.append(self._event_batch(fire))
            if self.td.at_every_millis is not None:
                self._next = fire + self.td.at_every_millis
            else:
                self._next = self._cron.next_fire(fire)
        return out

    def _event_batch(self, ts: int) -> OutputBatch:
        batch = EventBatch(
            self.out_schema,
            np.asarray([ts], dtype=TIMESTAMP_DTYPE),
            {TRIGGER_ATTR: np.asarray([ts], dtype=np.int64)}, 1)
        return OutputBatch(self.td.id, batch)

    # -- snapshot ------------------------------------------------------------

    def state_dict(self) -> dict:
        return {"next": self._next}

    def load_state_dict(self, d: dict) -> None:
        self._next = d.get("next")
