"""Parallel-in-time NFA plan families: associative-scan (SFA) + DFA/hybrid.

The sequential device kernel (nfa_device.NFAKernel) walks one event per
`lax.scan` step per lane: throughput is bounded by the T-long dependency
chain, not by math (BENCH_r05: ~0.01-0.02x the single-thread C++ roofline
on the P=1 pattern configs).  *Simultaneous Finite Automata* (arXiv
1405.0562) breaks that chain: simulate the automaton from EVERY state,
compose per-event transition functions associatively, and the whole
block collapses to log-depth scans.  First-match semantics make the
composed transition function DETERMINISTIC given a head event, so the
SFA composition factorizes into per-state primitives answered in
O(log T) each:

  * next-match pointers for statically-maskable transitions — a reverse
    `jax.lax.associative_scan` (min semiring) per chase node;
  * a vectorized perfect-segment-tree descent for *threshold*
    transitions — capture-dependent filters of the monotone comparison
    form `attr > f(earlier captures)` (the BENCH config-3/4 shape
    `e2.price > e1.price`), answered as "first index >= s whose masked
    value beats v" in O(log T) gathers per hop, batched over every
    pending instance at once;
  * rank/select over occurrence-count prefix sums for `<m:n>` count
    quantifiers — "the min-th occurrence after entry" is one segment
    tree query on the monotone cumulative-count array (the bit-packed
    state-SET lowering of arXiv 2210.10077 collapsed onto the counter
    lattice: the u32 frontier word's reachable set is an interval, so
    its boundary IS the rank);
  * forward prev-match scans (max semiring) for logical AND/OR partner
    pairs — "done" is the min (or) / max (and) of the two sides' first
    matches, captures re-resolve to the LAST side match at or before
    the done event, exactly like the sequential kernel's re-capturing
    station.

Two plan families are built on these primitives:

  * family "scan" — the SFA lowering above, O(S log T) depth.
  * family "dfa"  — NFA->DFA/hybrid lowering (arXiv 2210.10077) with
    state-set compaction and bit-packed transitions: the per-event
    chase-node masks pack into one u32 *symbol word* (bit k = event
    matches chase node k), blocks of STRIDE=4 events precompose into
    dense per-block transition tables (first-hit offsets for all
    chase nodes bit-packed into one u32 per block), and the block-level
    next pointers ride ONE associative scan over T/4 elements — a
    multi-stride dense table walk instead of per-event stepping
    (cf. 2209.05686, CAMA 2112.00267).  Threshold and count hops share
    the segment-tree machinery (the "hybrid" part).

Eligibility (classify_parallel) is strict and *sound*: anything outside
the supported algebra reports a reason string and the planner keeps the
sequential kernel (or the chunked-halo mode) — the families never guess.
The accepted algebra (byte-identical to the sequential kernel, asserted
by tests/test_plan_families.py):

  * linear chains of stream positions, within-bounded, `every` or
    single-arm (non-`every`) heads;
  * (1,1) positions with event-only filters plus at most one monotone
    threshold conjunct below the head;
  * `<m:n>` count quantifiers (min >= 1; unbounded max allowed except
    in the final position), event-only filters, incl. count heads and
    indexed capture reads (e1[0] / e1[last] / e1[last-1]);
  * logical AND/OR partner pairs of two stream nodes below the head,
    event-only filters (OR's unmatched side null-reconstructs through
    the presence rows, like the sequential kernel);
  * strict sequences (`,` succession): each hop reads the immediately
    next event, so capture-dependent filters are evaluated directly —
    arbitrary conjunctions allowed;
  * fused multi-query lanes (per-lane `__qparam` constants) and
    partitioned per-key lanes, both via ONE vmap of the flat block
    over the lane axis (pattern_plan ships (L, F) grids).

Cross-flush continuity reuses the chunked-halo harness in
pattern_plan.py: blocks are stateless, the last `within` window of
events replays at the next flush, and completions at or before the
previous flush's last seq are suppressed on device (per lane, for
partitioned grids).  Non-`every` chains additionally report a per-lane
resolution flag in the meta row so the host stops dispatching once the
single arm has definitively completed or died.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..query import ast
from .expr import (ExprError, compile_expression, compute_dtypes)
from .nfa_device import (ChainSpec, NFAKernel, _base_ref, _hi32, _lo32,
                         _I32, pow2_at_least)

STRIDE = 4                # dfa family: events per precomposed transition
_OFF_BITS = 3             # bits per packed first-hit offset (0..STRIDE)
NUMERIC = (ast.AttrType.INT, ast.AttrType.LONG,
           ast.AttrType.FLOAT, ast.AttrType.DOUBLE)
UNBOUNDED = 10 ** 9       # NFACompiler's normalization of <m:> counts
# single-arm (non-`every`) resolution flag, meta row slot 4
ARM_NONE, ARM_PENDING, ARM_RESOLVED = 0, 1, 2


class ParallelUnsupported(Exception):
    """Chain shape outside the parallel families' sound subset."""


@dataclass
class HopThreshold:
    """One monotone capture-dependent conjunct: own_col OP rhs(captures)."""
    own_key: str                  # "e2.price" — the arriving event's column
    op: str                       # "gt" | "ge" | "lt" | "le"
    rhs: object                   # CompiledExpr over earlier-ref captures
    own_type: ast.AttrType = ast.AttrType.DOUBLE


@dataclass
class HopNode:
    """One lowered stream node inside a chase position."""
    ref: str
    scode: int
    pre_conjs: list = field(default_factory=list)   # CompiledExpr, event-only
    threshold: Optional[HopThreshold] = None
    step_conjs: list = field(default_factory=list)  # sequence-mode direct eval

    @property
    def is_static(self) -> bool:
        return self.threshold is None and not self.step_conjs


@dataclass
class PPos:
    """One chain position lowered for the state chase."""
    kind: str                     # "single" | "count" | "logical"
    nodes: list                   # [HopNode]; 2 for logical
    within_ms: int = 0
    op: Optional[str] = None      # "and" | "or" (logical)
    min_count: int = 1
    max_count: int = 1


@dataclass
class ParallelProgram:
    positions: list               # [PPos], index = chain position
    stream_ids: list
    schemas: dict                 # ref -> StreamSchema
    ref_of: dict                  # ref -> (position index, node index)
    sequence: bool = False        # strict `,` succession
    single_arm: bool = False      # non-`every` head (one instance ever)

    @property
    def S(self) -> int:
        return len(self.positions)

    @property
    def count_refs(self) -> set:
        return {p.nodes[0].ref for p in self.positions if p.kind == "count"}


_FLIP = {"gt": "lt", "ge": "le", "lt": "gt", "le": "ge"}
_OPN = {ast.CompareOp.GT: "gt", ast.CompareOp.GE: "ge",
        ast.CompareOp.LT: "lt", ast.CompareOp.LE: "le"}


def _own_var(e, node, schemas) -> Optional[str]:
    """Attr name when `e` is a plain Variable over the node's OWN event
    (qualified with its ref, or unqualified resolving to its schema —
    PatternFilterContext resolution order), else None."""
    if not isinstance(e, ast.Variable) or e.index is not None:
        return None
    if e.stream_ref == node.ref:
        return e.attribute
    if e.stream_ref is None and e.attribute in schemas[node.ref].types:
        return e.attribute
    return None


def lower_parallel(spec: ChainSpec, strings,
                   param_extra: Optional[dict] = None) -> ParallelProgram:
    """Lower a ChainSpec into a state-chase program, or raise
    ParallelUnsupported with the (human-readable) ineligibility reason.
    See the module docstring for the accepted algebra."""
    if spec.S < 2:
        raise ParallelUnsupported("single-position chain (no scan depth)")
    sequence = bool(spec.is_sequence)
    single_arm = not spec.every_head
    positions: list = []
    ref_of: dict = {}
    count_refs: set = set()
    or_refs: set = set()
    S = spec.S
    for pi, pos in enumerate(spec.positions):
        for n in pos.nodes:
            if n.kind != "stream":
                raise ParallelUnsupported("absent (`not ... for`) position")
        if pos.sticky and pi > 0:
            raise ParallelUnsupported("`every` below the head")
        if pos.within_ms is None:
            raise ParallelUnsupported(
                "position without a `within` bound (stateless tail replay "
                "needs a finite horizon)")
        if pos.op is not None:
            if pi == 0:
                raise ParallelUnsupported("logical and/or head")
            if sequence:
                raise ParallelUnsupported(
                    "logical and/or position in a strict sequence")
            if pi > 0 and spec.positions[pi - 1].is_count:
                raise ParallelUnsupported("logical position after a count "
                                          "(no station to consume the arm)")
            nodes = []
            for n in pos.nodes:
                if n.step_conjs:
                    raise ParallelUnsupported(
                        "capture-dependent filter on a logical position")
                nodes.append(HopNode(n.ref, n.scode, list(n.pre_conjs)))
            pp = PPos("logical", nodes, pos.within_ms, op=pos.op)
            if pos.op == "or":
                or_refs.update(n.ref for n in pos.nodes)
        elif pos.is_count:
            if sequence:
                raise ParallelUnsupported(
                    "count quantifier in a strict sequence")
            if pos.min_count < 1:
                raise ParallelUnsupported(
                    "optional count quantifier (min 0 arms on entry)")
            if pi > 0 and spec.positions[pi - 1].is_count:
                raise ParallelUnsupported("adjacent count positions")
            if pi == S - 1 and (pos.max_count >= UNBOUNDED
                                or pos.max_count - pos.min_count + 1 > 8):
                raise ParallelUnsupported(
                    "unbounded or wide count in the final position "
                    "(one emission lane per allowed occurrence)")
            n = pos.nodes[0]
            if n.step_conjs:
                raise ParallelUnsupported(
                    "capture-dependent filter on a count position")
            pp = PPos("count", [HopNode(n.ref, n.scode, list(n.pre_conjs))],
                      pos.within_ms, min_count=pos.min_count,
                      max_count=pos.max_count)
            count_refs.add(n.ref)
        else:
            n = pos.nodes[0]
            hop = HopNode(n.ref, n.scode, list(n.pre_conjs))
            if n.step_conjs:
                if pi == 0:
                    raise ParallelUnsupported("head filter reads captures")
                if sequence:
                    # the strict next event is KNOWN (j+1): evaluate the
                    # conjunction directly, no monotonicity needed
                    hop.step_conjs = list(n.step_conjs)
                    _check_step_reads(n.step_conjs, n.ref, ref_of,
                                      count_refs, param_extra)
                else:
                    if len(n.step_conjs) > 1:
                        raise ParallelUnsupported(
                            "multiple capture-dependent conjuncts on one "
                            "position (first-match of a conjunction is not "
                            "decomposable)")
                    hop.threshold = _lower_threshold(
                        n, n.step_asts[0], spec, strings, param_extra,
                        ref_of, count_refs, or_refs)
            pp = PPos("single", [hop], pos.within_ms)
        positions.append(pp)
        for ni, hn in enumerate(pp.nodes):
            ref_of[hn.ref] = (pi, ni)
    return ParallelProgram(positions, list(spec.stream_ids),
                           dict(spec.schemas), ref_of, sequence=sequence,
                           single_arm=single_arm)


def _check_step_reads(step_conjs, own_ref, ref_of, count_refs, param_extra):
    """Sequence-mode step conjuncts: reads must be the own event's
    columns, earlier FROZEN captures, params, or __timestamp__."""
    for ce in step_conjs:
        for k in ce.reads:
            if k == "__timestamp__" or (param_extra and k in param_extra):
                continue
            if "." not in k:
                raise ParallelUnsupported(
                    f"step filter reads non-capture key {k!r}")
            base = _base_ref(k.split(".", 1)[0])[0]
            if base == own_ref:
                continue
            if base in count_refs:
                raise ParallelUnsupported(
                    "step filter reads a still-collecting count capture")
            if base not in ref_of:
                raise ParallelUnsupported(
                    f"step filter reads unresolved key {k!r}")


def _lower_threshold(node, cond, spec, strings, param_extra,
                     ref_of, count_refs, or_refs=()) -> HopThreshold:
    """`own.attr OP expr(earlier captures)` -> HopThreshold, else raise."""
    from .nfa_device import PatternFilterContext
    if not isinstance(cond, ast.Compare) or cond.op not in _OPN:
        raise ParallelUnsupported(
            "capture-dependent filter is not a <,<=,>,>= comparison")
    own_l = _own_var(cond.left, node, spec.schemas)
    own_r = _own_var(cond.right, node, spec.schemas)
    if (own_l is None) == (own_r is None):
        raise ParallelUnsupported(
            "comparison must have the arriving event's attribute on "
            "exactly one side")
    attr = own_l if own_l is not None else own_r
    op = _OPN[cond.op] if own_l is not None else _FLIP[_OPN[cond.op]]
    own_t = spec.schemas[node.ref].type_of(attr)
    if own_t not in NUMERIC:
        raise ParallelUnsupported(
            f"threshold attribute {attr!r} is not numeric")
    rhs_ast = cond.right if own_l is not None else cond.left
    ctx = PatternFilterContext(spec.schemas, strings, node.ref)
    if param_extra:
        ctx.extra = dict(param_extra)
    try:
        rhs = compile_expression(rhs_ast, ctx)
    except ExprError as e:
        raise ParallelUnsupported(f"threshold rhs not compilable: {e}")
    if rhs.type not in NUMERIC:
        raise ParallelUnsupported("threshold rhs is not numeric")
    ok_reads = set()
    for r in ref_of:
        for a in spec.schemas[r].attributes:
            ok_reads.add(f"{r}.{a.name}")
    if param_extra:
        ok_reads.update(param_extra)
    bad = set(rhs.reads) - ok_reads
    if bad:
        raise ParallelUnsupported(
            f"threshold rhs reads non-capture keys {sorted(bad)!r} "
            f"(own event / timestamp / later positions)")
    for k in rhs.reads:
        if "." not in k:
            continue
        base = _base_ref(k.split(".", 1)[0])[0]
        if base in count_refs:
            raise ParallelUnsupported(
                "threshold rhs reads a still-collecting count capture")
        if base in or_refs:
            raise ParallelUnsupported(
                "threshold rhs reads a maybe-absent `or` capture")
    return HopThreshold(f"{node.ref}.{attr}", op, rhs, own_t)


def classify_parallel(spec: ChainSpec, kernel: NFAKernel, strings,
                      param_extra: Optional[dict] = None) -> dict:
    """{'scan': True|reason, 'dfa': True|reason} for one lowered chain.
    A True value means the family is sound for this ChainSpec; a string
    is the ineligibility reason (surfaced in statistics() and asserted
    by the forced-fallback tests)."""
    try:
        prog = lower_parallel(spec, strings, param_extra)
        count_refs = prog.count_refs
        logical_refs = {n.ref for p in prog.positions
                        if p.kind == "logical" for n in p.nodes}
        for ce in (list(kernel.sel_fns.values())
                   + ([kernel.having] if kernel.having else [])):
            is_having = kernel.having is not None and ce is kernel.having
            for k in ce.reads:
                if "." not in k or k.startswith("__"):
                    continue
                refpart = k.split(".", 1)[0]
                base, cidx = _base_ref(refpart)
                if cidx is not None:
                    if base in count_refs and (
                            cidx in ("last", "last-1") or cidx.isdigit()):
                        pass            # rank/select-resolvable
                    elif cidx == "last" and base in prog.ref_of:
                        pass            # [last] over a (1,1) ref == plain
                    else:
                        raise ParallelUnsupported(
                            f"indexed capture read {k!r} outside a count "
                            f"position")
                if is_having and base in logical_refs:
                    raise ParallelUnsupported(
                        "having reads a capture of a logical (maybe-"
                        "absent) position")
    except ParallelUnsupported as e:   # lint: allow-swallow (the reason
        # string IS the demotion record — the planner surfaces it via
        # plan.families / rt.explain())
        return {"scan": str(e), "dfa": str(e)}
    return _classify_prog(prog)


def _chase_lanes(prog: ParallelProgram) -> list:
    """Static chase nodes (pi, ni) that resolve via next-match pointers —
    the dfa family's bit-packable symbol lanes.  Count positions resolve
    via rank/select and threshold hops via the segment tree; neither
    consumes a symbol bit."""
    lanes = []
    for pi, pos in enumerate(prog.positions):
        if pi == 0:
            continue
        if pos.kind == "single" and pos.nodes[0].is_static:
            lanes.append((pi, 0))
        elif pos.kind == "logical":
            lanes.extend((pi, ni) for ni in range(len(pos.nodes)))
    return lanes


def _classify_prog(prog: ParallelProgram) -> dict:
    """Family verdicts for a successfully-lowered chase program (shared
    between the built-kernel classifier above and the analysis-time
    classify_shape below)."""
    out = {"scan": True}
    lanes = _chase_lanes(prog)
    if prog.sequence:
        out["dfa"] = ("strict sequence (consecutive-event steps leave "
                      "nothing to bit-pack)")
    elif len(lanes) > 8:
        out["dfa"] = ("more than 8 positions (symbol words bit-pack one "
                      "position per u32 lane bit)")
    elif not lanes:
        out["dfa"] = ("no static transition to bit-pack (every hop is "
                      "threshold- or count-dependent)")
    else:
        out["dfa"] = True
    return out


def classify_shape(state_input, schemas, strings,
                   partitioned: bool = False) -> dict:
    """Analysis-time family eligibility for a raw AST pattern input:
    {'chunk'|'scan'|'dfa': True | reason} with the SAME reason strings
    classify_parallel reports for a built kernel — computable without
    constructing a device plan.  Used by the static analyzer's
    annotation-conflict rule (SA08, docs/ANALYSIS.md) so a forced
    `@app:patternFamily` on a provably ineligible shape is flagged at
    analysis time, before a deploy quietly falls back.

    `schemas` maps stream id -> StreamSchema for every stream the
    pattern consumes; a shape the device chain lowering itself rejects
    reports that reason for every family.  `partitioned` applies the
    per-key lane-vmap gates pattern_plan applies for patterns inside a
    `partition with (...)` block."""
    from ..interp.engine import _collect_filters
    from .nfa_device import lower_chain
    try:
        spec = lower_chain(state_input, schemas, strings,
                           _collect_filters(state_input.state))
    except Exception as e:   # lint: allow-swallow (reason IS the record)
        r = f"device chain lowering unavailable: {e}"
        return {"chunk": r, "scan": r, "dfa": r}
    # the stateless-harness gates DevicePatternPlan applies before any
    # family runs (pattern_plan.py "plan-family selection")
    base = True
    if any(n.kind != "stream" for n in spec.all_nodes) \
            or spec.needs_init_slot:
        base = "absent state (timer-driven deadlines need device state)"
    elif not all(p.within_ms is not None for p in spec.positions):
        base = "position without a `within` bound"
    if base is not True:
        return {"chunk": base, "scan": base, "dfa": base}
    if partitioned:
        out = {"chunk": "partitioned (the lane axis holds partition keys)"}
    elif not spec.every_head:
        out = {"chunk": "non-`every` head (single stateful arm)"}
    else:
        out = {"chunk": True}
    try:
        prog = lower_parallel(spec, strings)
        out.update(_classify_prog(prog))
        if partitioned and prog.single_arm:
            r = ("non-`every` head with partitioned lanes (per-key "
                 "single-arm state)")
            out.update({"scan": r, "dfa": r})
    except ParallelUnsupported as e:   # lint: allow-swallow (reason IS
        # the analysis-time record)
        out.update({"scan": str(e), "dfa": str(e)})
    return out


# ---------------------------------------------------------------------------
# vectorized "first index >= s with masked value OP v" primitives
# ---------------------------------------------------------------------------

def _sentinel(dt, agg: str):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.array(-jnp.inf if agg == "max" else jnp.inf, dt)
    info = jnp.iinfo(dt)
    return jnp.array(info.min if agg == "max" else info.max, dt)


def _tree_dtype(own_dt, rhs_dt):
    """Dtype the threshold tree aggregates (and compares) in: the
    promotion of both comparison sides, with int32 widened to int64 so
    the sentinel sits strictly OUTSIDE the value range — `>=`/`<=` hit
    checks must never be satisfiable by a masked-out leaf (an int32
    column whose rhs equals INT32_MIN would otherwise match them).
    Mixed int/float comparisons promote to the float side, whose ±inf
    sentinels are strictly outside every value, and whose rounding then
    matches the sequential kernel's own promoted per-event compare."""
    dt = jnp.promote_types(own_dt, rhs_dt)
    if dt == jnp.int32:
        return jnp.dtype(jnp.int64)
    return dt


def _build_heap(vals, mask, L: int, agg: str, dt):
    """Perfect binary segment tree in heap layout (1-based; leaves at
    [L, 2L)).  Built with log2(L) vectorized reductions — the SFA
    transition-composition tree for threshold hops.  Masked-out and NaN
    leaves are replaced by the sentinel BEFORE aggregation: the
    sequential kernel evaluates the predicate per event (NaN compares
    False there), while jnp.maximum/minimum would propagate a NaN to
    every ancestor and poison whole subtrees."""
    sent = _sentinel(dt, agg)
    keep = mask
    if jnp.issubdtype(vals.dtype, jnp.floating):
        keep = keep & ~jnp.isnan(vals)
    vals = jnp.where(keep, vals.astype(dt), sent)
    red = jnp.maximum if agg == "max" else jnp.minimum
    lvl = jnp.full((L,), sent, dt).at[:vals.shape[0]].set(vals)
    levels = [lvl]
    while lvl.shape[0] > 1:
        lvl = red(lvl[0::2], lvl[1::2])
        levels.append(lvl)
    # heap[1]=root ... heap[L:2L)=leaves; heap[0] unused (sentinel)
    return jnp.concatenate([jnp.full((1,), sent, dt)]
                           + [lv for lv in reversed(levels)])


def _first_hit(heap, L: int, s, v, op: str):
    """First leaf index >= s whose value satisfies OP v; L when none.
    Vectorized over query arrays s, v; 2*log2(L) gather rounds total
    (up-walk decomposing [s, L) into aligned blocks visited left to
    right, then a descent into the first qualifying subtree).

    Hit checks are sentinel-safe: `>=`/`<=` rewrite to strict compares
    against the adjacent representable value in the tree dtype (exact —
    int32 trees are widened, floats use nextafter; an infinite rhs
    meeting infinite data, or an int64 rhs of exactly INT64_MIN, are
    the accepted pathological corners)."""
    va = jnp.asarray(v, heap.dtype)
    if op == "ge":
        v = jnp.nextafter(va, jnp.array(-jnp.inf, heap.dtype)) \
            if jnp.issubdtype(heap.dtype, jnp.floating) else va - 1
        op = "gt"
    elif op == "le":
        v = jnp.nextafter(va, jnp.array(jnp.inf, heap.dtype)) \
            if jnp.issubdtype(heap.dtype, jnp.floating) else va + 1
        op = "lt"
    else:
        v = va
    cmp = {"gt": lambda a, b: a > b,
           "lt": lambda a, b: a < b}[op]
    P = max(L.bit_length() - 1, 0)

    # fori_loop (not an unrolled python loop): the round count is static
    # but the body is identical each round, and unrolling 2*log2(L)
    # gather rounds made the XLA program ~4x slower to COMPILE — which
    # dominates small deployments (every pattern test runtime pays it)
    def up(i, st):
        l, found, fnode = st
        r = jnp.int32(2 * L) >> i
        odd = (l & 1) == 1
        nv = heap[jnp.clip(l, 0, 2 * L - 1)]
        take = odd & (l < r) & cmp(nv, v) & ~found
        fnode = jnp.where(take, l, fnode)
        found = found | take
        return ((l + odd.astype(_I32)) >> 1, found, fnode)

    l0 = (jnp.clip(s, 0, L) + L).astype(_I32)
    _l, found, fnode = lax.fori_loop(
        0, P + 1, up, (l0, jnp.zeros(l0.shape, bool),
                       jnp.zeros(l0.shape, _I32)))

    def down(_i, fnode):
        internal = found & (fnode < L)
        left = 2 * fnode
        lv = heap[jnp.clip(left, 0, 2 * L - 1)]
        goleft = cmp(lv, v)
        return jnp.where(internal,
                         jnp.where(goleft, left, left + 1), fnode)

    fnode = lax.fori_loop(0, P, down, fnode)
    return jnp.where(found, fnode - L, L).astype(_I32)


def _next_static_scan(mask, L: int):
    """next[t] = first index >= t with mask set (L = none): ONE reverse
    associative scan in the min semiring — the SFA composition of
    per-event transition functions restricted to a static position."""
    F = mask.shape[0]
    idx = jnp.where(mask, jnp.arange(F, dtype=_I32), jnp.int32(L))
    return lax.associative_scan(jnp.minimum, idx, reverse=True)


def _prev_static_scan(mask):
    """prev[t] = LAST index <= t with mask set (-1 = none): one forward
    associative scan in the max semiring — resolves the sequential
    kernel's re-capturing logical stations (capture = last side match
    at or before the pair's done event)."""
    F = mask.shape[0]
    idx = jnp.where(mask, jnp.arange(F, dtype=_I32), jnp.int32(-1))
    return lax.associative_scan(jnp.maximum, idx)


# ---------------------------------------------------------------------------
# the block kernel
# ---------------------------------------------------------------------------

class ParallelChainKernel:
    """Stateless flat-block kernel for one lowered chain, in either the
    "scan" (pure SFA) or "dfa" (bit-packed multi-stride hybrid) family.

    Mirrors NFAKernel's packed-output contract exactly (meta row, valid
    row under `having`, out_names/out_dtypes from the plan's NFAKernel)
    so DevicePatternPlan's unpack consumes both interchangeably.
    Blocks carry no device state: ev is the chunked-halo flat layout
    (`__flat.*` arrays + `__nev__`/`__prev_seq__`/bases) minus the lane
    geometry — the whole flush is ONE log-depth program.  block_fn
    accepts T as an int (flat block) or an (L, F) tuple (ONE jax.vmap
    of the flat block over the lane axis: partitioned per-key grids and
    fused multi-query lanes — per-lane leaves map on axis 0, shared
    scalars broadcast)."""

    def __init__(self, prog: ParallelProgram, nfak: NFAKernel,
                 family: str = "scan"):
        assert family in ("scan", "dfa")
        self.prog = prog
        self.nfak = nfak              # selector/having/output metadata
        self.family = family
        self.f64 = nfak.f64
        self._mode = nfak._mode
        self._block_cache: dict = {}

    # NFAKernel-compatible surface consumed by _call_block / bench
    def block_fn(self, T, M: int):
        key = (T, M)
        fn = self._block_cache.get(key)
        if fn is None:
            if isinstance(T, tuple):
                fn = jax.jit(self._make_lane_block(M))
            else:
                fn = jax.jit(self._make_block(M))
            self._block_cache[key] = fn
        return fn

    def _make_block(self, M: int):
        def block(state, ev):
            with compute_dtypes(self._mode):
                return state, self._block_impl(ev, M)
        return block

    def _make_lane_block(self, M: int):
        """vmap the flat block over the lane axis: per-lane leaves (lane-
        major grids, per-lane scalars, params, qids) map on axis 0;
        shared leaves (bases, broadcast event arrays in fused mode)
        replicate."""
        def lane_block(state, ev):
            shared_nd = {"__base_ts__": 0, "__base_seq__": 0}
            axes = {}
            for k, v in ev.items():
                if k in shared_nd:
                    axes[k] = None
                elif k.startswith("__flat."):
                    axes[k] = 0 if v.ndim == 2 else None
                else:               # __nev__/__prev_seq__/__param.*/...
                    axes[k] = 0 if v.ndim >= 1 else None

            def one(e):
                with compute_dtypes(self._mode):
                    return self._block_impl(e, M)
            return state, jax.vmap(one, in_axes=(axes,))(ev)
        return lane_block

    # -- mask/env helpers -----------------------------------------------

    def _param_env(self, ev) -> dict:
        """Per-lane lifted constants (fused multi-query mode): scalars
        under the lane vmap, named exactly like NFAKernel.params."""
        return {k[len("__param."):]: v for k, v in ev.items()
                if k.startswith("__param.")}

    def _flat_env(self, ev, node: HopNode, ts, base_ts) -> dict:
        env = self._param_env(ev)
        for a in self.prog.schemas[node.ref].attributes:
            key = f"__flat.{node.scode}.{a.name}"
            if key in ev:
                env[f"{node.ref}.{a.name}"] = ev[key]
        env["__timestamp__"] = base_ts + ts.astype(jnp.int64)
        return env

    def _node_mask(self, ev, node: HopNode, ts, valid, base_ts):
        m = valid
        if len(self.prog.stream_ids) > 1:
            m = m & (ev["__flat.__scode__"] == node.scode)
        if node.pre_conjs:
            env = self._flat_env(ev, node, ts, base_ts)
            for ce in node.pre_conjs:
                m = m & jnp.broadcast_to(ce.fn(env), m.shape)
        return m

    def _gather_env(self, ev, idx_of: dict, keys, F: int, base_ts,
                    comp_j=None) -> dict:
        """Capture env gathered at resolved indices: key "r.attr" (or
        "r[i].attr") -> flat column at idx_of[refpart] (clipped; callers
        mask validity downstream).  `keys` bounds the gathers to what's
        read.  idx_of maps refpart -> index array (per-head or
        per-match, caller's choice)."""
        env = self._param_env(ev)
        for k in keys:
            if k == "__timestamp__":
                if comp_j is not None:
                    env[k] = base_ts + ev["__flat.__ts__"][comp_j] \
                        .astype(jnp.int64)
                continue
            if "." not in k or k.startswith("__"):
                continue
            refpart, attr = k.split(".", 1)
            base = _base_ref(refpart)[0]
            idx = idx_of.get(refpart, idx_of.get(base))
            if idx is None:
                continue
            pn = self.prog.ref_of.get(base)
            if pn is None:
                continue
            scode = self.prog.positions[pn[0]].nodes[pn[1]].scode
            col = ev.get(f"__flat.{scode}.{attr}")
            if col is None:
                continue
            env[k] = col[jnp.clip(idx, 0, F - 1)]
        return env

    # -- dfa family: bit-packed multi-stride static tables ----------------

    def _dfa_tables(self, lane_masks: list, F: int, L: int):
        """Precompose per-event symbol words into stride-4 block tables.
        lane_masks: one (F,) mask per chase node (symbol bit).  Returns
        (suffix_flat per lane, packed first-offset words, block-level
        next pointers per lane, NB)."""
        B = STRIDE
        NB = -(-F // B)
        Fp = NB * B
        lanes = range(len(lane_masks))
        # ONE u32 symbol word per event: bit k = matches chase node k
        sym = jnp.zeros((Fp,), jnp.uint32)
        for k in lanes:
            mk = jnp.zeros((Fp,), bool).at[:F].set(lane_masks[k])
            sym = sym | (mk.astype(jnp.uint32) << np.uint32(k))
        o = jnp.arange(B, dtype=_I32)[None, :]
        suffix = {}
        first = {}
        for k in lanes:
            bits = ((sym.reshape(NB, B) >> np.uint32(k)) & 1) != 0
            offs = jnp.where(bits, o, jnp.int32(B))
            # in-block suffix-first offsets (stride-4: 3 dense mins)
            acc = offs[:, B - 1]
            cols = [acc]
            for c in range(B - 2, -1, -1):
                acc = jnp.minimum(offs[:, c], acc)
                cols.append(acc)
            suf = jnp.stack(list(reversed(cols)), axis=1)   # (NB, B)
            suffix[k] = suf.reshape(-1)
            first[k] = suf[:, 0]
        # per-block transition table: first-hit offsets for ALL chase
        # nodes bit-packed into one u32 word per block
        packed = jnp.zeros((NB,), jnp.uint32)
        for k in lanes:
            packed = packed | (first[k].astype(jnp.uint32)
                               << np.uint32(_OFF_BITS * k))
        # block-level next pointers: one associative scan over F/4
        # elements per chase node (stacked -> a single scan call)
        if lane_masks:
            blk = jnp.stack(
                [jnp.where(first[k] < B,
                           jnp.arange(NB, dtype=_I32), jnp.int32(NB))
                 for k in lanes], axis=1)
            nblk = lax.associative_scan(jnp.minimum, blk, reverse=True,
                                        axis=0)
            nblk = {k: nblk[:, i] for i, k in enumerate(lanes)}
        else:
            nblk = {}
        return suffix, packed, nblk, NB

    def _dfa_next(self, k: int, s, suffix, packed, nblk, NB: int, L: int):
        """Multi-stride lookup: in-block suffix table, then the packed
        block-transition word of the next block containing a hit."""
        B = STRIDE
        Fp = NB * B
        sc = jnp.clip(s, 0, Fp - 1)
        inb = suffix[k][sc]                      # first o >= s%B in block
        b = sc >> 2
        j_in = (b << 2) + inb
        b2 = nblk[k][jnp.clip(b + 1, 0, NB - 1)]
        ok2 = (b + 1 < NB) & (b2 < NB)
        f2 = ((packed[jnp.clip(b2, 0, NB - 1)]
               >> (jnp.uint32(_OFF_BITS * k))) & jnp.uint32(7)).astype(_I32)
        j_blk = (b2 << 2) + f2
        j = jnp.where(inb < B, j_in, jnp.where(ok2, j_blk, jnp.int32(L)))
        return jnp.where(s < Fp, j, jnp.int32(L)).astype(_I32)

    # -- the block --------------------------------------------------------

    def _block_impl(self, ev, M: int):
        prog, nfak = self.prog, self.nfak
        S = prog.S
        F = ev["__flat.__ts__"].shape[0]
        L = pow2_at_least(F, lo=2)
        nev = ev["__nev__"].astype(_I32)
        prev_seq = ev["__prev_seq__"]
        base_ts = ev["__base_ts__"]
        ts = ev["__flat.__ts__"]
        # scan/dfa flushes always ship the explicit seq array (output
        # events consume global seqs, so derived-consecutive seqs would
        # force a second structural compile at flush 2)
        seq = ev["__flat.__seq__"]
        valid = jnp.arange(F, dtype=_I32) < nev
        nmask = {(pi, ni): self._node_mask(ev, n, ts, valid, base_ts)
                 for pi, pos in enumerate(prog.positions)
                 for ni, n in enumerate(pos.nodes)}

        chase = _chase_lanes(prog) if self.family == "dfa" else []
        if chase:
            lane_of = {pn: k for k, pn in enumerate(chase)}
            suffix, packed, nblk, NB = self._dfa_tables(
                [nmask[pn] for pn in chase], F, L)

        scan_next: dict = {}

        def nxt(pi, ni, s):
            """First index >= s matching chase node (pi, ni); L if none."""
            if chase and (pi, ni) in lane_of:
                return self._dfa_next(lane_of[(pi, ni)], s, suffix,
                                      packed, nblk, NB, L)
            key = (pi, ni)
            if key not in scan_next:
                scan_next[key] = _next_static_scan(nmask[key], L)
            nx = scan_next[key]
            return jnp.where(s < F, nx[jnp.clip(s, 0, F - 1)],
                             jnp.int32(L))

        # occurrence ranks per count position: inclusive cumulative match
        # count + a segment tree over it — "the r-th occurrence after
        # entry" is ONE monotone first-hit query (rank/select), so count
        # minima and capture indices never iterate
        ranks: dict = {}
        rank_heaps: dict = {}
        for pi, pos in enumerate(prog.positions):
            if pos.kind != "count":
                continue
            r = jnp.cumsum(nmask[(pi, 0)].astype(_I32), dtype=_I32)
            ranks[pi] = r
            rank_heaps[pi] = _build_heap(r, valid, L, "max",
                                         jnp.dtype(jnp.int64))

        def select(pi, s, r):
            """First index >= s whose inclusive occurrence rank >= r."""
            return _first_hit(rank_heaps[pi], L, s, r, "ge")

        # expiry heap: the sequential kernel expires a waiting instance
        # on the FIRST arriving event whose age exceeds the position's
        # `within` horizon — matching or not (nfa_device._step computes
        # `expired` before the match mask, over timey=valid).  With
        # out-of-order timestamps a later event can carry a REGRESSED
        # ts, so checking the matched event alone would resurrect
        # instances the sequential kernel killed.  i64 aggregation:
        # ts offsets reach ±2^30 and ts+W must not wrap i32.
        ts_heap = _build_heap(ts, valid, L, "max", jnp.dtype(jnp.int64))
        ts64 = ts.astype(jnp.int64)

        def killer(s, within_ms):
            """First event at or after s past the head's `within` horizon
            (per-head v = head ts + W; queries indexed by head)."""
            return _first_hit(ts_heap, L, s, ts64 + jnp.int64(within_ms),
                              "gt")

        def threshold_next(hop: HopNode, s, idx_of):
            th = hop.threshold
            agg = "max" if th.op in ("gt", "ge") else "min"
            own = ev[f"__flat.{hop.scode}.{th.own_key.split('.', 1)[1]}"]
            env = self._gather_env(ev, idx_of, th.rhs.reads, F, base_ts)
            v = jnp.broadcast_to(th.rhs.fn(env), (F,))
            dt = _tree_dtype(own.dtype, v.dtype)
            heap = _build_heap(own, nmask[self.prog.ref_of[hop.ref]], L,
                               agg, dt)
            return _first_hit(heap, L, s, v, th.op)

        # ---- the state chase: every event index is a candidate head ----
        j0 = jnp.arange(F, dtype=_I32)
        head = prog.positions[0]
        ok = nmask[(0, 0)]
        dead = jnp.zeros((F,), bool)    # definitive failure (single-arm)
        idx_of = {}                     # refpart -> per-head value index
        pres_of = {}                    # refpart -> per-head presence bool
        count_ctx = {}                  # pi -> (s_occ, ra) occurrence base
        pend_count = None               # (pi, entry) awaiting its advance
        j = j0

        def step_fail(alive, kl, jn):
            """Advance-step outcome: (still_ok, definitively_dead).
            Dead = the killer event exists in-block and the match did not
            land before it; not-found with no killer stays pending."""
            good = jn < kl
            return alive & good, alive & ~good & (kl < F)

        if head.kind == "count":
            # the arming event IS occurrence 1 (host _alloc_head): the
            # rank base excludes it, the select starts AT the head
            ra = ranks[0][j0] - 1
            count_ctx[0] = (j0, ra)
            jmin = select(0, j0, ra + jnp.int32(head.min_count))
            kl = killer(j0 + 1, head.within_ms)
            if S > 1:
                ok, d = step_fail(ok, kl, jmin)
                dead = dead | d
                pend_count = (0, head)
                j = jnp.clip(jmin, 0, F - 1)
        else:
            idx_of[head.nodes[0].ref] = j0

        final_count = prog.positions[S - 1].kind == "count"

        for pi in range(1, S):
            pos = prog.positions[pi]
            if pos.kind == "single":
                hop = pos.nodes[0]
                s = j + 1
                if not prog.sequence:
                    if pend_count is not None:
                        # the successor consumes the armed count: the
                        # station never waits AT this position, so the
                        # COUNT's within (anchored at the head) bounds
                        # this advance and the successor's own never
                        # applies (host parity: at_pos is never true for
                        # a count's successor)
                        _cpi, cpos = pend_count
                        kl = killer(s, cpos.within_ms)
                        pend_count = None
                    else:
                        kl = killer(s, pos.within_ms)
                if prog.sequence:
                    # strict succession: the hop consumes EXACTLY the
                    # next valid event — mask/filter/expiry all resolve
                    # by direct gather at s
                    sc = jnp.clip(s, 0, F - 1)
                    m = nmask[(pi, 0)][sc]
                    if hop.step_conjs:
                        senv = self._gather_env(ev, idx_of, set().union(
                            *[ce.reads for ce in hop.step_conjs]), F,
                            base_ts)
                        for a in prog.schemas[hop.ref].attributes:
                            col = ev.get(f"__flat.{hop.scode}.{a.name}")
                            if col is not None:
                                senv[f"{hop.ref}.{a.name}"] = col[sc]
                        senv["__timestamp__"] = base_ts \
                            + ts64[sc]
                        for ce in hop.step_conjs:
                            m = m & jnp.broadcast_to(ce.fn(senv), m.shape)
                    expired = ts64[sc] > ts64[j0] \
                        + jnp.int64(pos.within_ms)
                    have = s < nev
                    jn = jnp.where(have & m & ~expired, s, jnp.int32(L))
                    dead = dead | (ok & have & (expired | ~m))
                    ok = ok & (jn < F)
                elif hop.threshold is not None:
                    jn = threshold_next(hop, s, idx_of)
                    ok, d = step_fail(ok, kl, jn)
                    dead = dead | d
                else:
                    jn = nxt(pi, 0, s)
                    ok, d = step_fail(ok, kl, jn)
                    dead = dead | d
                j = jnp.clip(jn, 0, F - 1)
                idx_of[hop.ref] = j
            elif pos.kind == "logical":
                s = j + 1
                jl = nxt(pi, 0, s)
                jr = nxt(pi, 1, s)
                if pos.op == "or":
                    jd = jnp.minimum(jl, jr)
                else:
                    jd = jnp.where((jl < F) & (jr < F),
                                   jnp.maximum(jl, jr), jnp.int32(L))
                kl = killer(s, pos.within_ms)
                ok, d = step_fail(ok, kl, jd)
                dead = dead | d
                jdc = jnp.clip(jd, 0, F - 1)
                for ni, n in enumerate(pos.nodes):
                    jside = jl if ni == 0 else jr
                    if pos.op == "or":
                        # winner captures its own first match; loser is
                        # absent (presence row nulls it host-side)
                        idx_of[n.ref] = jnp.clip(jside, 0, F - 1)
                        pres_of[n.ref] = jside == jd
                    else:
                        # AND stations re-capture while waiting: the
                        # emitted value is the LAST side match at or
                        # before the done event
                        pv = _prev_static_scan(nmask[(pi, ni)])
                        idx_of[n.ref] = jnp.clip(pv[jdc], 0, F - 1)
                        pres_of[n.ref] = jnp.ones((F,), bool)
                j = jdc
            else:                       # count (non-head entry)
                entry = j
                ra = ranks[pi][entry]   # entry event is NOT an occurrence
                count_ctx[pi] = (entry + 1, ra)
                if pi < S - 1:
                    jmin = select(pi, entry + 1,
                                  ra + jnp.int32(pos.min_count))
                    kl = killer(entry + 1, pos.within_ms)
                    ok, d = step_fail(ok, kl, jmin)
                    dead = dead | d
                    pend_count = (pi, pos)
                    j = jnp.clip(jmin, 0, F - 1)

        # ---- emission candidates --------------------------------------
        if final_count:
            fpos = prog.positions[S - 1]
            s_occ, ra = count_ctx[S - 1]
            kl = killer(s_occ, fpos.within_ms)
            C = fpos.max_count - fpos.min_count + 1
            lvs, comps = [], []
            for c in range(fpos.min_count, fpos.max_count + 1):
                jc = select(S - 1, s_occ, ra + jnp.int32(c))
                lvs.append(ok & (jc < kl))
                comps.append(jnp.clip(jc, 0, F - 1))
            lv_all = jnp.stack(lvs)                 # (C, F)
            comp_all = jnp.stack(comps)
            # single-arm resolution: parked at max, or dead
            resolved = dead | lvs[-1]
        else:
            C = 1
            lv_all = ok[None, :]
            comp_all = j[None, :]
            resolved = dead | ok

        # dedup: completions at or before the previous flush's last seq
        # are tail replays — suppressed on device, per lane
        lv_all = lv_all & (seq[comp_all] > prev_seq.astype(_I32))

        arm_flag = jnp.int32(0)
        if prog.single_arm:
            # ONE instance ever: the first head match arms it; everything
            # else never existed.  The meta flag tells the host whether
            # the arm is still pending (keep dispatching) or resolved.
            hm = nmask[(0, 0)]
            h0 = jnp.min(jnp.where(hm, j0, jnp.int32(F)))
            lv_all = lv_all & (j0[None, :] == h0)
            arm_off = ev.get("__arm_done__")
            if arm_off is not None:
                lv_all = lv_all & (arm_off.astype(_I32) == 0)
            has_head = h0 < F
            r0 = resolved[jnp.clip(h0, 0, F - 1)]
            arm_flag = jnp.where(
                has_head,
                jnp.where(r0, jnp.int32(ARM_RESOLVED),
                          jnp.int32(ARM_PENDING)),
                jnp.int32(ARM_NONE))
            if arm_off is not None:
                arm_flag = jnp.where(arm_off.astype(_I32) != 0,
                                     jnp.int32(ARM_RESOLVED), arm_flag)

        # ---- compaction: (slot, head) candidates -> M match rows ------
        lvf = lv_all.reshape(C * F)
        pos_ = jnp.cumsum(lvf.astype(_I32), dtype=_I32) - lvf
        n = pos_[-1] + lvf[-1]
        wpos = jnp.where(lvf & (pos_ < M), pos_, M)

        def compact(a):
            return jnp.zeros((M,), a.dtype).at[wpos].set(
                a.reshape(C * F) if a.ndim == 2 else jnp.tile(a, C),
                mode="drop")

        hm_ = compact(jnp.broadcast_to(j0[None, :], (C, F)))
        cm_ = compact(jnp.broadcast_to(
            jnp.arange(C, dtype=_I32)[:, None], (C, F)))
        comp_m = compact(comp_all)

        # per-match capture indices: single/logical refs gather their
        # per-head chase results; count refs rank/select at the match's
        # completion index (collection is station-independent in the
        # sequential kernel — occurrences keep absorbing until max or
        # the park freeze at completion)
        midx: dict = {}
        mpres: dict = {}
        for rp, arr in idx_of.items():
            midx[rp] = arr[hm_] if arr is not j0 else hm_
        for rp, arr in pres_of.items():
            mpres[rp] = arr[hm_]

        need = set()
        for ce in list(nfak.sel_fns.values()) \
                + ([nfak.having] if nfak.having else []):
            need.update(ce.reads)
        need_bases: dict = {}
        for k in need:
            if "." in k and not k.startswith("__"):
                need_bases.setdefault(_base_ref(k.split(".", 1)[0])[0],
                                      set()).add(k.split(".", 1)[0])
        for k in nfak.out_names:
            if k.startswith("__present__."):
                rp = k[len("__present__."):]
                need_bases.setdefault(_base_ref(rp)[0], set()).add(rp)

        for pi, pos in enumerate(prog.positions):
            if pos.kind != "count":
                continue
            ref = pos.nodes[0].ref
            rps = need_bases.get(ref)
            if not rps:
                continue
            s_occ, ra = count_ctx[pi]
            s_m = s_occ[hm_] if s_occ.ndim else s_occ
            ra_m = ra[hm_]
            if pi == S - 1:
                q_m = jnp.int32(pos.min_count) + cm_
            else:
                avail = ranks[pi][comp_m] - ra_m
                q_m = jnp.minimum(avail, jnp.int32(pos.max_count)) \
                    if pos.max_count < UNBOUNDED else avail

            def sel_q(r):
                return jnp.clip(_first_hit(rank_heaps[pi], L, s_m,
                                           ra_m + r, "ge"), 0, F - 1)
            for rp in rps:
                _b, cidx = _base_ref(rp)
                if cidx is None or cidx == "last":
                    if pi == S - 1:
                        midx[rp] = comp_m   # the emitting occurrence
                    else:
                        midx[rp] = sel_q(q_m)
                    mpres[rp] = q_m >= 1
                elif cidx == "last-1":
                    midx[rp] = sel_q(q_m - 1)
                    mpres[rp] = q_m >= 2
                else:
                    want = jnp.int32(int(cidx) + 1)
                    midx[rp] = sel_q(want)
                    mpres[rp] = q_m >= want

        env = self._gather_env(ev, midx, need, F, base_ts, comp_j=comp_m)
        sel = {name: jnp.broadcast_to(ce.fn(env), (M,))
               for name, ce in nfak.sel_fns.items()}
        mvalid = jnp.arange(1, M + 1, dtype=_I32) <= n
        if nfak.having is not None:
            henv = dict(env)
            henv.update(sel)
            mvalid = mvalid & jnp.broadcast_to(nfak.having.fn(henv), (M,))
        sel["__timestamp__"] = ts[comp_m]
        sel["__seq__"] = seq[comp_m]
        sel["__head_seq__"] = seq[hm_]
        if nfak.emit_qid:
            qid = ev.get("__lane_qid__", jnp.int32(0))
            sel["__qid__"] = jnp.broadcast_to(qid.astype(_I32), (M,))
        for name in nfak.out_names:
            if not name.startswith("__present__."):
                continue
            rp = name[len("__present__."):]
            pr = mpres.get(rp)
            if pr is None:
                pr = jnp.ones((M,), bool)
            sel[name] = pr.astype(_I32)

        NO_DL = jnp.int32(2 ** 31 - 1)
        meta = (jnp.zeros((M,), _I32)
                .at[0].set(n).at[3].set(NO_DL).at[4].set(arm_flag))
        irows = [meta]
        if nfak.having is not None:
            irows.append(mvalid.astype(_I32))
        frows = []
        for name in nfak.out_names:
            col = sel[name]
            if col.dtype == jnp.float64:
                frows.append(col)
            elif col.dtype == jnp.float32:
                irows.append(lax.bitcast_convert_type(col, _I32))
            elif col.dtype == jnp.int64:
                irows.append(_hi32(col))
                irows.append(_lo32(col))
            else:
                irows.append(col.astype(_I32))
        out = {"i": jnp.stack(irows, axis=0)}
        if frows:
            out["f"] = jnp.stack(frows, axis=0)
        return out
