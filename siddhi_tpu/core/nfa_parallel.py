"""Parallel-in-time NFA plan families: associative-scan (SFA) + DFA/hybrid.

The sequential device kernel (nfa_device.NFAKernel) walks one event per
`lax.scan` step per lane: throughput is bounded by the T-long dependency
chain, not by math (BENCH_r05: ~0.01-0.02x the single-thread C++ roofline
on the P=1 pattern configs).  *Simultaneous Finite Automata* (arXiv
1405.0562) breaks that chain: simulate the automaton from EVERY state,
compose per-event transition functions associatively, and the whole
block collapses to log-depth scans.  For the linear chains this module
accepts, the composed transition function factorizes — "the earliest
completion reachable from state k at time t" is fully determined by
per-position *next-match pointers*, so the SFA composition lowers to:

  * a reverse `jax.lax.associative_scan` (min semiring) per position for
    statically-maskable transitions (the per-event predicate matrix is
    precomputed outside the scan, exactly like the sequential kernel's
    pre-masks), and
  * a vectorized segment-tree descent for *threshold* transitions —
    capture-dependent filters of the monotone comparison form
    `attr > f(earlier captures)` (the BENCH config-3/4 shape
    `e2.price > e1.price`), answered as "first index >= s whose masked
    value beats v" in O(log T) gathers per hop, batched over every
    pending instance at once.

Two plan families are built on these primitives:

  * family "scan" — the SFA lowering above, O(S log T) depth.
  * family "dfa"  — NFA->DFA/hybrid lowering (arXiv 2210.10077) with
    state-set compaction and bit-packed transitions: the per-event
    position masks pack into one u32 *symbol word* (bit k = event
    matches position k), blocks of STRIDE=4 events precompose into
    dense per-block transition tables (first-hit offsets for all
    positions bit-packed into one u32 per block), and the block-level
    next pointers ride ONE associative scan over T/4 elements — a
    multi-stride dense table walk instead of per-event stepping
    (cf. 2209.05686, CAMA 2112.00267).  Threshold hops share the
    segment-tree machinery (the "hybrid" part).

Eligibility (classify_parallel) is strict and *sound*: anything outside
the supported algebra reports a reason string and the planner keeps the
sequential kernel (or the chunked-halo mode) — the families never guess.
Match semantics of the eligible class (every-head linear chains of
(1,1) stream positions, within-bounded): each head-matching event arms
one instance; an instance at position k advances on the FIRST later
event matching position k (the slot is then consumed), expiring instead
when that event arrives past the position's `within` horizon.  The
next-pointer chase reproduces exactly that — one candidate completion
per head — so outputs are byte-identical to the sequential kernel and
the host oracle (asserted by tests/test_plan_families.py).

Cross-flush continuity reuses the chunked-halo harness in
pattern_plan.py: blocks are stateless, the last `within` window of
events replays at the next flush, and completions at or before the
previous flush's last seq are suppressed on device.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..query import ast
from .expr import (ExprError, compile_expression, compute_dtypes)
from .nfa_device import (ChainSpec, NFAKernel, _hi32, _lo32, _I32,
                         pow2_at_least)

STRIDE = 4                # dfa family: events per precomposed transition
_OFF_BITS = 3             # bits per packed first-hit offset (0..STRIDE)
NUMERIC = (ast.AttrType.INT, ast.AttrType.LONG,
           ast.AttrType.FLOAT, ast.AttrType.DOUBLE)


class ParallelUnsupported(Exception):
    """Chain shape outside the parallel families' sound subset."""


@dataclass
class HopThreshold:
    """One monotone capture-dependent conjunct: own_col OP rhs(captures)."""
    own_key: str                  # "e2.price" — the arriving event's column
    op: str                       # "gt" | "ge" | "lt" | "le"
    rhs: object                   # CompiledExpr over earlier-ref captures
    own_type: ast.AttrType = ast.AttrType.DOUBLE


@dataclass
class Hop:
    """One chain position lowered for the pointer chase."""
    ref: str
    scode: int
    within_ms: Optional[int]
    pre_conjs: list = field(default_factory=list)   # CompiledExpr, event-only
    threshold: Optional[HopThreshold] = None

    @property
    def is_static(self) -> bool:
        return self.threshold is None


@dataclass
class ParallelProgram:
    hops: list                    # [Hop], index = chain position
    stream_ids: list
    schemas: dict                 # ref -> StreamSchema
    ref_pos: dict                 # ref -> position index

    @property
    def S(self) -> int:
        return len(self.hops)


_FLIP = {"gt": "lt", "ge": "le", "lt": "gt", "le": "ge"}
_OPN = {ast.CompareOp.GT: "gt", ast.CompareOp.GE: "ge",
        ast.CompareOp.LT: "lt", ast.CompareOp.LE: "le"}


def _own_var(e, node, schemas) -> Optional[str]:
    """Attr name when `e` is a plain Variable over the node's OWN event
    (qualified with its ref, or unqualified resolving to its schema —
    PatternFilterContext resolution order), else None."""
    if not isinstance(e, ast.Variable) or e.index is not None:
        return None
    if e.stream_ref == node.ref:
        return e.attribute
    if e.stream_ref is None and e.attribute in schemas[node.ref].types:
        return e.attribute
    return None


def lower_parallel(spec: ChainSpec, strings,
                   param_extra: Optional[dict] = None) -> ParallelProgram:
    """Lower a ChainSpec into a pointer-chase program, or raise
    ParallelUnsupported with the (human-readable) ineligibility reason.
    The accepted algebra is the provably-equivalent subset: every-head
    linear chains of single (1,1) stream positions, within-bounded, with
    event-only filters plus at most one monotone threshold conjunct per
    non-head position."""
    if spec.is_sequence:
        raise ParallelUnsupported("strict sequence (`,` succession)")
    if not spec.every_head:
        raise ParallelUnsupported("non-`every` head (single stateful arm)")
    if spec.S < 2:
        raise ParallelUnsupported("single-position chain (no scan depth)")
    hops: list = []
    ref_pos: dict = {}
    for pi, pos in enumerate(spec.positions):
        if pos.op is not None:
            raise ParallelUnsupported("logical and/or position")
        if pos.is_count:
            raise ParallelUnsupported("count quantifier <m:n>")
        n = pos.nodes[0]
        if n.kind != "stream":
            raise ParallelUnsupported("absent (`not ... for`) position")
        if pos.sticky and pi > 0:
            raise ParallelUnsupported("`every` below the head")
        if pos.within_ms is None:
            raise ParallelUnsupported(
                "position without a `within` bound (stateless tail replay "
                "needs a finite horizon)")
        hop = Hop(n.ref, n.scode, pos.within_ms, list(n.pre_conjs))
        if n.step_conjs:
            if pi == 0:
                raise ParallelUnsupported("head filter reads captures")
            if len(n.step_conjs) > 1:
                raise ParallelUnsupported(
                    "multiple capture-dependent conjuncts on one position "
                    "(first-match of a conjunction is not decomposable)")
            hop.threshold = _lower_threshold(
                n, n.step_asts[0], spec, strings, param_extra, ref_pos)
        hops.append(hop)
        ref_pos[n.ref] = pi
    return ParallelProgram(hops, list(spec.stream_ids), dict(spec.schemas),
                           ref_pos)


def _lower_threshold(node, cond, spec, strings, param_extra,
                     ref_pos) -> HopThreshold:
    """`own.attr OP expr(earlier captures)` -> HopThreshold, else raise."""
    from .nfa_device import PatternFilterContext
    if not isinstance(cond, ast.Compare) or cond.op not in _OPN:
        raise ParallelUnsupported(
            "capture-dependent filter is not a <,<=,>,>= comparison")
    own_l = _own_var(cond.left, node, spec.schemas)
    own_r = _own_var(cond.right, node, spec.schemas)
    if (own_l is None) == (own_r is None):
        raise ParallelUnsupported(
            "comparison must have the arriving event's attribute on "
            "exactly one side")
    attr = own_l if own_l is not None else own_r
    op = _OPN[cond.op] if own_l is not None else _FLIP[_OPN[cond.op]]
    own_t = spec.schemas[node.ref].type_of(attr)
    if own_t not in NUMERIC:
        raise ParallelUnsupported(
            f"threshold attribute {attr!r} is not numeric")
    rhs_ast = cond.right if own_l is not None else cond.left
    ctx = PatternFilterContext(spec.schemas, strings, node.ref)
    if param_extra:
        ctx.extra = dict(param_extra)
    try:
        rhs = compile_expression(rhs_ast, ctx)
    except ExprError as e:
        raise ParallelUnsupported(f"threshold rhs not compilable: {e}")
    if rhs.type not in NUMERIC:
        raise ParallelUnsupported("threshold rhs is not numeric")
    ok_reads = set()
    for r, pi in ref_pos.items():
        for a in spec.schemas[r].attributes:
            ok_reads.add(f"{r}.{a.name}")
    bad = set(rhs.reads) - ok_reads
    if bad:
        raise ParallelUnsupported(
            f"threshold rhs reads non-capture keys {sorted(bad)!r} "
            f"(own event / timestamp / later positions)")
    return HopThreshold(f"{node.ref}.{attr}", op, rhs, own_t)


def classify_parallel(spec: ChainSpec, kernel: NFAKernel, strings,
                      param_extra: Optional[dict] = None) -> dict:
    """{'scan': True|reason, 'dfa': True|reason} for one lowered chain.
    A True value means the family is sound for this ChainSpec; a string
    is the ineligibility reason (surfaced in statistics() and asserted
    by the forced-fallback tests)."""
    try:
        prog = lower_parallel(spec, strings, param_extra)
        if kernel.params or kernel.emit_qid:
            raise ParallelUnsupported("per-lane query parameters "
                                      "(fused multi-query kernel)")
        for ce in (list(kernel.sel_fns.values())
                   + ([kernel.having] if kernel.having else [])):
            for k in ce.reads:
                if "." in k and "[" in k.split(".", 1)[0]:
                    raise ParallelUnsupported(
                        f"indexed capture read {k!r} in selector/having")
    except ParallelUnsupported as e:   # lint: allow-swallow (the reason
        # string IS the demotion record — the planner surfaces it via
        # plan.families / rt.explain())
        return {"scan": str(e), "dfa": str(e)}
    return _classify_prog(prog)


def _classify_prog(prog: ParallelProgram) -> dict:
    """Family verdicts for a successfully-lowered pointer-chase program
    (shared between the built-kernel classifier above and the
    analysis-time classify_shape below)."""
    out = {"scan": True}
    if prog.S > 8:
        out["dfa"] = ("more than 8 positions (symbol words bit-pack one "
                      "position per u32 lane bit)")
    elif not any(h.is_static for h in prog.hops[1:]):
        out["dfa"] = ("no static transition to bit-pack (every hop is "
                      "threshold-dependent)")
    else:
        out["dfa"] = True
    return out


def classify_shape(state_input, schemas, strings) -> dict:
    """Analysis-time family eligibility for a raw AST pattern input:
    {'chunk'|'scan'|'dfa': True | reason} with the SAME reason strings
    classify_parallel reports for a built kernel — computable without
    constructing a device plan.  Used by the static analyzer's
    annotation-conflict rule (SA08, docs/ANALYSIS.md) so a forced
    `@app:patternFamily` on a provably ineligible shape is flagged at
    analysis time, before a deploy quietly falls back.

    `schemas` maps stream id -> StreamSchema for every stream the
    pattern consumes; a shape the device chain lowering itself rejects
    reports that reason for every family."""
    from ..interp.engine import _collect_filters
    from .nfa_device import lower_chain
    try:
        spec = lower_chain(state_input, schemas, strings,
                           _collect_filters(state_input.state))
    except Exception as e:   # lint: allow-swallow (reason IS the record)
        r = f"device chain lowering unavailable: {e}"
        return {"chunk": r, "scan": r, "dfa": r}
    # the stateless-harness gates DevicePatternPlan applies before any
    # family runs (pattern_plan.py "plan-family selection")
    base = True
    if not spec.every_head:
        base = "non-`every` head (single stateful arm)"
    elif any(n.kind != "stream" for n in spec.all_nodes):
        base = "absent state (timer-driven deadlines need device state)"
    elif not all(p.within_ms is not None for p in spec.positions):
        base = "position without a `within` bound"
    if base is not True:
        return {"chunk": base, "scan": base, "dfa": base}
    out = {"chunk": True}
    try:
        prog = lower_parallel(spec, strings)
        out.update(_classify_prog(prog))
    except ParallelUnsupported as e:   # lint: allow-swallow (reason IS
        # the analysis-time record)
        out.update({"scan": str(e), "dfa": str(e)})
    return out


# ---------------------------------------------------------------------------
# vectorized "first index >= s with masked value OP v" primitives
# ---------------------------------------------------------------------------

def _sentinel(dt, agg: str):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.array(-jnp.inf if agg == "max" else jnp.inf, dt)
    info = jnp.iinfo(dt)
    return jnp.array(info.min if agg == "max" else info.max, dt)


def _tree_dtype(own_dt, rhs_dt):
    """Dtype the threshold tree aggregates (and compares) in: the
    promotion of both comparison sides, with int32 widened to int64 so
    the sentinel sits strictly OUTSIDE the value range — `>=`/`<=` hit
    checks must never be satisfiable by a masked-out leaf (an int32
    column whose rhs equals INT32_MIN would otherwise match them).
    Mixed int/float comparisons promote to the float side, whose ±inf
    sentinels are strictly outside every value, and whose rounding then
    matches the sequential kernel's own promoted per-event compare."""
    dt = jnp.promote_types(own_dt, rhs_dt)
    if dt == jnp.int32:
        return jnp.dtype(jnp.int64)
    return dt


def _build_heap(vals, mask, L: int, agg: str, dt):
    """Perfect binary segment tree in heap layout (1-based; leaves at
    [L, 2L)).  Built with log2(L) vectorized reductions — the SFA
    transition-composition tree for threshold hops.  Masked-out and NaN
    leaves are replaced by the sentinel BEFORE aggregation: the
    sequential kernel evaluates the predicate per event (NaN compares
    False there), while jnp.maximum/minimum would propagate a NaN to
    every ancestor and poison whole subtrees."""
    sent = _sentinel(dt, agg)
    keep = mask
    if jnp.issubdtype(vals.dtype, jnp.floating):
        keep = keep & ~jnp.isnan(vals)
    vals = jnp.where(keep, vals.astype(dt), sent)
    red = jnp.maximum if agg == "max" else jnp.minimum
    lvl = jnp.full((L,), sent, dt).at[:vals.shape[0]].set(vals)
    levels = [lvl]
    while lvl.shape[0] > 1:
        lvl = red(lvl[0::2], lvl[1::2])
        levels.append(lvl)
    # heap[1]=root ... heap[L:2L)=leaves; heap[0] unused (sentinel)
    return jnp.concatenate([jnp.full((1,), sent, dt)]
                           + [lv for lv in reversed(levels)])


def _first_hit(heap, L: int, s, v, op: str):
    """First leaf index >= s whose value satisfies OP v; L when none.
    Vectorized over query arrays s, v; 2*log2(L) gather rounds total
    (up-walk decomposing [s, L) into aligned blocks visited left to
    right, then a descent into the first qualifying subtree).

    Hit checks are sentinel-safe: `>=`/`<=` rewrite to strict compares
    against the adjacent representable value in the tree dtype (exact —
    int32 trees are widened, floats use nextafter; an infinite rhs
    meeting infinite data, or an int64 rhs of exactly INT64_MIN, are
    the accepted pathological corners)."""
    va = jnp.asarray(v, heap.dtype)
    if op == "ge":
        v = jnp.nextafter(va, jnp.array(-jnp.inf, heap.dtype)) \
            if jnp.issubdtype(heap.dtype, jnp.floating) else va - 1
        op = "gt"
    elif op == "le":
        v = jnp.nextafter(va, jnp.array(jnp.inf, heap.dtype)) \
            if jnp.issubdtype(heap.dtype, jnp.floating) else va + 1
        op = "lt"
    else:
        v = va
    cmp = {"gt": lambda a, b: a > b,
           "lt": lambda a, b: a < b}[op]
    P = max(L.bit_length() - 1, 0)

    # fori_loop (not an unrolled python loop): the round count is static
    # but the body is identical each round, and unrolling 2*log2(L)
    # gather rounds made the XLA program ~4x slower to COMPILE — which
    # dominates small deployments (every pattern test runtime pays it)
    def up(i, st):
        l, found, fnode = st
        r = jnp.int32(2 * L) >> i
        odd = (l & 1) == 1
        nv = heap[jnp.clip(l, 0, 2 * L - 1)]
        take = odd & (l < r) & cmp(nv, v) & ~found
        fnode = jnp.where(take, l, fnode)
        found = found | take
        return ((l + odd.astype(_I32)) >> 1, found, fnode)

    l0 = (jnp.clip(s, 0, L) + L).astype(_I32)
    _l, found, fnode = lax.fori_loop(
        0, P + 1, up, (l0, jnp.zeros(l0.shape, bool),
                       jnp.zeros(l0.shape, _I32)))

    def down(_i, fnode):
        internal = found & (fnode < L)
        left = 2 * fnode
        lv = heap[jnp.clip(left, 0, 2 * L - 1)]
        goleft = cmp(lv, v)
        return jnp.where(internal,
                         jnp.where(goleft, left, left + 1), fnode)

    fnode = lax.fori_loop(0, P, down, fnode)
    return jnp.where(found, fnode - L, L).astype(_I32)


def _next_static_scan(mask, L: int):
    """next[t] = first index >= t with mask set (L = none): ONE reverse
    associative scan in the min semiring — the SFA composition of
    per-event transition functions restricted to a static position."""
    F = mask.shape[0]
    idx = jnp.where(mask, jnp.arange(F, dtype=_I32), jnp.int32(L))
    return lax.associative_scan(jnp.minimum, idx, reverse=True)


# ---------------------------------------------------------------------------
# the block kernel
# ---------------------------------------------------------------------------

class ParallelChainKernel:
    """Stateless flat-block kernel for one lowered chain, in either the
    "scan" (pure SFA) or "dfa" (bit-packed multi-stride hybrid) family.

    Mirrors NFAKernel's packed-output contract exactly (meta row, valid
    row under `having`, out_names/out_dtypes from the plan's NFAKernel)
    so DevicePatternPlan._unpack_block consumes both interchangeably.
    Blocks carry no device state: ev is the chunked-halo flat layout
    (`__flat.*` arrays + `__nev__`/`__prev_seq__`/bases) minus the lane
    geometry — the whole flush is ONE log-depth program."""

    def __init__(self, prog: ParallelProgram, nfak: NFAKernel,
                 family: str = "scan"):
        assert family in ("scan", "dfa")
        self.prog = prog
        self.nfak = nfak              # selector/having/output metadata
        self.family = family
        self.f64 = nfak.f64
        self._mode = nfak._mode
        self._block_cache: dict = {}

    # NFAKernel-compatible surface consumed by _call_block / bench
    def block_fn(self, F: int, M: int):
        key = (F, M)
        fn = self._block_cache.get(key)
        if fn is None:
            fn = self._block_cache[key] = jax.jit(self._make_block(M))
        return fn

    def _make_block(self, M: int):
        def block(state, ev):
            with compute_dtypes(self._mode):
                return state, self._block_impl(ev, M)
        return block

    # -- mask/env helpers -----------------------------------------------

    def _flat_env(self, ev, hop: Hop, ts, base_ts) -> dict:
        env = {}
        for a in self.prog.schemas[hop.ref].attributes:
            key = f"__flat.{hop.scode}.{a.name}"
            if key in ev:
                env[f"{hop.ref}.{a.name}"] = ev[key]
        env["__timestamp__"] = base_ts + ts.astype(jnp.int64)
        return env

    def _hop_mask(self, ev, hop: Hop, ts, valid, base_ts):
        m = valid
        if len(self.prog.stream_ids) > 1:
            m = m & (ev["__flat.__scode__"] == hop.scode)
        if hop.pre_conjs:
            env = self._flat_env(ev, hop, ts, base_ts)
            for ce in hop.pre_conjs:
                m = m & jnp.broadcast_to(ce.fn(env), m.shape)
        return m

    def _cap_env(self, ev, j_at: dict, keys, F: int, base_ts, comp_j=None):
        """Capture env gathered at resolved hop indices: key "r.attr" ->
        flat column at j_at[position(r)] (clipped; callers mask validity
        downstream).  `keys` bounds the gathers to what's read."""
        env = {}
        for k in keys:
            if k == "__timestamp__":
                if comp_j is not None:
                    env[k] = base_ts + ev["__flat.__ts__"][comp_j] \
                        .astype(jnp.int64)
                continue
            if "." not in k or k.startswith("__"):
                continue
            refpart, attr = k.split(".", 1)
            base = refpart.split("[", 1)[0]
            pi = self.prog.ref_pos.get(base)
            if pi is None:
                continue
            scode = self.prog.hops[pi].scode
            col = ev.get(f"__flat.{scode}.{attr}")
            if col is None:
                continue
            env[k] = col[jnp.clip(j_at[pi], 0, F - 1)]
        return env

    # -- dfa family: bit-packed multi-stride static tables ----------------

    def _dfa_tables(self, masks, F: int, L: int):
        """Precompose per-event symbol words into stride-4 block tables.
        Returns (suffix_flat per static hop, packed first-offset words,
        block-level next pointers per static hop, NB)."""
        B = STRIDE
        NB = -(-F // B)
        Fp = NB * B
        static = [k for k in range(1, self.prog.S)
                  if self.prog.hops[k].is_static]
        # ONE u32 symbol word per event: bit k = matches position k
        sym = jnp.zeros((Fp,), jnp.uint32)
        for k in static:
            mk = jnp.zeros((Fp,), bool).at[:F].set(masks[k])
            sym = sym | (mk.astype(jnp.uint32) << np.uint32(k))
        o = jnp.arange(B, dtype=_I32)[None, :]
        suffix = {}
        first = {}
        for k in static:
            bits = ((sym.reshape(NB, B) >> np.uint32(k)) & 1) != 0
            offs = jnp.where(bits, o, jnp.int32(B))
            # in-block suffix-first offsets (stride-4: 3 dense mins)
            acc = offs[:, B - 1]
            cols = [acc]
            for c in range(B - 2, -1, -1):
                acc = jnp.minimum(offs[:, c], acc)
                cols.append(acc)
            suf = jnp.stack(list(reversed(cols)), axis=1)   # (NB, B)
            suffix[k] = suf.reshape(-1)
            first[k] = suf[:, 0]
        # per-block transition table: first-hit offsets for ALL static
        # positions bit-packed into one u32 word per block
        packed = jnp.zeros((NB,), jnp.uint32)
        for k in static:
            packed = packed | (first[k].astype(jnp.uint32)
                               << np.uint32(_OFF_BITS * k))
        # block-level next pointers: one associative scan over F/4
        # elements per static position (stacked -> a single scan call)
        if static:
            blk = jnp.stack(
                [jnp.where(first[k] < B,
                           jnp.arange(NB, dtype=_I32), jnp.int32(NB))
                 for k in static], axis=1)
            nblk = lax.associative_scan(jnp.minimum, blk, reverse=True,
                                        axis=0)
            nblk = {k: nblk[:, i] for i, k in enumerate(static)}
        else:
            nblk = {}
        return suffix, packed, nblk, NB

    def _dfa_next(self, k: int, s, suffix, packed, nblk, NB: int, L: int):
        """Multi-stride lookup: in-block suffix table, then the packed
        block-transition word of the next block containing a hit."""
        B = STRIDE
        Fp = NB * B
        sc = jnp.clip(s, 0, Fp - 1)
        inb = suffix[k][sc]                      # first o >= s%B in block
        b = sc >> 2
        j_in = (b << 2) + inb
        b2 = nblk[k][jnp.clip(b + 1, 0, NB - 1)]
        ok2 = (b + 1 < NB) & (b2 < NB)
        f2 = ((packed[jnp.clip(b2, 0, NB - 1)]
               >> (jnp.uint32(_OFF_BITS * k))) & jnp.uint32(7)).astype(_I32)
        j_blk = (b2 << 2) + f2
        j = jnp.where(inb < B, j_in, jnp.where(ok2, j_blk, jnp.int32(L)))
        return jnp.where(s < Fp, j, jnp.int32(L)).astype(_I32)

    # -- the block --------------------------------------------------------

    def _block_impl(self, ev, M: int):
        prog, nfak = self.prog, self.nfak
        S = prog.S
        F = ev["__flat.__ts__"].shape[0]
        L = pow2_at_least(F, lo=2)
        nev = ev["__nev__"].astype(_I32)
        prev_seq = ev["__prev_seq__"]
        base_ts = ev["__base_ts__"]
        ts = ev["__flat.__ts__"]
        # scan/dfa flushes always ship the explicit seq array (output
        # events consume global seqs, so derived-consecutive seqs would
        # force a second structural compile at flush 2)
        seq = ev["__flat.__seq__"]
        valid = jnp.arange(F, dtype=_I32) < nev
        masks = [self._hop_mask(ev, h, ts, valid, base_ts)
                 for h in prog.hops]

        if self.family == "dfa":
            suffix, packed, nblk, NB = self._dfa_tables(masks, F, L)

        # expiry heap: the sequential kernel expires a waiting instance
        # on the FIRST arriving event whose age exceeds the position's
        # `within` horizon — matching or not (nfa_device._step computes
        # `expired` before the match mask, over timey=valid).  With
        # out-of-order timestamps a later event can carry a REGRESSED
        # ts, so checking the matched event alone would resurrect
        # instances the sequential kernel killed.  i64 aggregation:
        # ts offsets reach ±2^30 and ts+W must not wrap i32.
        ts_heap = _build_heap(ts, valid, L, "max", jnp.dtype(jnp.int64))
        ts64 = ts.astype(jnp.int64)

        # pointer chase: every event index is a candidate head
        j0 = jnp.arange(F, dtype=_I32)
        ok = masks[0]
        j_at = {0: j0}
        j = j0
        for k in range(1, S):
            hop = prog.hops[k]
            s = j + 1
            if hop.is_static:
                if self.family == "dfa":
                    jn = self._dfa_next(k, s, suffix, packed, nblk, NB, L)
                else:
                    nxt = _next_static_scan(masks[k], L)
                    jn = jnp.where(s < F, nxt[jnp.clip(s, 0, F - 1)],
                                   jnp.int32(L))
            else:
                th = hop.threshold
                agg = "max" if th.op in ("gt", "ge") else "min"
                own = ev[f"__flat.{hop.scode}.{th.own_key.split('.', 1)[1]}"]
                env = self._cap_env(ev, j_at, th.rhs.reads, F, base_ts)
                v = jnp.broadcast_to(th.rhs.fn(env), (F,))
                dt = _tree_dtype(own.dtype, v.dtype)
                heap = _build_heap(own, masks[k], L, agg, dt)
                jn = _first_hit(heap, L, s, v, th.op)
            ok = ok & (jn < F)
            js = jnp.clip(jn, 0, F - 1)
            # the hop survives iff the match arrives BEFORE the first
            # event that would expire the waiting instance (ts > head_ts
            # + W_k); this also subsumes the matched event's own age
            # check (a killer has ts strictly past the horizon)
            killer = _first_hit(ts_heap, L, s,
                                ts64 + jnp.int64(hop.within_ms), "gt")
            ok = ok & (jn < killer)
            j_at[k] = js
            j = js
        comp_j = j_at[S - 1]
        lv = ok & (seq[comp_j] > prev_seq.astype(_I32))

        # compaction: one cumsum + one scatter per column (NFAKernel's
        # flat-buffer layout; M overflow re-runs with a bigger buffer)
        pos = jnp.cumsum(lv.astype(_I32), dtype=_I32) - lv
        n = pos[-1] + lv[-1]
        wpos = jnp.where(lv & (pos < M), pos, M)
        jm = {k: jnp.zeros((M,), _I32).at[wpos].set(v, mode="drop")
              for k, v in j_at.items()}

        # selector env over compacted capture gathers
        need = set()
        for ce in list(nfak.sel_fns.values()) \
                + ([nfak.having] if nfak.having else []):
            need.update(ce.reads)
        env = self._cap_env(ev, jm, need, F, base_ts,
                            comp_j=jm[S - 1])
        sel = {name: jnp.broadcast_to(ce.fn(env), (M,))
               for name, ce in nfak.sel_fns.items()}
        mvalid = jnp.arange(1, M + 1, dtype=_I32) <= n
        if nfak.having is not None:
            henv = dict(env)
            henv.update(sel)
            mvalid = mvalid & jnp.broadcast_to(nfak.having.fn(henv), (M,))
        sel["__timestamp__"] = ts[jm[S - 1]]
        sel["__seq__"] = seq[jm[S - 1]]
        sel["__head_seq__"] = seq[jm[0]]

        NO_DL = jnp.int32(2 ** 31 - 1)
        meta = (jnp.zeros((M,), _I32)
                .at[0].set(n).at[3].set(NO_DL))
        irows = [meta]
        if nfak.having is not None:
            irows.append(mvalid.astype(_I32))
        frows = []
        for name in nfak.out_names:
            col = sel[name]
            if col.dtype == jnp.float64:
                frows.append(col)
            elif col.dtype == jnp.float32:
                irows.append(lax.bitcast_convert_type(col, _I32))
            elif col.dtype == jnp.int64:
                irows.append(_hi32(col))
                irows.append(_lo32(col))
            else:
                irows.append(col.astype(_I32))
        out = {"i": jnp.stack(irows, axis=0)}
        if frows:
            out["f"] = jnp.stack(frows, axis=0)
        return out
