"""External-store table SPI — the analog of the reference's
AbstractRecordTable + ExpressionBuilder condition pushdown
(reference: core:table/record/AbstractRecordTable.java:424,
core:table/record/ExpressionBuilder.java:405,
core:util/collection/expression/* compiled-condition model).

A table defined with `@store(type='x', ...)` lives OUTSIDE the engine
(RDBMS, KV store, ...).  The engine compiles each table condition ONCE
into a backend-neutral `StoreCondition` tree where:

  * table columns are `("col", name)` leaves,
  * stream-side subexpressions (anything not touching the table) are
    lifted into named parameters `("param", key)` whose values are
    computed per probe event and shipped with the operation — exactly
    the reference's ExpressionBuilder constant/variable lifting,
  * the store renders the tree into its query language (SQL etc.); a
    default `evaluate(record, params)` interpreter lets simple stores
    filter generically.

All engine operations reach the store through the SPI verbs
(add/find/update/delete/update_or_add/contains) with pushed-down
conditions — never row handles: external rows have no engine identity
(reference semantics).  `set` values for record tables may reference
stream/output attributes only (computed host-side and shipped as plain
values; the reference ships the same computed update-set maps).

The engine-facing `RecordTableBridge` mirrors the InMemoryTable access
surface (compiled-condition find + row_env/row_tuple over a per-probe
fetch cache) so joins, store queries, writers, and `in Table` membership
work unchanged via the dispatch hook in compile_table_condition.
"""
from __future__ import annotations

import time
import warnings
from typing import Callable, Optional

import numpy as np

from ..query import ast
from ..query.ast import AttrType
from .schema import StreamSchema, StringTable
from .table import TableError

# ---------------------------------------------------------------------------
# backend-neutral compiled condition
# ---------------------------------------------------------------------------

_CMP = {
    ast.CompareOp.LT: "<", ast.CompareOp.LE: "<=", ast.CompareOp.GT: ">",
    ast.CompareOp.GE: ">=", ast.CompareOp.EQ: "==", ast.CompareOp.NEQ: "!=",
}
_MATH = {ast.MathOp.ADD: "+", ast.MathOp.SUB: "-", ast.MathOp.MUL: "*",
         ast.MathOp.DIV: "/", ast.MathOp.MOD: "%"}


class StoreCondition:
    """Immutable pushdown tree.  Node forms (nested tuples):
      ("col", name) | ("param", key) | ("const", value)
      ("cmp", op, l, r) | ("and", l, r) | ("or", l, r) | ("not", e)
      ("math", op, l, r) | ("isnull", e) | ("true",)
    """

    __slots__ = ("node", "param_fns")

    def __init__(self, node, param_fns: dict):
        self.node = node
        self.param_fns = param_fns      # key -> fn(env) -> value

    def params(self, env: dict) -> dict:
        return {k: f(env) for k, f in self.param_fns.items()}

    def evaluate(self, record: dict, params: dict) -> bool:
        return bool(_eval(self.node, record, params))

    def __repr__(self):
        return f"StoreCondition({self.node!r})"


def _eval(n, rec, params):
    tag = n[0]
    if tag == "true":
        return True
    if tag == "col":
        return rec.get(n[1])
    if tag == "param":
        return params[n[1]]
    if tag == "const":
        return n[1]
    if tag == "and":
        return bool(_eval(n[1], rec, params)) and bool(_eval(n[2], rec, params))
    if tag == "or":
        return bool(_eval(n[1], rec, params)) or bool(_eval(n[2], rec, params))
    if tag == "not":
        return not bool(_eval(n[1], rec, params))
    if tag == "isnull":
        return _eval(n[1], rec, params) is None
    l, r = _eval(n[2], rec, params), _eval(n[3], rec, params)
    if tag == "cmp":
        if l is None or r is None:
            return False
        op = n[1]
        return {"<": l < r, "<=": l <= r, ">": l > r, ">=": l >= r,
                "==": l == r, "!=": l != r}[op]
    if tag == "math":
        if l is None or r is None:
            return None
        op = n[1]
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        return l % r
    raise TableError(f"bad store-condition node {tag!r}")


class StoreExpressionBuilder:
    """ast.Expression -> StoreCondition (reference ExpressionBuilder's
    visitor).  Subtrees that never touch the table become parameters."""

    def __init__(self, table_refs: set, schema: StreamSchema, stream_ctx):
        self.table_refs = table_refs
        self.schema = schema
        self.stream_ctx = stream_ctx
        self.param_fns: dict = {}

    def build(self, expr: Optional[ast.Expression]) -> StoreCondition:
        node = ("true",) if expr is None else self._walk(expr)
        return StoreCondition(node, self.param_fns)

    # -- helpers ----------------------------------------------------------

    def _is_table_col(self, e) -> Optional[str]:
        if isinstance(e, ast.Variable) and e.index is None:
            if e.stream_ref in self.table_refs:
                return e.attribute
            if e.stream_ref is None and e.attribute in self.schema.types \
                    and not self._stream_resolves(e):
                return e.attribute
        return None

    def _stream_resolves(self, e: ast.Variable) -> bool:
        try:
            self.stream_ctx.resolve(e)
            return True
        except Exception:
            return False

    def _touches_table(self, e) -> bool:
        if self._is_table_col(e) is not None:
            return True
        for nm in ("left", "right", "expr"):
            sub = getattr(e, nm, None)
            if isinstance(sub, ast.Expression) and self._touches_table(sub):
                return True
        for sub in getattr(e, "args", ()) or ():
            if isinstance(sub, ast.Expression) and self._touches_table(sub):
                return True
        return False

    def _param(self, e: ast.Expression):
        from ..interp.expr import compile_py
        key = f"p{len(self.param_fns)}"
        fn, _t = compile_py(e, self.stream_ctx)
        self.param_fns[key] = fn
        return ("param", key)

    def _walk(self, e: ast.Expression):
        col = self._is_table_col(e)
        if col is not None:
            return ("col", col)
        if not self._touches_table(e):
            if isinstance(e, ast.Constant):
                return ("const", e.value)
            return self._param(e)
        if isinstance(e, ast.And):
            return ("and", self._walk(e.left), self._walk(e.right))
        if isinstance(e, ast.Or):
            return ("or", self._walk(e.left), self._walk(e.right))
        if isinstance(e, ast.Not):
            return ("not", self._walk(e.expr))
        if isinstance(e, ast.Compare):
            return ("cmp", _CMP[e.op], self._walk(e.left), self._walk(e.right))
        if isinstance(e, ast.Math):
            return ("math", _MATH[e.op], self._walk(e.left), self._walk(e.right))
        if isinstance(e, ast.IsNull) and e.expr is not None:
            return ("isnull", self._walk(e.expr))
        raise TableError(
            f"record-store condition: cannot push down "
            f"{type(e).__name__} over table columns")


# ---------------------------------------------------------------------------
# the SPI
# ---------------------------------------------------------------------------

class RecordTable:
    """Extension base for external table stores.  Subclass and register
    with `register_store_type`; records are dicts of decoded python
    values keyed by attribute name, plus "__timestamp__"."""

    def __init__(self, defn: ast.TableDefinition, options: dict):
        self.defn = defn
        self.options = options
        self.connected = False

    # -- lifecycle (reference: Table.connectWithRetry) --------------------

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def connect_with_retry(self, max_tries: int = 5,
                           base_delay_s: float = 0.05) -> None:
        delay = base_delay_s
        for attempt in range(max_tries):
            try:
                self.connect()
                self.connected = True
                return
            except Exception as e:
                if attempt == max_tries - 1:
                    raise
                warnings.warn(
                    f"store {type(self).__name__} for table "
                    f"{self.defn.id!r}: connect failed ({e}); retrying in "
                    f"{delay:.2f}s", RuntimeWarning)
                time.sleep(delay)
                delay *= 2

    # -- operations (reference AbstractRecordTable verbs) -----------------

    def add(self, records: list) -> None:
        raise NotImplementedError

    def find(self, condition: StoreCondition, params: dict) -> list:
        raise NotImplementedError

    def update(self, condition: StoreCondition, params: dict,
               set_values: dict) -> int:
        raise NotImplementedError

    def delete(self, condition: StoreCondition, params: dict) -> int:
        raise NotImplementedError

    def update_or_add(self, condition: StoreCondition, params: dict,
                      set_values: dict, record: dict) -> None:
        if self.update(condition, params, set_values) == 0:
            self.add([record])

    def contains(self, condition: StoreCondition, params: dict) -> bool:
        return bool(self.find(condition, params))

    # -- optional snapshot participation ----------------------------------

    def snapshot(self):
        return None

    def restore(self, state) -> None:
        pass


class InMemoryRecordStore(RecordTable):
    """Reference implementation / test double (the analog of the
    reference's TestStoreContainingInMemoryTable)."""

    def __init__(self, defn, options):
        super().__init__(defn, options)
        self.records: list = []
        self.op_counts = {"add": 0, "find": 0, "update": 0, "delete": 0}

    def add(self, records: list) -> None:
        self.op_counts["add"] += 1
        self.records.extend(dict(r) for r in records)

    def find(self, condition, params) -> list:
        self.op_counts["find"] += 1
        return [r for r in self.records if condition.evaluate(r, params)]

    def update(self, condition, params, set_values) -> int:
        self.op_counts["update"] += 1
        n = 0
        for r in self.records:
            if condition.evaluate(r, params):
                r.update(set_values)
                n += 1
        return n

    def delete(self, condition, params) -> int:
        self.op_counts["delete"] += 1
        before = len(self.records)
        self.records = [r for r in self.records
                        if not condition.evaluate(r, params)]
        return before - len(self.records)

    def snapshot(self):
        return [dict(r) for r in self.records]

    def restore(self, state) -> None:
        self.records = [dict(r) for r in (state or [])]


STORE_TYPES: dict = {"memory": InMemoryRecordStore,
                     "teststore": InMemoryRecordStore}


def register_store_type(name: str, cls, meta=None) -> None:
    from ..extension import register_meta
    register_meta("store", meta)
    STORE_TYPES[name.lower()] = cls


# ---------------------------------------------------------------------------
# engine-facing bridge
# ---------------------------------------------------------------------------

class RecordTableBridge:
    """Quacks like InMemoryTable for the engine's consumers; every
    operation round-trips through the SPI with a pushed-down condition.
    Fetched records are cached under virtual row indices for the duration
    of one probe (find -> row_env/row_tuple access pattern)."""

    is_record = True

    def __init__(self, defn: ast.TableDefinition, strings: StringTable,
                 store: RecordTable):
        self.defn = defn
        self.id = defn.id
        self.schema = StreamSchema(defn.id, tuple(defn.attributes))
        self.strings = strings
        self.store = store
        self.pk_attrs: tuple = tuple(defn.primary_keys())
        self._fetch: list = []       # virtual row index -> record dict

    # -- fetch cache -------------------------------------------------------

    def cache_records(self, records: list) -> np.ndarray:
        base = len(self._fetch)
        self._fetch.extend(records)
        if len(self._fetch) > 1 << 16:      # bound the cache across probes
            self._fetch = list(records)
            base = 0
        return np.arange(base, base + len(records), dtype=np.int64)

    def _rec(self, row: int) -> dict:
        return self._fetch[int(row)]

    def row_env(self, row: int, refs: tuple = ()) -> dict:
        rec = self._rec(row)
        env = {}
        for a in self.defn.attributes:
            v = rec.get(a.name)
            for r in refs:
                env[f"{r}.{a.name}"] = v
        return env

    def row_tuple(self, row: int) -> tuple:
        rec = self._rec(row)
        return tuple(rec.get(a.name) for a in self.defn.attributes)

    def row_ts(self, row: int) -> int:
        return int(self._rec(row).get("__timestamp__", 0) or 0)

    # -- InMemoryTable-surface operations ---------------------------------

    def insert_batch(self, batch) -> None:
        rows = batch.rows(self.strings)
        recs = []
        for ts, row in zip(batch.timestamps, rows):
            rec = {a.name: v for a, v in zip(self.defn.attributes, row)}
            rec["__timestamp__"] = int(ts)
            recs.append(rec)
        self.store.add(recs)

    def all_rows(self) -> list:
        cond = StoreCondition(("true",), {})
        return [tuple(r.get(a.name) for a in self.defn.attributes)
                for r in self.store.find(cond, {})]

    def __len__(self) -> int:
        return len(self.store.find(StoreCondition(("true",), {}), {}))

    # -- snapshot ---------------------------------------------------------

    def state_dict(self) -> dict:
        return {"store": self.store.snapshot()}

    def load_state_dict(self, st: dict) -> None:
        self.store.restore(st.get("store"))


class CompiledRecordCondition:
    """CompiledTableCondition-compatible probe over the SPI."""

    uses_index = False

    def __init__(self, bridge: RecordTableBridge, cond: StoreCondition):
        self.table = bridge
        self.cond = cond

    def find(self, env: dict) -> np.ndarray:
        records = self.table.store.find(self.cond, self.cond.params(env))
        return self.table.cache_records(records)

    def contains(self, env: dict) -> bool:
        return self.table.store.contains(self.cond, self.cond.params(env))


def compile_record_condition(expr: Optional[ast.Expression],
                             bridge: RecordTableBridge,
                             refs, stream_ctx) -> CompiledRecordCondition:
    b = StoreExpressionBuilder(set(refs), bridge.schema, stream_ctx)
    cond = b.build(expr)
    # a bare value expression (`expr in T`) means primary-key membership
    # (reference InConditionExpressionExecutor)
    if cond.node[0] in ("col", "param", "const", "math"):
        if len(bridge.pk_attrs) != 1:
            raise TableError(
                f"'in {bridge.id}': needs exactly one @PrimaryKey attribute")
        cond = StoreCondition(
            ("cmp", "==", ("col", bridge.pk_attrs[0]), cond.node),
            cond.param_fns)
    return CompiledRecordCondition(bridge, cond)


# ---------------------------------------------------------------------------
# record-table writers (reference: RecordTableHandler add/update/delete)
# ---------------------------------------------------------------------------

class _RecordConditionedWriter:
    def __init__(self, bridge, out_schema, on, set_clauses=(), strings=None):
        from ..interp.expr import PyExprContext, compile_py

        self.bridge = bridge
        self.out_schema = out_schema
        self.strings = strings or bridge.strings
        self._out_ref = f"#out#{out_schema.id}"
        sctx = PyExprContext({self._out_ref: out_schema},
                             default_ref=self._out_ref)
        b = StoreExpressionBuilder({bridge.id}, bridge.schema, sctx)
        self.cond = b.build(on)
        # set values: stream/output side only (computed host-side, shipped
        # as plain values; table-column references can't be pushed down)
        self.sets: list = []
        for sc in set_clauses:
            attr = sc.attribute.attribute
            if attr not in bridge.schema.types:
                raise TableError(f"set: table {bridge.id!r} has no "
                                 f"attribute {attr!r}")
            if b._touches_table(sc.value):
                raise TableError(
                    f"record table {bridge.id!r}: set values may reference "
                    f"stream attributes only (store-side expressions are "
                    f"not pushed down)")
            f, _t = compile_py(sc.value, sctx)
            self.sets.append((attr, f))
        if not set_clauses:
            self.sets = [
                (a.name, (lambda env, _n=a.name: env.get(_n)))
                for a in bridge.schema.attributes if a.name in out_schema.types]

    def _row_envs(self, batch):
        names = [a.name for a in self.out_schema.attributes]
        rows = batch.rows(self.strings)
        for ts, row in zip(batch.timestamps, rows):
            env = dict(zip(names, row))
            env["__timestamp__"] = int(ts)
            yield env, row


class RecordInsertWriter:
    def __init__(self, bridge, out_schema):
        self.bridge = bridge
        if [a.type for a in out_schema.attributes] != \
                [a.type for a in bridge.schema.attributes]:
            raise TableError(
                f"insert into record table {bridge.id!r}: schema mismatch")

    def apply(self, batch) -> None:
        self.bridge.insert_batch(batch)


class RecordUpdateWriter(_RecordConditionedWriter):
    def apply(self, batch) -> None:
        for env, _row in self._row_envs(batch):
            sets = {attr: f(env) for attr, f in self.sets}
            self.bridge.store.update(self.cond, self.cond.params(env), sets)


class RecordDeleteWriter(_RecordConditionedWriter):
    def apply(self, batch) -> None:
        for env, _row in self._row_envs(batch):
            self.bridge.store.delete(self.cond, self.cond.params(env))


class RecordUpdateOrInsertWriter(_RecordConditionedWriter):
    def apply(self, batch) -> None:
        for env, row in self._row_envs(batch):
            sets = {attr: f(env) for attr, f in self.sets}
            rec = {a.name: v for a, v in
                   zip(self.bridge.defn.attributes, row)}
            rec["__timestamp__"] = env["__timestamp__"]
            self.bridge.store.update_or_add(
                self.cond, self.cond.params(env), sets, rec)


def make_record_table_writer(action, bridge, out_schema):
    if isinstance(action, ast.InsertInto):
        return RecordInsertWriter(bridge, out_schema)
    if isinstance(action, ast.UpdateTable):
        return RecordUpdateWriter(bridge, out_schema, action.on,
                                  action.set_clauses)
    if isinstance(action, ast.DeleteFrom):
        return RecordDeleteWriter(bridge, out_schema, action.on)
    if isinstance(action, ast.UpdateOrInsertTable):
        return RecordUpdateOrInsertWriter(bridge, out_schema, action.on,
                                          action.set_clauses)
    raise TableError(f"unsupported table action {type(action).__name__}")


def build_record_table(defn: ast.TableDefinition, strings: StringTable):
    """@store(type='x', ...) table -> bridge, or None for in-memory."""
    sa = ast.find_annotation(defn.annotations, "store")
    if sa is None:
        return None
    typ = sa.element("type")
    if typ is None:
        raise TableError(f"table {defn.id!r}: @store needs a type")
    cls = STORE_TYPES.get(str(typ).lower())
    if cls is None:
        raise TableError(f"table {defn.id!r}: unknown store type {typ!r}; "
                         f"register_store_type() first")
    opts = {k: v for k, v in sa.elements if k is not None}
    store = cls(defn, opts)
    store.connect_with_retry()
    return RecordTableBridge(defn, strings, store)
