"""End-to-end frame tracing — causal cross-thread span trees.

The PR-1 `PipelineTracer` records thread-local per-batch spans, which
breaks at every thread hand-off of the serving path (net reader ->
admission park -> WAL append -> dispatch pipeline -> scheduler-pump
materialization -> sink egress).  This module is the causal plane that
survives the hops: one ingested frame yields ONE trace — a tree of
spans linked by explicit (trace_id, span_id, parent_id) edges, no
matter which `siddhi-*` thread recorded each span.

Pieces:

  * `TraceHandle` — the per-frame carrier.  It rides the `Work` unit
    through admission, the frozen `EventBatch` through dispatch and the
    `DispatchPipeline`, and the sink outbox to egress.  `mark()` records
    one span parented on the handle's current head and advances the
    head, so the recorded spans form a causal chain/tree
    (admit -> wal.append -> freeze -> dispatch -> materialize ->
    sink.publish) with no orphans.
  * `FrameTracer` — the per-runtime recorder: a bounded always-on ring
    of completed spans (cheap: one deque append per span), sampling
    (`@app:trace(sample='N')` — 1 in N server-assigned frames gets a
    trace; producer-stamped wire trace ids ALWAYS trace), and trace-id
    allocation tagged with host+pid so multi-host dumps merge.
  * the trigger registry — `trigger(kind, detail)` is nonblocking and
    lock-cheap (it only enqueues; safe under engine locks).  A
    triggered kind (`slo_breach`, `breaker_open`, `quarantine`,
    `shed_burst`, `wal_stall`) promotes the ring into a retained dump
    on the `siddhi-trace-export` thread, which also auto-exports Chrome
    `trace_event` JSON (with hostname metadata) to the configured dir.
    Per-kind cooldown bounds dump churn.

The overhead contract (docs/OBSERVABILITY.md): tracing off
(`@app:trace('off')` -> `rt.tracing is None`) or on-but-unsampled
costs <= 5 % of config-3 TCP-ingest eps — the unsampled hot path is
one counter increment and a modulo per frozen frame, and every other
hook is gated on a `None` handle check.
"""
from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..utils.locks import new_lock

# the trigger registry: every kind a dump can cite, with the site that
# fires it (all sites enqueue-only — the promotion/export work runs on
# the siddhi-trace-export thread, never under an engine lock)
TRIGGER_KINDS = (
    "slo_breach",     # autotune.SLOController: decision-window p99 > target
    "breaker_open",   # io.Sink: a per-sink circuit breaker opened
    "quarantine",     # runtime: a device plan quarantined onto the interpreter
    "shed_burst",     # net.admission: frames shed by rate limit / watermark
    "wal_stall",      # core.wal: a durability barrier exceeded its budget
    "host_share_breach",  # core.profiler: windowed host-dispatch share
                          # above @app:hostShareAlert — the profile dump
)

# span names the engine records (docs/OBSERVABILITY.md span taxonomy)
SPAN_NAMES = ("frame", "admit", "wal.append", "freeze", "dispatch",
              "materialize", "sink.publish")


class TraceHandle:
    """One frame's trace carrier.  `head` is the span id the NEXT span
    parents on; `mark()` advances it, so sequential stages chain and a
    hand-off to another thread keeps the causal link (the handle object
    itself crosses the thread boundary on the Work/EventBatch/outbox
    entry it rides)."""

    __slots__ = ("tracer", "trace_id", "head")

    def __init__(self, tracer: "FrameTracer", trace_id: str, head: int = 0):
        self.tracer = tracer
        self.trace_id = trace_id
        self.head = head

    def mark(self, name: str, t0: float, dur: float, **args) -> int:
        """Record one completed span (t0 = perf_counter at start) as a
        child of the current head; the new span becomes the head."""
        sid = self.tracer._record(self.trace_id, self.head, name, t0, dur,
                                  args or None)
        self.head = sid
        return sid

    def ctx(self) -> tuple:
        """(trace_id, head) — the resumable wire/payload form
        (`FrameTracer.resume`)."""
        return (self.trace_id, self.head)


class FrameTracer:
    """Per-runtime span recorder + trigger-promoted flight dumps."""

    def __init__(self, app_name: str, sample_every: int = 16,
                 export_dir: Optional[str] = None,
                 cooldown_s: float = 5.0, capacity: int = 8192,
                 max_dumps: int = 8):
        self.app = app_name
        # 1 in N server-assigned frames gets a trace; 0 disables
        # server-assigned sampling (producer-stamped ids still trace)
        self.sample_every = int(sample_every)
        self.export_dir = export_dir or os.environ.get("SIDDHI_TRACE_DIR")
        self.cooldown_s = float(cooldown_s)
        self.hostname = socket.gethostname()
        self._tag = f"{self.hostname.split('.')[0]}-{os.getpid():x}"
        # completed spans: (trace_id, span_id, parent_id, name, t0_rel,
        # dur, thread_name, args|None).  deque.append is atomic under
        # the GIL — the one hot-path mutation stays lock-free by design
        self._ring: deque = deque(maxlen=int(capacity))
        self._epoch = time.perf_counter()
        self._epoch_wall = time.time()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._frame_ctr = itertools.count(0)
        self._lock = new_lock("FrameTracer._lock")
        # trigger -> dump machinery (exporter thread owns the slow work)
        self.dumps: deque = deque(maxlen=int(max_dumps))
        self._pending: list = []
        self._last_trigger: dict = {}
        self._wake = threading.Event()
        # a never-started placeholder (is_alive() False): _ensure_exporter
        # swaps in a live one per burst; the constructor assignment also
        # pins the attr's type for the concurrency self-analysis, so
        # `.start()` resolves to threading.Thread, not an engine class
        self._exporter = threading.Thread(name="siddhi-trace-export",
                                          daemon=True)
        self._closed = False
        # gauges (statistics()["tracing"])
        self.traces_started = 0
        self.producer_traces = 0
        self.trigger_counts: dict = {}
        self.triggers_suppressed = 0
        self.exported_files = 0

    # -- recording -----------------------------------------------------------

    def begin_frame(self, stream_id: str, trace_id: Optional[str] = None,
                    parent: int = 0) -> Optional[TraceHandle]:
        """Start a frame trace.  A producer-stamped `trace_id` (wire
        TRACE frame) always traces; otherwise the sampling decision is
        made here — `None` means this frame is unsampled and every
        downstream hook stays on its no-op path.  `parent` is the
        upstream engine's head span id (the TRACE frame's `span`
        field): span ids are only unique per host, so it is recorded as
        the root marker's `remote_parent` annotation — federation
        merges the cross-hop edge via (trace_id, remote_parent) without
        colliding with local span ids."""
        if trace_id is None:
            se = self.sample_every
            if se <= 0 or next(self._frame_ctr) % se:
                return None
            trace_id = f"{self._tag}-{next(self._trace_ids):x}"
            with self._lock:
                self.traces_started += 1
        else:
            with self._lock:
                self.traces_started += 1
                self.producer_traces += 1
        h = TraceHandle(self, str(trace_id))
        # zero-duration root marker: every stage span descends from it
        extra = {"remote_parent": int(parent)} if parent else {}
        h.mark("frame", time.perf_counter(), 0.0, stream=stream_id,
               **extra)
        return h

    def resume(self, trace_id: str, head: int = 0) -> TraceHandle:
        """Re-attach to a trace from its resumable ctx (ErrorStore
        payload replay, cross-hop continuations)."""
        return TraceHandle(self, str(trace_id), int(head))

    def _record(self, trace_id: str, parent: int, name: str, t0: float,
                dur: float, args: Optional[dict]) -> int:
        sid = next(self._span_ids)
        self._ring.append((trace_id, sid, parent, name,
                           t0 - self._epoch, dur,
                           threading.current_thread().name, args))
        return sid

    # -- read side -----------------------------------------------------------

    def spans(self) -> list:
        """Snapshot of the ring as dicts (tests / the trace endpoint)."""
        return [self._span_dict(s) for s in list(self._ring)]

    @staticmethod
    def _span_dict(s: tuple) -> dict:
        trace_id, sid, parent, name, t0, dur, thread, args = s
        d = {"trace": trace_id, "span": sid, "parent": parent,
             "name": name, "t0_s": round(t0, 6), "dur_s": round(dur, 6),
             "thread": thread}
        if args:
            d["args"] = dict(args)
        return d

    def traces(self) -> dict:
        """{trace_id: [span dicts]} over the current ring."""
        out: dict = {}
        for s in list(self._ring):
            out.setdefault(s[0], []).append(self._span_dict(s))
        return out

    def chrome_events(self, spans: Optional[list] = None,
                      pid: int = 1) -> list:
        """Chrome `trace_event` array for a span snapshot: "X" duration
        events per span plus thread_name metadata, threads mapped to
        stable integer tids."""
        raw = list(self._ring) if spans is None else spans
        tids: dict = {}
        evs = []
        for trace_id, sid, parent, name, t0, dur, thread, args in raw:
            tid = tids.setdefault(thread, len(tids) + 1)
            ev = {"name": name, "cat": "frame", "ph": "X",
                  "ts": round(t0 * 1e6, 1), "dur": round(dur * 1e6, 1),
                  "pid": pid, "tid": tid,
                  "args": {"trace": trace_id, "span": sid,
                           "parent": parent, **(args or {})}}
            evs.append(ev)
        meta = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                 "args": {"name": f"{self.hostname}/{self.app}"}}]
        for thread, tid in sorted(tids.items(), key=lambda kv: kv[1]):
            meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                         "tid": tid, "args": {"name": thread}})
        return meta + evs

    def chrome_dump(self, spans: Optional[list] = None,
                    extra_meta: Optional[dict] = None) -> dict:
        """The exported/HTTP-served object form: {"traceEvents": [...],
        "metadata": {hostname, app, ...}} — hostname rides every dump so
        cross-host federation can merge them."""
        raw = list(self._ring) if spans is None else spans
        slowest = None
        for s in raw:
            if s[3] == "frame":
                continue                    # zero-dur root markers
            if slowest is None or s[5] > slowest[5]:
                slowest = s
        meta = {"hostname": self.hostname, "app": self.app,
                "epoch_unix_s": round(self._epoch_wall, 3),
                "spans": len(raw)}
        if slowest is not None:
            meta["slowest"] = {"name": slowest[3],
                               "dur_ms": round(slowest[5] * 1e3, 4),
                               "trace": slowest[0],
                               **({"args": slowest[7]} if slowest[7]
                                  else {})}
        if extra_meta:
            meta.update(extra_meta)
        return {"traceEvents": self.chrome_events(raw), "metadata": meta}

    # -- triggers ------------------------------------------------------------

    def trigger(self, kind: str, detail: str = "") -> bool:
        """Ask for a retained dump.  NONBLOCKING and safe under engine
        locks: this only enqueues — snapshotting the ring, building the
        dump, and writing the export file all happen on the
        `siddhi-trace-export` thread.  Per-kind cooldown; returns
        whether the trigger was accepted."""
        if self._closed:
            return False
        now = time.monotonic()
        with self._lock:
            last = self._last_trigger.get(kind)
            if last is not None and now - last < self.cooldown_s:
                self.triggers_suppressed += 1
                return False
            self._last_trigger[kind] = now
            self.trigger_counts[kind] = self.trigger_counts.get(kind, 0) + 1
            self._pending.append((kind, str(detail), time.time()))
        self._wake.set()
        self._ensure_exporter()
        return True

    def _ensure_exporter(self) -> None:
        # the thread is CONSTRUCTED and STARTED outside the tracer lock
        # (trigger() may be called under engine locks; a spawn must not
        # widen that hold) — only the reference swap is guarded, and a
        # loser that finds the slot already live never starts its thread
        t = threading.Thread(target=self._export_loop,
                             name="siddhi-trace-export", daemon=True)
        with self._lock:
            if self._exporter.is_alive():
                return
            self._exporter = t
        t.start()

    def _export_loop(self) -> None:
        """Drain pending triggers; self-terminates after a short idle so
        a runtime that never shuts down cleanly cannot leak a live
        thread past the conftest leak gate."""
        while True:
            self._wake.wait(0.5)
            self._wake.clear()
            worked = False
            while True:
                with self._lock:
                    item = self._pending.pop(0) if self._pending else None
                if item is None:
                    break
                worked = True
                try:
                    self._promote(item)
                except Exception:
                    # a failed export must never kill the exporter loop
                    # mid-queue; the dump is simply lost
                    pass
            if self._closed or not worked:
                with self._lock:
                    if not self._pending:
                        # leave self._exporter pointing at THIS (about to
                        # finish) thread: is_alive() goes False and the
                        # next trigger swaps in a fresh one
                        return

    def _promote(self, item: tuple) -> None:
        """One trigger -> retained dump (+ optional file export)."""
        kind, detail, wall_ts = item
        spans = list(self._ring)
        dump = {"reason": kind, "detail": detail,
                "at_unix_s": round(wall_ts, 3), "spans": len(spans),
                "chrome": self.chrome_dump(
                    spans, extra_meta={"reason": kind, "detail": detail})}
        # export BEFORE publication: a dump visible through dumps /
        # dump_summaries / statistics()["tracing"] must never mutate
        # afterwards — the old order set dump["path"] outside the lock
        # on an already-published dict, a torn read for any scraper
        path = None
        if self.export_dir:
            try:
                os.makedirs(self.export_dir, exist_ok=True)
                safe_app = self.app.replace(os.sep, "_") or "_app"
                with self._lock:
                    n = self.exported_files
                path = os.path.join(
                    self.export_dir, f"trace-{safe_app}-{kind}-{n}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(dump["chrome"], f)
                os.replace(tmp, path)
            except OSError:
                path = None
        with self._lock:
            if path is not None:
                dump["path"] = path
                self.exported_files += 1
            self.dumps.append(dump)

    # -- lifecycle / telemetry ----------------------------------------------

    def close(self, timeout: float = 2.0) -> None:
        """Flush pending triggers and join the exporter (bounded)."""
        self._closed = True
        with self._lock:
            t = self._exporter
        self._wake.set()
        if t.ident is not None:     # never-started placeholder: no join
            t.join(timeout=timeout)

    def reopen(self) -> None:
        """Re-arm a closed tracer (a shutdown()/start() cycle in one
        process — the WAL-reopen analog): triggers enqueue again and the
        exporter respawns on the next one.  The ring and counters carry
        across generations; a no-op on a live tracer."""
        self._closed = False

    def metrics(self) -> dict:
        with self._lock:
            return {"sample_every": self.sample_every,
                    "ring_spans": len(self._ring),
                    "traces_started": self.traces_started,
                    "producer_traces": self.producer_traces,
                    "dumps": len(self.dumps),
                    "triggers": dict(self.trigger_counts),
                    "triggers_suppressed": self.triggers_suppressed,
                    "exported_files": self.exported_files}

    def dump_summaries(self) -> list:
        with self._lock:
            return [{k: v for k, v in d.items() if k != "chrome"}
                    for d in self.dumps]


def tracer_from_annotations(app) -> Optional[FrameTracer]:
    """Build the runtime's tracer from `@app:trace(...)`:

        @app:trace('off')                 -- rt.tracing is None (zero cost)
        @app:trace('all')                 -- every frame traced
        (default / 'sampled')             -- 1 in 16 frames traced
        @app:trace(sample='64')           -- 1 in 64
        @app:trace(dir='/var/traces')     -- triggered-dump export dir
        @app:trace(cooldown='1')          -- per-kind trigger cooldown (s)

    $SIDDHI_TRACE_DIR supplies the export dir when `dir=` is absent;
    $SIDDHI_TRACE_SAMPLE overrides the default sampling for apps
    without the annotation."""
    from ..query import ast as qast
    ann = qast.find_annotation(app.annotations, "app:trace")
    mode = None
    sample = None
    export_dir = None
    cooldown = 5.0
    if ann is not None:
        mode = (ann.element() or "").lower() or None
        for k, v in ann.elements:
            if k is None:
                continue
            kl = k.lower()
            if kl == "sample":
                sample = int(v)
            elif kl == "dir":
                export_dir = v
            elif kl == "cooldown":
                cooldown = float(v)
    if mode == "off":
        return None
    if mode in ("on", "all"):
        sample = 1
    if sample is None:
        env = os.environ.get("SIDDHI_TRACE_SAMPLE")
        sample = int(env) if env else 16
    return FrameTracer(app.name, sample_every=sample,
                       export_dir=export_dir, cooldown_s=cooldown)
