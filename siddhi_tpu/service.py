"""REST control plane + columnar serving data plane.

Reference: modules/siddhi-service (JAX-RS/MSF4J microservice,
`POST /siddhi/artifact/deploy`, `GET /siddhi/artifact/undeploy`,
src/gen/.../api/SiddhiApi.java:31-63).

The HTTP surface is the CONTROL plane (deploy/undeploy/query/stats/
errors/metrics) plus a convenience JSON event endpoint; production
traffic enters through the DATA plane — a NetServer (siddhi_tpu/net)
speaking the columnar frame protocol over TCP and WebSocket on its own
port (`service.net_port`), feeding every deployed app with zero
per-event Python and per-stream admission control (docs/SERVING.md).

Endpoints (JSON unless noted):
  POST /siddhi/artifact/deploy      body = SiddhiQL app text (plain)
  GET  /siddhi/artifact/undeploy?siddhiApp=<name>
  GET  /siddhi/artifact/apps
  POST /siddhi/artifact/event       {"app": ..., "stream": ..., "data": [...],
                                     "timestamp": optional ms}
                                    `data` may be ONE row or a LIST of
                                    rows (batch form, one shared
                                    optional timestamp), or pass
                                    "events": [{"data": [...],
                                    "timestamp": ...}, ...] — all forms
                                    share one validation path; malformed
                                    bodies get a 400 JSON error.  The
                                    batch rides the stream's admission
                                    controller (same quotas/shed
                                    accounting as the frame plane): a
                                    rate-limited stream sheds REST
                                    traffic into the ErrorStore with a
                                    429, or parks it with a 202 under
                                    shed.policy='oldest'
  POST /siddhi/artifact/snapshot    {"app": ..., "incremental": bool?}
                                    persist a revision NOW; returns its
                                    structured descriptor — revision id +
                                    per-stream durable WAL watermark
                                    (persistence.Revision.to_dict())
  GET  /siddhi/artifact/snapshot?siddhiApp=<name>
                                    durability state: sync policy, last
                                    revision descriptor, WAL gauges, and
                                    the last crash-recovery report
  POST /siddhi/artifact/query       {"app": ..., "query": "from T select ..."}
  GET  /siddhi/artifact/stats?siddhiApp=<name>
  GET  /siddhi/artifact/explain?siddhiApp=<name>
                                    the EXPLAIN plane (docs/ANALYSIS.md):
                                    rt.explain() verbatim — per-query
                                    placement (device vs interpreter),
                                    chosen plan family, geometry
                                    provenance, and the full Demotion
                                    reason chain for every rejected
                                    alternative
  GET  /metrics[?siddhiApp=<name>]  Prometheus text exposition (0.0.4) over
                                    every deployed app (or just <name>);
                                    the per-stream dispatch-latency
                                    histogram buckets carry OpenMetrics
                                    trace-id exemplars
  GET  /siddhi/artifact/trace[?siddhiApp=<name>]
                                    the frame-tracing plane
                                    (docs/OBSERVABILITY.md "Frame
                                    tracing"): Chrome trace_event JSON
                                    ({"traceEvents": [...], "metadata":
                                    {hostname, apps, dumps}}) of the
                                    live span ring — load in
                                    chrome://tracing / ui.perfetto.dev;
                                    `metadata.dumps` lists trigger-
                                    promoted retained dumps
  GET  /siddhi/artifact/profile[?siddhiApp=<name>&window=<n>]
                                    the device-time attribution plane
                                    (docs/OBSERVABILITY.md "Device-time
                                    profiling"): per-plan phase shares,
                                    host-dispatch share, windowed ring
                                    (last <n> snapshots), roofline fold
  GET  /siddhi/artifact/tuning[?siddhiApp=<name>]
                                    the persisted execution-geometry tuning
                                    cache (docs/AUTOTUNING.md): entries +
                                    hit/miss gauges, or one app's view
  GET  /siddhi/net                  data-plane descriptor: frame port +
                                    per-stream admission/transport gauges
  GET  /siddhi/errors?siddhiApp=<name>[&stream=<id>]
                                    list the app's ErrorStore entries
                                    (@OnError(action='store') captures,
                                    exhausted sink publishes, net sheds)
  POST /siddhi/errors               {"app": ..., "action": "replay"|
                                     "discard", "ids": optional [int]}
                                    replay captured events/payloads through
                                    the live runtime, or drop them

Deployed runtimes run with statistics ENABLED (a served engine is meant
to be scraped; one clock read per micro-batch) unless the app itself
says `@app:statistics('false')`.

Run:  python -m siddhi_tpu.service [port]     (or SiddhiService(port).start())
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import SiddhiManager
from .core.telemetry import render_prometheus
from .query import ast as qast
from .utils.locks import new_lock

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# negotiated via the Accept header: exemplar syntax is only legal in
# OpenMetrics — a classic 0.0.4 parser rejects a line carrying one
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


class _ControlServer(ThreadingHTTPServer):
    """Handler threads are daemons AND tracked, so `stop()` can join
    them with a bounded timeout — test runs and bench teardown never
    hang on a stuck keep-alive connection."""

    daemon_threads = True
    block_on_close = False      # stdlib would join unbounded; we bound it

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self._handler_threads: list = []
        self._threads_lock = new_lock("_ControlServer._threads_lock")

    def process_request(self, request, client_address):
        t = threading.Thread(target=self.process_request_thread,
                             args=(request, client_address),
                             name="siddhi-http", daemon=True)
        with self._threads_lock:
            self._handler_threads = [th for th in self._handler_threads
                                     if th.is_alive()] + [t]
        t.start()

    def join_handlers(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        with self._threads_lock:
            threads = list(self._handler_threads)
            self._handler_threads = []
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))


class SiddhiService:
    def __init__(self, port: int = 0, manager: Optional[SiddhiManager] = None,
                 net: bool = True, net_port: int = 0):
        self.manager = manager or SiddhiManager()
        self.runtimes: dict = {}
        self._stopping = False          # unblocks 'block'-policy REST waits
        # serializes deploy/undeploy/stop: the control server handles
        # requests on concurrent threads, and two same-name deploys
        # racing each other used to BOTH start a runtime — the loser
        # leaked alive (scheduler thread and all), never retired, never
        # shut down.  Ops are rare; correctness beats parallel deploys.
        self._ops_lock = new_lock("SiddhiService._ops_lock")
        # ErrorStores of undeployed apps: frames admitted by the data
        # plane before an undeploy land here (never dropped), and stay
        # inspectable until the name is redeployed
        self.retired_errors: dict = {}
        # app name -> static-analysis findings (dicts) from deploy time;
        # the deploy response carries them (docs/ANALYSIS.md)
        self.diagnostics: dict = {}
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):           # quiet
                pass

            def _reply(self, code: int, body: dict) -> None:
                blob = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _reply_text(self, code: int, text: str,
                            ctype: str = PROM_CONTENT_TYPE) -> None:
                blob = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n)

            def do_POST(self):
                path = urlparse(self.path).path
                try:
                    if path == "/siddhi/artifact/deploy":
                        name = service.deploy(self._body().decode())
                        self._reply(200, {
                            "status": "deployed", "app": name,
                            # static-analysis findings for the deployed
                            # app (docs/ANALYSIS.md) — under
                            # @app:strictAnalysis a warn/error finding
                            # fails the deploy instead (400 below)
                            "diagnostics": service.diagnostics.get(name,
                                                                   [])})
                    elif path == "/siddhi/artifact/event":
                        body = self._body()
                        try:
                            req = json.loads(body)
                        except ValueError as e:
                            raise ValueError(f"body is not JSON: {e}") \
                                from None
                        code, out = service.send_events(req,
                                                        nbytes=len(body))
                        self._reply(code, out)
                    elif path == "/siddhi/artifact/snapshot":
                        req = json.loads(self._body())
                        app = req.get("app")
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.snapshot_action(
                                app, bool(req.get("incremental"))))
                    elif path == "/siddhi/artifact/promote":
                        req = json.loads(self._body() or b"{}")
                        app = req.get("app")
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.promote(app))
                    elif path == "/siddhi/artifact/query":
                        req = json.loads(self._body())
                        rows = service.store_query(req["app"], req["query"])
                        self._reply(200, {"rows": rows})
                    elif path == "/siddhi/errors":
                        req = json.loads(self._body())
                        app = req.get("app")
                        if (app not in service.runtimes
                                and app not in service.retired_errors):
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.errors_action(
                                app, req.get("action", "replay"),
                                req.get("ids")))
                    else:
                        self._reply(404, {"error": f"no route {path}"})
                except Exception as e:
                    # EVERY failure is a 400 JSON error — a malformed
                    # body must never surface as a 500 stack trace.  A
                    # strict-analysis rejection additionally ships the
                    # structured findings so the caller sees rule ids,
                    # not just prose
                    body = {"error": f"{type(e).__name__}: {e}"}
                    findings = getattr(e, "findings", None)
                    if findings is not None:
                        body["diagnostics"] = [f.to_dict()
                                               for f in findings]
                    self._reply(400, body)

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    if u.path == "/siddhi/artifact/undeploy":
                        app = q.get("siddhiApp", [None])[0]
                        service.undeploy(app)
                        self._reply(200, {"status": "undeployed", "app": app})
                    elif u.path == "/siddhi/artifact/apps":
                        self._reply(200, {"apps": sorted(service.runtimes)})
                    elif u.path == "/siddhi/artifact/stats":
                        app = q.get("siddhiApp", [None])[0]
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.stats(app))
                    elif u.path == "/siddhi/artifact/explain":
                        app = q.get("siddhiApp", [None])[0]
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            # rt.explain() VERBATIM: the test suite holds
                            # this body byte-for-byte equal to it
                            self._reply(200, service.explain(app))
                    elif u.path == "/siddhi/artifact/snapshot":
                        app = q.get("siddhiApp", [None])[0]
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.snapshot_info(app))
                    elif u.path == "/siddhi/errors":
                        app = q.get("siddhiApp", [None])[0]
                        if (app not in service.runtimes
                                and app not in service.retired_errors):
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.errors(
                                app, q.get("stream", [None])[0]))
                    elif u.path == "/siddhi/artifact/trace":
                        app = q.get("siddhiApp", [None])[0]
                        if app is not None and app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.trace(app))
                    elif u.path == "/siddhi/artifact/profile":
                        app = q.get("siddhiApp", [None])[0]
                        if app is not None and app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            w = q.get("window", [None])[0]
                            self._reply(200, service.profile(
                                app, window=None if w is None else int(w)))
                    elif u.path == "/siddhi/artifact/tuning":
                        app = q.get("siddhiApp", [None])[0]
                        if app is not None and app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.tuning(app))
                    elif u.path == "/siddhi/net":
                        self._reply(200, service.net_info())
                    elif u.path == "/metrics":
                        app = q.get("siddhiApp", [None])[0]
                        if app is not None and app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            # content negotiation: Prometheus asks for
                            # OpenMetrics by default and gets the
                            # exemplar-carrying form; anything else gets
                            # classic 0.0.4 (exemplars stripped — they
                            # are illegal in that format)
                            om = "application/openmetrics-text" in \
                                (self.headers.get("Accept") or "")
                            self._reply_text(
                                200, service.metrics(app, openmetrics=om),
                                ctype=OPENMETRICS_CONTENT_TYPE if om
                                else PROM_CONTENT_TYPE)
                    else:
                        self._reply(404, {"error": f"no route {u.path}"})
                except Exception as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})

        self.httpd = _ControlServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # the data plane: one shared frame server over every deployed
        # app; admission controllers are per (app, stream) and shared
        # with any @source(type='tcp'|'shm') the app itself declares
        self.net = None
        self.net_port = None
        if net:
            from .net.server import NetServer
            self.net = NetServer(self._net_resolve, port=net_port,
                                 name="siddhi-service-net",
                                 repl_resolve=self._repl_resolve,
                                 query_resolve=self._query_resolve)
            self.net_port = self.net.port

    # -- data plane -------------------------------------------------------

    def _net_resolve(self, app: Optional[str], stream: str):
        rt = self.runtimes.get(app or "")
        if rt is None:
            raise KeyError(f"no deployed app {app!r}")
        if rt.is_standby():
            # a replica serves nothing: producers must talk to the
            # primary (or promote this node first) — rejecting at HELLO
            # keeps their retransmit buffers intact
            raise KeyError(
                f"app {app!r} is a standby replica — promote it or "
                f"send to the primary")
        ctrl = rt.admission.get(stream)
        if ctrl is None:
            if stream not in rt.schemas:
                raise KeyError(f"app {app!r} has no stream {stream!r}")
            from .net.admission import controller_from_options
            # default controller: unlimited rate, pure accounting —
            # declare @source(rate.limit=..., shed.policy=...) on the
            # stream to arm real limits (the SAME controller then
            # governs both the app's own port and this front door).
            # setdefault: concurrent HELLOs race this insert — exactly
            # one controller may win or accounting splits across two
            ctrl = rt.admission.setdefault(
                stream, controller_from_options(stream, {}, rt))
        return rt, ctrl

    def _repl_resolve(self, app: str):
        """REPL_SUBSCRIBE resolution for the data plane: the app's
        runtime (the shipper-side checks — durability, standby role —
        live in net/server.py)."""
        rt = self.runtimes.get(app or "")
        if rt is None:
            raise KeyError(f"no deployed app {app!r}")
        return rt

    def _query_resolve(self, app: str):
        """QUERY-frame resolution: store queries naming an app run
        against its deployed runtime — the same compile cache and feed
        gate `POST /siddhi/artifact/query` goes through."""
        rt = self.runtimes.get(app or "")
        if rt is None:
            raise KeyError(f"no deployed app {app!r}")
        return rt

    def net_info(self) -> dict:
        if self.net is None:
            return {"enabled": False}
        streams = {}
        # list() snapshots: connection threads insert controllers at
        # HELLO time, racing this scrape
        for name, rt in list(self.runtimes.items()):
            for sid, ctrl in list(rt.admission.items()):
                streams[f"{name}/{sid}"] = ctrl.metrics()
        return {"enabled": True, "port": self.net.port,
                "server": self.net.metrics(), "streams": streams}

    # -- operations -------------------------------------------------------

    def deploy(self, app_text: str) -> str:
        # the build runs OUTSIDE the ops lock (slow: device lowering);
        # the swap of the live runtime under the name is what must not
        # interleave with another deploy/undeploy of the same name
        rt = self.manager.create_app_runtime(app_text)
        with self._ops_lock:
            # a same-name redeploy shuts the old runtime down (bounded
            # joins) while holding the ops lock: that wait IS the
            # serialization — no other deploy may see the half-swapped name
            # lint: allow (bounded teardown join under the ops lock by design)
            return self._install(rt)

    def _install(self, rt) -> str:
        name = rt.app.name
        # deploy-time lint (docs/ANALYSIS.md): the findings ride the
        # deploy response; @app:strictAnalysis apps never reach here
        # with warn/error findings (the runtime constructor raised)
        from .analysis import analyze_app
        try:
            self.diagnostics[name] = [f.to_dict()
                                      for f in analyze_app(rt.app)]
        except Exception as e:   # lint: allow-swallow (diagnostics are
            # advisory — an analyzer crash must never block a deploy)
            self.diagnostics[name] = [{
                "rule_id": "SA00", "severity": "info",
                "message": f"analyzer failed: {type(e).__name__}: {e}"}]
        # served runtimes default statistics ON (the /metrics scrape is
        # the point of running as a service); an @app:statistics annotation
        # of any flavor was already applied by the runtime constructor
        if qast.find_annotation(rt.app.annotations, "app:statistics") is None:
            rt.enable_stats(True)
        old = self.runtimes.pop(name, None)
        if old is not None:
            if self.net is not None:
                self.net.retire(old)
            self._park_errors(name, old.error_store)
            old.shutdown()
        # recover-on-redeploy (docs/RELIABILITY.md): a durable app
        # restores its newest snapshot and replays the WAL suffix
        # BEFORE serving — a service restart or same-name redeploy
        # resumes exactly where the durable log ends, instead of
        # parking-only.  (The old runtime above shut down first, so
        # its final barrier landed before this replay scans the log.)
        cfg = getattr(rt, "replication_config", None)
        if rt.durability != "off" and not (cfg is not None
                                           and cfg.role == "standby"):
            # standby replicas do NOT recover at deploy: their state
            # materializes at promote() from the replicated log + the
            # shipped revisions (rt.start() enters standby mode)
            rt.recover()
        rt.start()
        self.runtimes[name] = rt
        return name

    def undeploy(self, name: str) -> None:
        with self._ops_lock:
            rt = self.runtimes.pop(name)
            self.diagnostics.pop(name, None)
            # retire FIRST: the data plane serializes this against
            # in-flight feeds, so every admitted frame either reached the
            # live runtime or lands whole in the (parked) ErrorStore —
            # never dropped
            if self.net is not None:
                self.net.retire(rt)
            self._park_errors(name, rt.error_store)
            # lint: allow (bounded teardown join under the ops lock by design)
            rt.shutdown()

    def _park_errors(self, name: str, store) -> None:
        """Park a retiring runtime's ErrorStore under its app name.  A
        PREVIOUS generation's still-unreplayed entries must survive the
        churn ('never dropped'): they merge INTO the retiring store,
        oldest generation first.  The INCOMING store is always the one
        parked — the data plane's retire() pointed in-flight feeds at
        it, so frames admitted before the undeploy but fed after this
        call still land somewhere reachable (merging the other way
        would orphan them in a store nothing lists or replays)."""
        prev = self.retired_errors.get(name)
        self.retired_errors[name] = store
        if prev is None or prev is store or not len(prev):
            return
        newer = store.take(None)
        for e in prev.take(None):       # fresh ids: two generations'
            store.add(e.stream_id, e.point, e.message,    # counters both
                      e.timestamp_ms, events=e.events,    # start at 1
                      payloads=e.payloads, sink=e.sink)
        for e in newer:
            store._readd(e)

    def send_events(self, req: dict, nbytes: int = 0) -> tuple:
        """Shared validation for the single-event AND batch JSON forms;
        raises ValueError (→ 400) on anything malformed.  Returns
        (http_code, body): admitted requests ingest and return
        200 {"status": "ok"}; the batch rides the stream's
        AdmissionController — the SAME quotas, shed accounting, and
        telemetry as the frame plane (docs/SERVING.md) — so under a
        rate limit REST traffic sheds into the replayable ErrorStore
        (429 {"status": "shed"}) or parks ('oldest' policy,
        202 {"status": "queued"}) instead of jumping the line."""
        if not isinstance(req, dict):
            raise ValueError("body must be a JSON object")
        app = req.get("app")
        rt = self.runtimes.get(app)
        if rt is None:
            raise ValueError(f"no deployed app {app!r}")
        stream = req.get("stream")
        if stream not in rt.schemas:
            raise ValueError(f"app {app!r} has no stream {stream!r}")
        attrs = rt.schemas[stream].attributes
        n_attrs = len(attrs)
        events: list = []

        def _row(data, ts, where: str):
            if not isinstance(data, (list, tuple)):
                raise ValueError(f"{where}: 'data' must be a list")
            if len(data) != n_attrs:
                raise ValueError(
                    f"{where}: stream {stream!r} expects {n_attrs} "
                    f"attributes, got {len(data)}")
            for v, a in zip(data, attrs):
                # type-check at the boundary: a bad value admitted here
                # would only surface at flush, inside the engine's
                # batch builder — poisoning the whole runtime, not just
                # this request (malformed input must 400, never 500)
                t = a.type.name
                if t in ("INT", "LONG", "FLOAT", "DOUBLE") and (
                        isinstance(v, bool)
                        or not isinstance(v, (int, float))):
                    raise ValueError(
                        f"{where}: attribute {a.name!r} expects a "
                        f"number ({t.lower()}), got {type(v).__name__}")
                if t == "BOOL" and not isinstance(v, bool):
                    raise ValueError(
                        f"{where}: attribute {a.name!r} expects a bool, "
                        f"got {type(v).__name__}")
            if ts is not None and not isinstance(ts, (int, float)):
                raise ValueError(f"{where}: 'timestamp' must be a number")
            events.append((tuple(data),
                           int(ts) if ts is not None else None))

        if "events" in req:
            evs = req["events"]
            if not isinstance(evs, list):
                raise ValueError("'events' must be a list of objects")
            for i, ev in enumerate(evs):
                if not isinstance(ev, dict) or "data" not in ev:
                    raise ValueError(
                        f"events[{i}] must be an object with 'data'")
                _row(ev["data"], ev.get("timestamp"), f"events[{i}]")
        else:
            data = req.get("data")
            ts = req.get("timestamp")
            if isinstance(data, list) and data \
                    and isinstance(data[0], (list, tuple)):
                for i, row in enumerate(data):       # batch of rows
                    _row(row, ts, f"data[{i}]")
            else:
                _row(data, ts, "event")
        from .net.admission import (ADMIT, QUEUED, SHED, Work,
                                    controller_from_options)
        ctrl = rt.admission.get(stream)
        if ctrl is None:
            ctrl = rt.admission.setdefault(
                stream, controller_from_options(stream, {}, rt))

        def feed():
            for data, ts in events:
                rt.send(stream, data, ts)
            rt.flush()

        def rows():
            now = rt.now_ms()
            return [(ts if ts is not None else now, tuple(data))
                    for data, ts in events]

        work = Work(n=len(events), nbytes=nbytes or len(events) * 64,
                    feed=feed, rows=rows, stream_id=stream)
        # 'block' policy stalls THIS handler thread (the HTTP analogue
        # of a stalled socket reader); shutdown stays responsive
        d = ctrl.submit(work, stop=lambda: self._stopping)
        for w in d.ready:
            # guarded: a failure in OTHER queued work must not 400 this
            # request or vanish — it captures to the app's ErrorStore
            ctrl.feed_safely(w)
        if d.action == ADMIT:
            work.feed()
            return 200, {"status": "ok", "events": len(events)}
        if d.action == QUEUED:
            return 202, {"status": "queued", "events": len(events)}
        assert d.action == SHED
        return 429, {"status": "shed", "events": len(events),
                     "stored": True,
                     "detail": "rate limit exceeded; events captured in "
                               "the ErrorStore (POST /siddhi/errors "
                               "action=replay to re-ingest)"}

    # back-compat embedding surface
    def send_event(self, app: str, stream: str, data: tuple,
                   timestamp=None) -> None:
        self.send_events({"app": app, "stream": stream,
                          "data": list(data), "timestamp": timestamp})

    def store_query(self, app: str, text: str) -> list:
        return [[ts, list(row)] for ts, row in self.runtimes[app].query(text)]

    def stats(self, app: str) -> dict:
        return self.runtimes[app].stats.report()

    def explain(self, app: str) -> dict:
        """rt.explain() verbatim (core/placement.py) — placement +
        demotion reason chains for every query of a deployed app."""
        return self.runtimes[app].explain()

    def _error_stores(self, app: str) -> tuple:
        """(live_store_or_None, parked_store_or_None) for `app` — the
        parked store holds frames admitted before an undeploy (or a
        same-name redeploy) of the name."""
        rt = self.runtimes.get(app)
        live = rt.error_store if rt is not None else None
        parked = self.retired_errors.get(app)
        if live is None and parked is None:
            raise ValueError(f"no deployed app {app!r}")
        return live, parked

    def errors(self, app: str, stream: Optional[str] = None) -> dict:
        """The app's ErrorStore entries (JSON-safe dicts) — live store
        plus anything parked by an undeploy of the same name."""
        live, parked = self._error_stores(app)
        out: list = []
        evicted = 0
        for store, is_parked in ((live, False), (parked, True)):
            if store is None:
                continue
            for e in store.entries(stream):
                d = e.to_dict()
                if is_parked:
                    d["parked"] = True
                out.append(d)
            evicted += store.evicted
        return {"errors": out, "evicted": evicted}

    def errors_action(self, app: str, action: str, ids=None) -> dict:
        """Replay (re-ingest events / re-publish payloads) or discard
        captured failures.  Replay drains the parked store of an
        undeployed-then-redeployed name into the live runtime; an app
        that is not deployed can only be discarded (redeploy to replay).

        The live and parked stores number entries independently, so an
        explicit id could name one entry in EACH: ids resolve against
        the live store first, and only ids the live store does not hold
        reach the parked one — an action aimed at a live entry can
        never also consume an unrelated parked entry (ids=None still
        means everything in both)."""
        live, parked = self._error_stores(app)
        parked_ids = ids
        if ids is not None and live is not None and parked is not None:
            held = {e.id for e in live.entries()}
            parked_ids = [i for i in ids if i not in held]
        if action == "replay":
            rt = self.runtimes.get(app)
            if rt is None:
                raise ValueError(
                    f"app {app!r} is not deployed: redeploy it to replay "
                    f"its parked errors (or action='discard')")
            out = rt.error_store.replay(rt, ids)
            if parked is not None and len(parked):
                for k, v in parked.replay(rt, parked_ids).items():
                    out[k] = out.get(k, 0) + v
            return out
        if action == "discard":
            discarded = remaining = 0
            for store, want in ((live, ids), (parked, parked_ids)):
                if store is None:
                    continue
                discarded += len(store.take(want))
                remaining += len(store)
            return {"discarded": discarded, "remaining": remaining}
        raise ValueError(f"unknown errors action {action!r} "
                         f"(replay | discard)")

    def snapshot_action(self, app: str, incremental: bool = False) -> dict:
        """POST /siddhi/artifact/snapshot: persist a revision NOW and
        return its structured descriptor (revision id + per-stream
        durable watermark — persistence.Revision.to_dict())."""
        rt = self.runtimes[app]
        return rt.persist(incremental=incremental).to_dict()

    def promote(self, app: str) -> dict:
        """POST /siddhi/artifact/promote: fail a standby replica over
        to serving primary (rt.promote() — fence, recover to head,
        start serving).  Serialized with deploy/undeploy: a promote
        racing a redeploy of the same name must see one runtime."""
        with self._ops_lock:
            rt = self.runtimes[app]
            # lint: allow (bounded recovery join under the ops lock by design)
            return rt.promote()

    def snapshot_info(self, app: str) -> dict:
        """GET /siddhi/artifact/snapshot: the durability/recovery state
        of a deployed app — last revision descriptor (this process OR
        the store's newest), WAL gauges, and the last recovery report."""
        rt = self.runtimes[app]
        desc = rt.last_revision_descriptor
        store = rt.manager.persistence_store if rt.manager else None
        out = {
            "app": app,
            "durability": rt.durability,
            "last_revision": desc.to_dict() if desc is not None else None,
            "store_revision": (store.last_revision(app)
                               if store is not None else None),
        }
        if rt.wal is not None:
            out["wal"] = rt.wal.metrics()
        if getattr(rt, "_wal_recovery", None) is not None:
            # the last recover() report (replayed/skipped/failed/
            # corrupt/recovery_s): the post-failover audit trail —
            # also mirrored in rt.explain()["durability"]["recovery"]
            out["recovery"] = rt._wal_recovery
        if getattr(rt, "_promote_report", None) is not None:
            out["promotion"] = rt._promote_report
        coord = getattr(rt, "replication", None)
        if coord is not None:
            out["replication"] = coord.metrics()
        return out

    def trace(self, app: Optional[str] = None) -> dict:
        """GET /siddhi/artifact/trace: the frame-tracing plane as one
        Chrome `trace_event` object (docs/OBSERVABILITY.md).  Spans of
        every deployed app (or just `app`) merge with one pid per app;
        the hostname metadata is what lets cross-host federation merge
        dumps from several engines into one timeline."""
        import socket as _socket
        names = [app] if app is not None else sorted(self.runtimes)
        evs: list = []
        apps_meta: list = []
        dumps: list = []
        for i, name in enumerate(names):
            tr = getattr(self.runtimes[name], "tracing", None)
            if tr is None:
                apps_meta.append({"app": name, "tracing": False})
                continue
            evs.extend(tr.chrome_events(pid=i + 1))
            apps_meta.append({"app": name, "tracing": True,
                              **tr.metrics()})
            dumps.extend({"app": name, **d}
                         for d in tr.dump_summaries())
        return {"traceEvents": evs,
                "metadata": {"hostname": _socket.gethostname(),
                             "apps": apps_meta, "dumps": dumps}}

    def profile(self, app: Optional[str] = None,
                window: Optional[int] = None) -> dict:
        """GET /siddhi/artifact/profile: the device-time attribution
        plane (docs/OBSERVABILITY.md "Device-time profiling") — per-plan
        phase seconds/shares, host-dispatch share, windowed ring, and
        the roofline fold, for every deployed app (or just `app`).
        `window` limits each app's ring to its last N snapshots."""
        names = [app] if app is not None else sorted(self.runtimes)
        return {"apps": {n: self.runtimes[n].profile(window=window)
                         for n in names}}

    def tuning(self, app: Optional[str] = None) -> dict:
        """The persisted execution-geometry tuning cache (autotune.py):
        globally, or one deployed app's view of it (its hit/miss gauges
        and the geometries its build resolved)."""
        from .core.autotune import device_kind, jax_version, shared_cache
        if app is not None:
            rt = self.runtimes[app]
            return {"app": app, **rt.tuner.metrics()}
        c = shared_cache()
        return {"path": c.path, "device": device_kind(),
                "jax": jax_version(), "hits": c.hits, "misses": c.misses,
                "corrupt": c.corrupt, "entries": c.entries()}

    def metrics(self, app: Optional[str] = None,
                openmetrics: bool = False) -> str:
        """Text exposition rendered LIVE from every deployed runtime's
        statistics (or just `app`'s when given); `openmetrics=True` is
        the Accept-negotiated exemplar-carrying form."""
        names = [app] if app is not None else sorted(self.runtimes)
        return render_prometheus(
            {n: self.runtimes[n].stats.report() for n in names},
            openmetrics=openmetrics)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "SiddhiService":
        # short poll interval: shutdown() waits one poll tick, and the
        # default 0.5 s turns every stop (tests, bench teardown, ops
        # restarts) into a half-second stall
        self._thread = threading.Thread(
            target=lambda: self.httpd.serve_forever(poll_interval=0.05),
            name="siddhi-service", daemon=True)
        self._thread.start()
        if self.net is not None:
            self.net.start()
        return self

    def stop(self) -> None:
        self._stopping = True
        if self.net is not None:
            self.net.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # outstanding handler threads: bounded join, so teardown never
        # wedges a test run behind a stuck keep-alive
        self.httpd.join_handlers(timeout=5.0)
        with self._ops_lock:    # a straggler undeploy must not interleave
            for rt in list(self.runtimes.values()):
                # lint: allow (bounded teardown join under the ops lock)
                rt.shutdown()
            self.runtimes.clear()


if __name__ == "__main__":
    import sys
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 8006
    svc = SiddhiService(port).start()
    print(f"siddhi-tpu service on http://127.0.0.1:{svc.port}"
          + (f" (data plane :{svc.net_port})" if svc.net_port else ""))
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        svc.stop()
