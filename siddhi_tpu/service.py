"""REST deployment service — run the engine as a server.

Reference: modules/siddhi-service (JAX-RS/MSF4J microservice,
`POST /siddhi/artifact/deploy`, `GET /siddhi/artifact/undeploy`,
src/gen/.../api/SiddhiApi.java:31-63).

Endpoints (JSON unless noted):
  POST /siddhi/artifact/deploy      body = SiddhiQL app text (plain)
  GET  /siddhi/artifact/undeploy?siddhiApp=<name>
  GET  /siddhi/artifact/apps
  POST /siddhi/artifact/event       {"app": ..., "stream": ..., "data": [...],
                                     "timestamp": optional ms}
  POST /siddhi/artifact/query       {"app": ..., "query": "from T select ..."}
  GET  /siddhi/artifact/stats?siddhiApp=<name>
  GET  /metrics[?siddhiApp=<name>]  Prometheus text exposition (0.0.4) over
                                    every deployed app (or just <name>)
  GET  /siddhi/artifact/tuning[?siddhiApp=<name>]
                                    the persisted execution-geometry tuning
                                    cache (docs/AUTOTUNING.md): entries +
                                    hit/miss gauges, or one app's view
  GET  /siddhi/errors?siddhiApp=<name>[&stream=<id>]
                                    list the app's ErrorStore entries
                                    (@OnError(action='store') captures,
                                    exhausted sink publishes)
  POST /siddhi/errors               {"app": ..., "action": "replay"|
                                     "discard", "ids": optional [int]}
                                    replay captured events/payloads through
                                    the live runtime, or drop them

Deployed runtimes run with statistics ENABLED (a served engine is meant
to be scraped; one clock read per micro-batch) unless the app itself
says `@app:statistics('false')`.

Run:  python -m siddhi_tpu.service [port]     (or SiddhiService(port).start())
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import SiddhiManager
from .core.telemetry import render_prometheus
from .query import ast as qast

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class SiddhiService:
    def __init__(self, port: int = 0, manager: Optional[SiddhiManager] = None):
        self.manager = manager or SiddhiManager()
        self.runtimes: dict = {}
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):           # quiet
                pass

            def _reply(self, code: int, body: dict) -> None:
                blob = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _reply_text(self, code: int, text: str,
                            ctype: str = PROM_CONTENT_TYPE) -> None:
                blob = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n)

            def do_POST(self):
                path = urlparse(self.path).path
                try:
                    if path == "/siddhi/artifact/deploy":
                        name = service.deploy(self._body().decode())
                        self._reply(200, {"status": "deployed", "app": name})
                    elif path == "/siddhi/artifact/event":
                        req = json.loads(self._body())
                        service.send_event(req["app"], req["stream"],
                                           tuple(req["data"]),
                                           req.get("timestamp"))
                        self._reply(200, {"status": "ok"})
                    elif path == "/siddhi/artifact/query":
                        req = json.loads(self._body())
                        rows = service.store_query(req["app"], req["query"])
                        self._reply(200, {"rows": rows})
                    elif path == "/siddhi/errors":
                        req = json.loads(self._body())
                        app = req.get("app")
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.errors_action(
                                app, req.get("action", "replay"),
                                req.get("ids")))
                    else:
                        self._reply(404, {"error": f"no route {path}"})
                except Exception as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    if u.path == "/siddhi/artifact/undeploy":
                        app = q.get("siddhiApp", [None])[0]
                        service.undeploy(app)
                        self._reply(200, {"status": "undeployed", "app": app})
                    elif u.path == "/siddhi/artifact/apps":
                        self._reply(200, {"apps": sorted(service.runtimes)})
                    elif u.path == "/siddhi/artifact/stats":
                        app = q.get("siddhiApp", [None])[0]
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.stats(app))
                    elif u.path == "/siddhi/errors":
                        app = q.get("siddhiApp", [None])[0]
                        if app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.errors(
                                app, q.get("stream", [None])[0]))
                    elif u.path == "/siddhi/artifact/tuning":
                        app = q.get("siddhiApp", [None])[0]
                        if app is not None and app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply(200, service.tuning(app))
                    elif u.path == "/metrics":
                        app = q.get("siddhiApp", [None])[0]
                        if app is not None and app not in service.runtimes:
                            self._reply(404, {"error":
                                              f"no deployed app {app!r}"})
                        else:
                            self._reply_text(200, service.metrics(app))
                    else:
                        self._reply(404, {"error": f"no route {u.path}"})
                except Exception as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- operations -------------------------------------------------------

    def deploy(self, app_text: str) -> str:
        rt = self.manager.create_app_runtime(app_text)
        name = rt.app.name
        # served runtimes default statistics ON (the /metrics scrape is
        # the point of running as a service); an @app:statistics annotation
        # of any flavor was already applied by the runtime constructor
        if qast.find_annotation(rt.app.annotations, "app:statistics") is None:
            rt.enable_stats(True)
        old = self.runtimes.pop(name, None)
        if old is not None:
            old.shutdown()
        rt.start()
        self.runtimes[name] = rt
        return name

    def undeploy(self, name: str) -> None:
        rt = self.runtimes.pop(name)
        rt.shutdown()

    def send_event(self, app: str, stream: str, data: tuple,
                   timestamp=None) -> None:
        rt = self.runtimes[app]
        rt.send(stream, data, timestamp)
        rt.flush()

    def store_query(self, app: str, text: str) -> list:
        return [[ts, list(row)] for ts, row in self.runtimes[app].query(text)]

    def stats(self, app: str) -> dict:
        return self.runtimes[app].stats.report()

    def errors(self, app: str, stream: Optional[str] = None) -> dict:
        """The app's ErrorStore entries (JSON-safe dicts)."""
        store = self.runtimes[app].error_store
        return {"errors": [e.to_dict() for e in store.entries(stream)],
                "evicted": store.evicted}

    def errors_action(self, app: str, action: str, ids=None) -> dict:
        """Replay (re-ingest events / re-publish payloads) or discard
        captured failures."""
        rt = self.runtimes[app]
        if action == "replay":
            return rt.error_store.replay(rt, ids)
        if action == "discard":
            return {"discarded": len(rt.error_store.take(ids)),
                    "remaining": len(rt.error_store)}
        raise ValueError(f"unknown errors action {action!r} "
                         f"(replay | discard)")

    def tuning(self, app: Optional[str] = None) -> dict:
        """The persisted execution-geometry tuning cache (autotune.py):
        globally, or one deployed app's view of it (its hit/miss gauges
        and the geometries its build resolved)."""
        from .core.autotune import device_kind, jax_version, shared_cache
        if app is not None:
            rt = self.runtimes[app]
            return {"app": app, **rt.tuner.metrics()}
        c = shared_cache()
        return {"path": c.path, "device": device_kind(),
                "jax": jax_version(), "hits": c.hits, "misses": c.misses,
                "corrupt": c.corrupt, "entries": c.entries()}

    def metrics(self, app: Optional[str] = None) -> str:
        """Prometheus text exposition rendered LIVE from every deployed
        runtime's statistics (or just `app`'s when given)."""
        names = [app] if app is not None else sorted(self.runtimes)
        return render_prometheus(
            {n: self.runtimes[n].stats.report() for n in names})

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "SiddhiService":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="siddhi-service", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for rt in list(self.runtimes.values()):
            rt.shutdown()
        self.runtimes.clear()


if __name__ == "__main__":
    import sys
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 8006
    svc = SiddhiService(port).start()
    print(f"siddhi-tpu service on http://127.0.0.1:{svc.port}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        svc.stop()
