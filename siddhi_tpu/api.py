"""Fluent programmatic query API — build apps without QL text.

Reference: siddhi-query-api's builder surface
(`SiddhiApp.siddhiApp().defineStream(StreamDefinition.id("S")
.attribute("price", DOUBLE)).addQuery(Query.query().from_(...)
.select(...).insertInto("Out"))` — SiddhiApp.java:72-198,
execution/query/Query.java:52-104, StreamDefinition/Selector builders).
Here the builders emit the SAME frozen AST dataclasses the QL parser
produces, so everything downstream (planner, device compilers, docgen)
is identical for both front ends.

Expressions use python operators on `col(...)`/`val(...)` handles:

    from siddhi_tpu.api import SiddhiAppBuilder, Query, col, val

    app = (SiddhiAppBuilder("demo")
           .stream("S", symbol=str, price=float, volume=int)
           .query(Query("q1").from_stream("S")
                  .where(col("price") > 100)
                  .window("length", 10)
                  .select(symbol=col("symbol"), total=col("price").sum())
                  .group_by("symbol")
                  .insert_into("Out"))
           .build())
    rt = SiddhiManager().create_app_runtime(app)
"""
from __future__ import annotations

from typing import Optional, Union

from .query import ast
from .query.ast import AttrType

_PY_TYPES = {str: AttrType.STRING, int: AttrType.INT, float: AttrType.DOUBLE,
             bool: AttrType.BOOL, object: AttrType.OBJECT,
             "string": AttrType.STRING, "int": AttrType.INT,
             "long": AttrType.LONG, "float": AttrType.FLOAT,
             "double": AttrType.DOUBLE, "bool": AttrType.BOOL,
             "object": AttrType.OBJECT}

_AGGS = ("sum", "count", "avg", "min", "max", "stdDev", "distinctCount",
         "minForever", "maxForever", "unionSet")


def _expr(v) -> ast.Expression:
    if isinstance(v, E):
        return v.node
    if isinstance(v, ast.Expression):
        return v
    if isinstance(v, bool):
        return ast.Constant(v, AttrType.BOOL)
    if isinstance(v, int):
        return ast.Constant(v, AttrType.LONG if abs(v) > 2**31 else AttrType.INT)
    if isinstance(v, float):
        return ast.Constant(v, AttrType.DOUBLE)
    if isinstance(v, str):
        return ast.Constant(v, AttrType.STRING)
    raise TypeError(f"cannot lift {v!r} into an expression")


class E:
    """Expression handle with python operator overloading."""

    def __init__(self, node: ast.Expression):
        self.node = node

    # comparisons -> ast.Compare
    def _cmp(self, other, op):
        return E(ast.Compare(self.node, op, _expr(other)))

    def __gt__(self, o):
        return self._cmp(o, ast.CompareOp.GT)

    def __ge__(self, o):
        return self._cmp(o, ast.CompareOp.GE)

    def __lt__(self, o):
        return self._cmp(o, ast.CompareOp.LT)

    def __le__(self, o):
        return self._cmp(o, ast.CompareOp.LE)

    def __eq__(self, o):                      # noqa: A003 — fluent DSL
        return self._cmp(o, ast.CompareOp.EQ)

    def __ne__(self, o):
        return self._cmp(o, ast.CompareOp.NEQ)

    __hash__ = None

    # arithmetic -> ast.Math
    def _math(self, other, op, rev=False):
        a, b = (_expr(other), self.node) if rev else (self.node, _expr(other))
        return E(ast.Math(a, op, b))

    def __add__(self, o):
        return self._math(o, ast.MathOp.ADD)

    def __radd__(self, o):
        return self._math(o, ast.MathOp.ADD, rev=True)

    def __sub__(self, o):
        return self._math(o, ast.MathOp.SUB)

    def __rsub__(self, o):
        return self._math(o, ast.MathOp.SUB, rev=True)

    def __mul__(self, o):
        return self._math(o, ast.MathOp.MUL)

    def __rmul__(self, o):
        return self._math(o, ast.MathOp.MUL, rev=True)

    def __truediv__(self, o):
        return self._math(o, ast.MathOp.DIV)

    def __rtruediv__(self, o):
        return self._math(o, ast.MathOp.DIV, rev=True)

    def __mod__(self, o):
        return self._math(o, ast.MathOp.MOD)

    # boolean combinators (python `and`/`or` can't overload -> methods)
    def and_(self, o):
        return E(ast.And(self.node, _expr(o)))

    def or_(self, o):
        return E(ast.Or(self.node, _expr(o)))

    def not_(self):
        return E(ast.Not(self.node))

    def is_null(self):
        return E(ast.IsNull(expr=self.node))

    # aggregator shorthands: col("price").sum() etc.
    def _agg(self, name):
        return E(ast.FunctionCall(name, (self.node,)))

    def fn(self, name, *more, namespace=None):
        return E(ast.FunctionCall(name, (self.node,
                                         *(map(_expr, more))), namespace))


for _a in _AGGS:
    setattr(E, _a, (lambda _n: lambda self: self._agg(_n))(_a))


def col(name: str, of: Optional[str] = None, index=None) -> E:
    """An attribute reference: col("price"), col("price", of="e1")."""
    return E(ast.Variable(name, stream_ref=of, index=index))


def val(v) -> E:
    """A literal constant."""
    return E(_expr(v))


def fn(name: str, *args, namespace: Optional[str] = None) -> E:
    """A bare function call: fn("count"), fn("str:concat", ...)."""
    return E(ast.FunctionCall(name, tuple(_expr(a) for a in args), namespace))


def time_ms(millis: int) -> E:
    return E(ast.TimeConstant(int(millis)))


class Query:
    """Fluent single-query builder (reference Query.query())."""

    def __init__(self, name: Optional[str] = None):
        self._name = name
        self._stream: Optional[str] = None
        self._alias: Optional[str] = None
        self._handlers: list = []
        self._select_all = True
        self._attrs: list = []
        self._group: list = []
        self._having = None
        self._order: list = []
        self._limit = None
        self._offset = None
        self._output: Optional[ast.OutputStreamAction] = None
        self._annotations: list = []

    def from_stream(self, stream_id: str, as_: Optional[str] = None) -> "Query":
        self._stream = stream_id
        self._alias = as_
        return self

    def where(self, cond) -> "Query":
        self._handlers.append(ast.Filter(_expr(cond)))
        return self

    def window(self, name: str, *args, namespace: Optional[str] = None) -> "Query":
        self._handlers.append(ast.WindowHandler(
            name, tuple(_expr(a) for a in args), namespace))
        return self

    def stream_function(self, name: str, *args,
                        namespace: Optional[str] = None) -> "Query":
        self._handlers.append(ast.StreamFunction(
            name, tuple(_expr(a) for a in args), namespace))
        return self

    def select(self, *positional, **named) -> "Query":
        """select(col("a"), total=col("x").sum()) — keywords rename."""
        self._select_all = False
        for p in positional:
            self._attrs.append(ast.OutputAttribute(_expr(p)))
        for name, e in named.items():
            self._attrs.append(ast.OutputAttribute(_expr(e), rename=name))
        return self

    def select_all(self) -> "Query":
        self._select_all = True
        return self

    def group_by(self, *names: str) -> "Query":
        self._group.extend(ast.Variable(n) for n in names)
        return self

    def having(self, cond) -> "Query":
        self._having = _expr(cond)
        return self

    def order_by(self, name: str, desc: bool = False) -> "Query":
        self._order.append(ast.OrderByAttribute(
            ast.Variable(name),
            ast.OrderDir.DESC if desc else ast.OrderDir.ASC))
        return self

    def limit(self, n: int) -> "Query":
        self._limit = n
        return self

    def offset(self, n: int) -> "Query":
        self._offset = n
        return self

    def insert_into(self, target: str) -> "Query":
        self._output = ast.InsertInto(target)
        return self

    def annotate(self, name: str, *indexed, **kv) -> "Query":
        elements = tuple((None, str(v)) for v in indexed) + \
            tuple((k, str(v)) for k, v in kv.items())
        self._annotations.append(ast.Annotation(name.lower(), elements))
        return self

    def build(self) -> ast.Query:
        if self._stream is None:
            raise ValueError("query needs from_stream(...)")
        if self._output is None:
            raise ValueError("query needs insert_into(...)")
        anns = list(self._annotations)
        if self._name and not any(a.name == "info" for a in anns):
            anns.insert(0, ast.Annotation("info", ((None, self._name),)))
        inp = ast.SingleInputStream(self._stream, self._alias,
                                    tuple(self._handlers))
        sel = ast.Selector(self._select_all, tuple(self._attrs),
                           tuple(self._group), self._having,
                           tuple(self._order), self._limit, self._offset)
        return ast.Query(inp, sel, self._output, None, tuple(anns))


class SiddhiAppBuilder:
    """Fluent app assembly (reference SiddhiApp.siddhiApp())."""

    def __init__(self, name: Optional[str] = None):
        self._name = name
        self._streams: dict = {}
        self._elements: list = []
        self._annotations: list = []

    def annotate(self, name: str, *indexed, **kv) -> "SiddhiAppBuilder":
        elements = tuple((None, str(v)) for v in indexed) + \
            tuple((k, str(v)) for k, v in kv.items())
        self._annotations.append(ast.Annotation(name.lower(), elements))
        return self

    def stream(self, stream_id: str, **attrs) -> "SiddhiAppBuilder":
        """stream("S", symbol=str, price=float, volume=int) — values are
        python types or type-name strings ("long", "double", ...)."""
        attributes = []
        for n, t in attrs.items():
            at = _PY_TYPES.get(t if not isinstance(t, str) else t.lower())
            if at is None:
                raise ValueError(f"stream {stream_id!r}: unknown type {t!r} "
                                 f"for attribute {n!r}")
            attributes.append(ast.Attribute(n, at))
        self._streams[stream_id] = ast.StreamDefinition(
            stream_id, tuple(attributes))
        return self

    def query(self, q: Union[Query, ast.Query]) -> "SiddhiAppBuilder":
        self._elements.append(q.build() if isinstance(q, Query) else q)
        return self

    def build(self) -> ast.SiddhiApp:
        anns = list(self._annotations)
        if self._name and not any(a.name == "app:name" for a in anns):
            anns.insert(0, ast.Annotation("app:name", ((None, self._name),)))
        return ast.SiddhiApp(
            annotations=tuple(anns),
            stream_definitions=dict(self._streams),
            execution_elements=tuple(self._elements))
