"""App-level lint rules over the SiddhiQL AST (docs/ANALYSIS.md).

The deploy-time half of the static analyzer: ~12 rules catching the
failure classes that cost real debugging time at scale — unbounded
state, type mismatches at stream boundaries, dead graph elements, and
annotation conflicts that would make the build *soundly but silently*
fall back (the placement plane records those at build; these rules
catch them before a deploy is even attempted).

Severities:
  error — the app will not build, or will definitely misbehave
  warn  — will deploy, but carries unbounded state / surprising
          placement; `@app:strictAnalysis` turns these into deploy errors
  info  — worth knowing; never blocks anything

Every rule is a pure function over the parsed app (no runtime needed),
so `python -m siddhi_tpu.analysis`, the service deploy endpoint, and
`@app:strictAnalysis` all share one implementation.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..query import ast
from ..core.planner import selector_has_aggregators
from ..core.partition import input_stream_ids

SEVERITIES = ("error", "warn", "info")

# rule id -> (default severity, one-line title)
RULES = {
    "SA01": ("warn", "`every` pattern without a `within` bound "
                     "(unbounded pending-instance state)"),
    "SA02": ("warn", "window-less aggregation over an unbounded stream"),
    "SA03": ("warn", "stateful partition without a @purge annotation "
                     "(per-key state never expires)"),
    "SA04": ("error", "output schema mismatch at a stream boundary"),
    "SA05": ("info", "dead stream: defined but never produced or consumed"),
    "SA06": ("error", "query consumes a stream nothing defines or produces"),
    "SA07": ("info", "inferred output stream consumed by nothing"),
    "SA08": ("warn", "@app:patternFamily forced on a provably ineligible "
                     "shape (build will fall back)"),
    "SA09": ("warn", "@source(rate.limit='0') admits nothing"),
    "SA10": ("warn", "@app:deviceChunkLanes conflicts with "
                     "@app:patternFamily"),
    "SA11": ("warn", "join without an `on` condition (cross product)"),
    "SA12": ("info", "device pattern path computes doubles in f32 "
                     "(@app:devicePrecision('f64') opts out)"),
    "SA13": ("warn", "@app:durability with no resolvable store/WAL "
                     "directory, or 'fsync' behind an unbounded "
                     "block-policy source"),
    "SA14": ("warn", "@app:replication without @app:durability (nothing "
                     "to ship), or 'semi-sync' over an unbounded "
                     "block-policy source"),
    "SA15": ("warn", "aggregation groups by an unbounded key with no "
                     "@purge retention (rolling bucket state never "
                     "expires)"),
}


@dataclass
class Finding:
    rule_id: str
    severity: str          # "error" | "warn" | "info"
    message: str
    subject: Optional[str] = None     # query / stream / partition label

    def to_dict(self) -> dict:
        d = {"rule_id": self.rule_id, "severity": self.severity,
             "message": self.message}
        if self.subject is not None:
            d["subject"] = self.subject
        return d

    def __str__(self) -> str:
        where = f" [{self.subject}]" if self.subject else ""
        return f"{self.rule_id} {self.severity}{where}: {self.message}"


def _finding(rule_id: str, message: str, subject=None) -> Finding:
    return Finding(rule_id, RULES[rule_id][0], message, subject)


# ---------------------------------------------------------------------------
# app context
# ---------------------------------------------------------------------------

def iter_queries(app: ast.SiddhiApp):
    """(name, query, partition_or_None) for every query, named with the
    same defaults build.py uses, so findings line up with explain()."""
    for i, el in enumerate(app.execution_elements):
        if isinstance(el, ast.Query):
            yield el.name(f"query_{i}"), el, None
        elif isinstance(el, ast.Partition):
            for qi, q in enumerate(el.queries):
                yield q.name(f"query_p{i}_{qi}"), q, el


def _walk_state(el):
    yield el
    if isinstance(el, (ast.StreamStateElement, ast.AbsentStreamStateElement)):
        return
    if isinstance(el, ast.LogicalStateElement):
        yield from _walk_state(el.left)
        yield from _walk_state(el.right)
    elif isinstance(el, ast.CountStateElement):
        yield from _walk_state(el.stream)
    elif isinstance(el, ast.NextStateElement):
        yield from _walk_state(el.state)
        yield from _walk_state(el.next)
    elif isinstance(el, ast.EveryStateElement):
        yield from _walk_state(el.state)


class AppContext:
    """One pass of bookkeeping shared by every rule."""

    def __init__(self, app: ast.SiddhiApp):
        self.app = app
        self.queries = list(iter_queries(app))
        self.defined = set(app.stream_definitions)
        self.tables = set(app.table_definitions)
        self.windows = set(app.window_definitions)
        self.aggregations = set(app.aggregation_definitions)
        self.triggers = set(app.trigger_definitions)
        # producers/consumers over plain stream ids (inner '#' and fault
        # '!' prefixes stripped of analysis: they resolve at build time)
        self.producers: dict = {}
        self.consumers: dict = {}
        self.onerror_streams = {
            sid for sid, sd in app.stream_definitions.items()
            if ast.find_annotation(sd.annotations, "onerror") is not None}
        for name, q, _part in self.queries:
            if isinstance(q.output, ast.InsertInto) and not q.output.is_inner:
                tgt = q.output.target
                if not q.output.is_fault:
                    self.producers.setdefault(tgt, []).append(name)
            for sid in input_stream_ids(q):
                if sid.startswith("#"):
                    continue
                self.consumers.setdefault(sid.lstrip("!"), []).append(name)
        for ad in app.aggregation_definitions.values():
            self.consumers.setdefault(ad.input.stream_id, []).append(ad.id)

    def known_source(self, sid: str) -> bool:
        """Can `sid` carry events into a query?"""
        return (sid in self.defined or sid in self.windows
                or sid in self.triggers or sid in self.aggregations
                or sid in self.producers)

    def schema_of(self, sid: str):
        from ..core.schema import StreamSchema
        sd = self.app.stream_definitions.get(sid)
        return StreamSchema.of(sd) if sd is not None else None


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _rule_sa01_every_without_within(ctx, out):
    for name, q, _part in ctx.queries:
        if not isinstance(q.input, ast.StateInputStream):
            continue
        has_every = any(isinstance(el, ast.EveryStateElement)
                        for el in _walk_state(q.input.state))
        if not has_every:
            continue
        withins = [q.input.within] + [
            getattr(el, "within", None) for el in _walk_state(q.input.state)]
        waiting = [getattr(el, "waiting_time", None)
                   for el in _walk_state(q.input.state)]
        if not any(w is not None for w in withins + waiting):
            out.append(_finding(
                "SA01",
                "`every` pattern with no `within` bound anywhere: every "
                "head event arms an instance that can pend forever "
                "(unbounded state, and no parallel plan family applies)",
                name))


def _rule_sa02_windowless_aggregation(ctx, out):
    for name, q, _part in ctx.queries:
        inp = q.input
        if not isinstance(inp, ast.SingleInputStream):
            continue
        if inp.stream_id in ctx.windows or inp.stream_id in ctx.tables \
                or inp.stream_id in ctx.aggregations:
            continue   # named windows/tables bound their own state
        if inp.window is not None:
            continue
        has_agg = selector_has_aggregators(q.selector) or bool(
            q.selector.group_by)
        if has_agg:
            grp = (" per group key (key cardinality is unbounded)"
                   if q.selector.group_by else "")
            out.append(_finding(
                "SA02",
                f"aggregation over unbounded stream "
                f"{inp.stream_id!r} without a window: running state "
                f"never resets{grp}", name))


def _is_stateful_query(q: ast.Query) -> bool:
    if isinstance(q.input, ast.StateInputStream):
        return True
    if isinstance(q.input, ast.JoinInputStream):
        return True
    if isinstance(q.input, ast.SingleInputStream):
        if q.input.window is not None:
            return True
        return selector_has_aggregators(q.selector) or bool(
            q.selector.group_by)
    return False


def _rule_sa03_partition_without_purge(ctx, out):
    # @app:partitionCapacity bounds the per-key lane slab engine-wide —
    # the engine's own cap on partition state (docs/PERFORMANCE.md)
    if ast.find_annotation(ctx.app.annotations,
                           "app:partitionCapacity") is not None:
        return
    for i, el in enumerate(ctx.app.execution_elements):
        if not isinstance(el, ast.Partition):
            continue
        if ast.find_annotation(el.annotations, "purge") is not None:
            continue
        if any(_is_stateful_query(q) for q in el.queries):
            out.append(_finding(
                "SA03",
                "partition holds per-key state (pattern/window/"
                "aggregation) with no @purge annotation and no "
                "@app:partitionCapacity bound: at high key cardinality, "
                "per-key state grows forever",
                f"#partition_{i}"))


def _infer_type(expr, schema, ctx) -> Optional[ast.AttrType]:
    """Cheap type inference: plain variables + constants only — a rule
    must never claim a mismatch it can't prove."""
    if isinstance(expr, ast.Constant):
        return expr.type
    if isinstance(expr, ast.Variable) and expr.index is None:
        ref = expr.stream_ref
        if ref is not None and ref in ctx.defined:
            s = ctx.schema_of(ref)
            if s is not None and expr.attribute in s.types:
                return s.type_of(expr.attribute)
            return None
        if ref is None and schema is not None \
                and expr.attribute in schema.types:
            return schema.type_of(expr.attribute)
    return None


def _rule_sa04_output_schema_mismatch(ctx, out):
    for name, q, _part in ctx.queries:
        if not isinstance(q.output, ast.InsertInto) or q.output.is_fault:
            continue
        tgt = q.output.target
        sd = ctx.app.stream_definitions.get(tgt)
        if sd is None or q.selector.select_all:
            continue
        want = list(sd.attributes)
        have = list(q.selector.attributes)
        if len(want) != len(have):
            out.append(_finding(
                "SA04",
                f"inserts {len(have)} attributes into {tgt!r} which "
                f"defines {len(want)} — the build will reject this "
                f"schema mismatch", name))
            continue
        in_schema = None
        if isinstance(q.input, ast.SingleInputStream):
            in_schema = ctx.schema_of(q.input.stream_id)
        for oa, attr in zip(have, want):
            t = _infer_type(oa.expr, in_schema, ctx)
            if t is not None and t != attr.type:
                lossy = (t, attr.type) in (
                    (ast.AttrType.DOUBLE, ast.AttrType.FLOAT),
                    (ast.AttrType.LONG, ast.AttrType.INT),
                    (ast.AttrType.DOUBLE, ast.AttrType.INT),
                    (ast.AttrType.DOUBLE, ast.AttrType.LONG))
                extra = (" (lossy narrowing)" if lossy else "")
                out.append(_finding(
                    "SA04",
                    f"output attribute {oa.name!r} is {t.value} but "
                    f"{tgt!r} declares {attr.type.value}{extra} — the "
                    f"build requires exact type equality", name))


def _rule_sa05_dead_stream(ctx, out):
    for sid, sd in ctx.app.stream_definitions.items():
        if sid in ctx.consumers or sid in ctx.producers:
            continue
        anns = {a.name.lower() for a in sd.annotations}
        if anns & {"source", "sink", "onerror"}:
            continue
        out.append(_finding(
            "SA05",
            f"stream {sid!r} is defined but no query reads or writes it "
            f"and it has no @source/@sink — dead definition (or a typo "
            f"elsewhere)", sid))


def _rule_sa06_unknown_input(ctx, out):
    for name, q, part in ctx.queries:
        for sid in input_stream_ids(q):
            if sid.startswith("#"):
                continue           # partition inner streams
            base = sid.lstrip("!")
            if sid.startswith("!") and base in ctx.onerror_streams:
                continue
            if base in ctx.tables:
                if isinstance(q.input, ast.JoinInputStream):
                    continue       # table side of a join is legal
                out.append(_finding(
                    "SA06",
                    f"streams from table {base!r}: tables cannot be "
                    f"streamed — use a join or an on-demand (store) "
                    f"query; this build will fail", name))
                continue
            if not ctx.known_source(base):
                out.append(_finding(
                    "SA06",
                    f"consumes stream {base!r}, which is not defined and "
                    f"which no query produces — this build will fail "
                    f"(or the query waits forever on a typo)", name))


def _rule_sa07_unconsumed_output(ctx, out):
    for name, q, _part in ctx.queries:
        if not isinstance(q.output, ast.InsertInto) \
                or q.output.is_fault or q.output.is_inner:
            continue
        tgt = q.output.target
        if tgt in ctx.defined or tgt in ctx.tables or tgt in ctx.windows:
            continue               # declared somewhere: deliberate
        if tgt in ctx.consumers:
            continue
        out.append(_finding(
            "SA07",
            f"inserts into inferred stream {tgt!r} which no query "
            f"consumes and no definition declares — reachable only via "
            f"callbacks (fine if intended, a silent sink if a typo)",
            name))


def _rule_sa08_ineligible_family(ctx, out):
    fam_ann = ast.find_annotation(ctx.app.annotations, "app:patternFamily")
    if fam_ann is None:
        return
    fam = str(fam_ann.element() or "").lower()
    if fam in ("", "auto", "seq"):
        return
    from ..core.nfa_parallel import classify_shape
    from ..core.schema import StringTable
    for name, q, part in ctx.queries:
        if not isinstance(q.input, ast.StateInputStream):
            continue
        schemas = {}
        missing = False
        for sid in input_stream_ids(q):
            s = ctx.schema_of(sid)
            if s is None:
                missing = True     # inferred input: SA06/SA07 territory
                break
            schemas[sid] = s
        if missing:
            continue
        # partitioned patterns apply the lane-vmap gates (chunk's lane
        # axis is spent on partition keys; non-`every` arms need per-key
        # state) — classify_shape mirrors pattern_plan's build gates
        verdict = classify_shape(q.input, schemas, StringTable(),
                                 partitioned=part is not None).get(fam)
        if verdict is not True and fam in ("chunk", "scan", "dfa"):
            out.append(_finding(
                "SA08",
                f"@app:patternFamily({fam!r}) is provably ineligible for "
                f"this shape: {verdict} — the build will warn and fall "
                f"back to automatic selection", name))


def _rule_sa09_zero_rate_limit(ctx, out):
    for sid, sd in ctx.app.stream_definitions.items():
        src = ast.find_annotation(sd.annotations, "source")
        if src is None:
            continue
        rl = src.element("rate.limit")
        try:
            zero = rl is not None and float(rl) == 0.0
        except ValueError:
            zero = False
        if zero:
            out.append(_finding(
                "SA09",
                f"@source(rate.limit='0') on {sid!r} admits NOTHING — "
                f"every frame sheds/blocks; if intended, say so with a "
                f"comment, otherwise this is a typo'd limit", sid))


def _rule_sa10_lanes_family_conflict(ctx, out):
    lanes_ann = ast.find_annotation(ctx.app.annotations,
                                    "app:deviceChunkLanes")
    fam_ann = ast.find_annotation(ctx.app.annotations, "app:patternFamily")
    if lanes_ann is None or fam_ann is None:
        return
    try:
        lanes = int(lanes_ann.element())
    except (TypeError, ValueError):
        return                     # the build rejects the value itself
    fam = str(fam_ann.element() or "").lower()
    if fam == "chunk" and lanes <= 1:
        out.append(_finding(
            "SA10",
            f"@app:patternFamily('chunk') with "
            f"@app:deviceChunkLanes({lanes}): the chunk family needs "
            f"more than one lane — the build will fall back",
            "app"))
    elif fam in ("seq", "scan", "dfa"):
        out.append(_finding(
            "SA10",
            f"@app:deviceChunkLanes({lanes}) has no effect under "
            f"@app:patternFamily({fam!r}) — the lanes knob only shapes "
            f"the chunk family", "app"))


def _rule_sa11_cross_join(ctx, out):
    for name, q, _part in ctx.queries:
        inp = q.input
        if not isinstance(inp, ast.JoinInputStream):
            continue
        if inp.on is not None or inp.per is not None:
            continue
        out.append(_finding(
            "SA11",
            f"join of {inp.left.stream_id!r} and {inp.right.stream_id!r} "
            f"has no `on` condition: every retained left event pairs "
            f"with every retained right event (cross product)", name))


def _rule_sa12_f32_precision(ctx, out):
    if ast.find_annotation(ctx.app.annotations, "app:devicePrecision") \
            is not None:
        return
    dp_ann = ast.find_annotation(ctx.app.annotations, "app:devicePatterns")
    dp = str(dp_ann.element()).lower() if dp_ann is not None else "auto"
    for name, q, part in ctx.queries:
        if not isinstance(q.input, ast.StateInputStream):
            continue
        on_device = part is not None or dp in ("prefer", "always")
        if not on_device:
            continue
        has_double = any(
            a.type == ast.AttrType.DOUBLE
            for sid in input_stream_ids(q)
            for a in (ctx.app.stream_definitions.get(sid).attributes
                      if sid in ctx.defined else ()))
        if has_double:
            out.append(_finding(
                "SA12",
                "device pattern kernels compute DOUBLE columns in f32 "
                "by default: thresholds within ~7 significant digits "
                "may compare differently than the host path; "
                "@app:devicePrecision('f64') opts out", name))
            return        # one note per app is enough


def _rule_sa13_durability(ctx, out):
    """Durability misconfigurations the runtime only surfaces at start
    time (docs/RELIABILITY.md "Durability & exactly-once recovery"):

    (a) `@app:durability` with no `dir=` element — the WAL directory
        then depends on manager-side state the app text cannot prove
        (a file persistence store or $SIDDHI_WAL_DIR); if neither is
        configured at deploy time, durability disables with only a
        runtime warning, and without a persistence store the log can
        NEVER truncate (snapshot barriers never happen) — unbounded
        growth plus full-log replay on every recovery.

    (b) `'fsync'` combined with `shed.policy='block'` (explicit or the
        default) on a source with no `rate.limit`: every admitted frame
        pays an fsync with no admission bound — when the disk stalls,
        backpressure is the ONLY relief valve, and it arrives as a
        stalled socket, not an accounted shed."""
    dur = ast.find_annotation(ctx.app.annotations, "app:durability")
    if dur is None:
        return
    mode = str(dur.element() or "batch").lower()
    if mode == "off":
        return
    if next((v for k, v in dur.elements if k == "dir"), None) is None:
        out.append(_finding(
            "SA13",
            f"@app:durability({mode!r}) declares no dir= element: the "
            f"WAL directory falls back to the manager's file "
            f"persistence store or $SIDDHI_WAL_DIR — if neither exists "
            f"at deploy time durability silently disables (runtime "
            f"warning only), and without a snapshot store the log "
            f"never truncates and every recovery replays it whole",
            "app"))
    if mode != "fsync":
        return
    for sid, sd in ctx.app.stream_definitions.items():
        src = ast.find_annotation(sd.annotations, "source")
        if src is None:
            continue
        policy = str(src.element("shed.policy") or "block").lower()
        if policy == "block" and src.element("rate.limit") is None:
            out.append(_finding(
                "SA13",
                f"@app:durability('fsync') with shed.policy='block' "
                f"and no rate.limit on source stream {sid!r}: every "
                f"admitted frame pays a per-frame fsync with no "
                f"admission bound — a disk stall surfaces only as a "
                f"stalled producer socket; bound the rate or use "
                f"'batch' (ACK/PING barriers still fsync)", sid))


def _rule_sa14_replication(ctx, out):
    """Replication misconfigurations (docs/RELIABILITY.md "High
    availability & failover"):

    (a) `@app:replication` without `@app:durability` — replication
        ships the write-ahead log; with no log there is nothing to
        ship, and the runtime constructor rejects the app at deploy.

    (b) `'semi-sync'` combined with `shed.policy='block'` (explicit or
        the default) on a source with no `max.pending` bound: the
        durable-ACK barrier now waits on the standby's append-ack, so
        a slow/partitioned standby stalls the PING path — with a
        block-policy source and no pending bound, that stall
        backpressures ingest unboundedly instead of surfacing as an
        accounted shed or a bounded park."""
    rep = ast.find_annotation(ctx.app.annotations, "app:replication")
    if rep is None:
        return
    dur = ast.find_annotation(ctx.app.annotations, "app:durability")
    mode = str(rep.element() or "async").lower()
    if dur is None or str(dur.element() or "batch").lower() == "off":
        out.append(_finding(
            "SA14",
            f"@app:replication({mode!r}) without @app:durability: "
            f"replication ships the write-ahead log, and this app "
            f"writes none — the deploy will be rejected; declare "
            f"@app:durability('batch'|'fsync')",
            "app"))
        return
    if mode != "semi-sync":
        return
    for sid, sd in ctx.app.stream_definitions.items():
        src = ast.find_annotation(sd.annotations, "source")
        if src is None:
            continue
        policy = str(src.element("shed.policy") or "block").lower()
        if policy == "block" and src.element("max.pending") is None:
            out.append(_finding(
                "SA14",
                f"@app:replication('semi-sync') with "
                f"shed.policy='block' and no max.pending on source "
                f"stream {sid!r}: the durable-ACK barrier waits on the "
                f"standby's append-ack, so a slow or partitioned "
                f"standby stalls ingest unboundedly — bound "
                f"max.pending (or shed) so replication lag surfaces "
                f"as accounted backpressure", sid))


def _rule_sa15_aggregation_retention(ctx, out):
    """An aggregation keeps one rolling bucket row per (bucket, group)
    pair PER DURATION (docs/AGGREGATION.md "Retention").  With a
    `group by` the row count scales with key cardinality times elapsed
    wall time, and nothing ever expires it — on the device-resident
    path that is base-matrix capacity doubling forever, on the host
    path an ever-growing dict.  `@purge(retention='...')` bounds it;
    `@purge(enable='false')` is an explicit opt-out this rule
    respects."""
    for aid, ad in sorted(ctx.app.aggregation_definitions.items()):
        if not ad.selector.group_by:
            continue
        purge = ast.find_annotation(ad.annotations, "purge")
        if purge is not None:
            continue                     # any @purge (even an explicit
        #                                  opt-out) is a decision made
        keys = ", ".join(v.attribute for v in ad.selector.group_by)
        durs = ", ".join(d.value for d in ad.durations)
        out.append(_finding(
            "SA15",
            f"aggregation {aid!r} groups by ({keys}) across "
            f"durations ({durs}) with no @purge annotation: bucket "
            f"state grows with key cardinality x wall time and never "
            f"expires — declare @purge(retention='...') (or "
            f"per-duration spans), or @purge(enable='false') to "
            f"accept unbounded state", aid))


_RULE_FNS = (
    _rule_sa01_every_without_within,
    _rule_sa02_windowless_aggregation,
    _rule_sa03_partition_without_purge,
    _rule_sa04_output_schema_mismatch,
    _rule_sa05_dead_stream,
    _rule_sa06_unknown_input,
    _rule_sa07_unconsumed_output,
    _rule_sa08_ineligible_family,
    _rule_sa09_zero_rate_limit,
    _rule_sa10_lanes_family_conflict,
    _rule_sa11_cross_join,
    _rule_sa12_f32_precision,
    _rule_sa13_durability,
    _rule_sa14_replication,
    _rule_sa15_aggregation_retention,
)

_SEV_ORDER = {"error": 0, "warn": 1, "info": 2}


def analyze_app(app: ast.SiddhiApp) -> list:
    """All rules over one parsed app; findings sorted most-severe first,
    then by rule id (deterministic output for the CLI / service JSON)."""
    ctx = AppContext(app)
    out: list = []
    for fn in _RULE_FNS:
        fn(ctx, out)
    out.sort(key=lambda f: (_SEV_ORDER.get(f.severity, 3), f.rule_id,
                            f.subject or "", f.message))
    return out
