"""CLI for the static analyzer + EXPLAIN plane (docs/ANALYSIS.md).

    python -m siddhi_tpu.analysis [options] <file> [<file> ...]
    python -m siddhi_tpu.analysis --self

Inputs: a SiddhiQL app file (.siddhi or any text file), ``-`` for
stdin, or a .py file — every module-level string constant containing
``define stream`` is analyzed as its own app (the samples/*.py shape).

Options:
  --json          machine output (one JSON document on stdout)
  --explain       also BUILD each app and include rt.explain(): per-query
                  placement (device vs interpreter), chosen plan family,
                  geometry provenance, and the Demotion reason chains
  --strict        exit non-zero on warn findings too (the CLI mirror of
                  @app:strictAnalysis)
  --expect IDS    comma-separated rule-id multiset (e.g. SA07,SA07,SA12)
                  the findings must match EXACTLY — the smoke pin for
                  expected-findings corpora; exit non-zero on any drift
  --self          lint siddhi_tpu's own source instead (SL01 silent
                  demotions, SL02 unguarded shared counters); any
                  finding exits non-zero — this is the CI gate

Exit status: 0 clean (or --expect matched), 1 findings at error
severity (warn too under --strict), 2 usage/input errors.
"""
from __future__ import annotations

import ast as pyast
import json
import sys

from . import analyze_source
from .rules import Finding
from .selflint import lint_package


def extract_apps(path: str) -> list:
    """[(label, app_text)] from one input path.  .py files contribute
    every module-level string constant that looks like an app; anything
    else is one app string ('-' reads stdin)."""
    if path == "-":
        return [("<stdin>", sys.stdin.read())]
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if not path.endswith(".py"):
        return [(path, text)]
    out = []
    tree = pyast.parse(text)
    for node in tree.body:
        tgt = None
        if isinstance(node, pyast.Assign) and node.targets and \
                isinstance(node.targets[0], pyast.Name):
            tgt, val = node.targets[0].id, node.value
        elif isinstance(node, pyast.AnnAssign) and \
                isinstance(node.target, pyast.Name):
            tgt, val = node.target.id, node.value
        else:
            continue
        if isinstance(val, pyast.Constant) and isinstance(val.value, str) \
                and "define stream" in val.value:
            out.append((f"{path}:{tgt}", val.value))
    return out


def _explain_app(text: str) -> dict:
    """Build the app (device planning included) and return rt.explain().
    Imports JAX — only paid under --explain."""
    import warnings
    from .. import SiddhiManager
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # forced-family fallbacks etc.
        rt = mgr.create_app_runtime(text)
    try:
        return rt.explain()
    finally:
        mgr.shutdown()


def _render_text(entry: dict) -> str:
    lines = [f"== {entry['source']}"]
    ex = entry.get("explain")
    if ex is not None:
        lines.append(f"app {ex['app']!r}: "
                     f"{ex['placement']['device']} device / "
                     f"{ex['placement']['interpreter']} interpreter "
                     f"({ex['placement']['interp_demotions']} demotions)")
        for qn, qd in ex["queries"].items():
            fam = f" family={qd['family']}" if qd.get("family") else ""
            lines.append(f"  {qn}: {qd['path']} [{qd['kind']}]{fam}")
            for d in qd.get("demotions", ()):
                cause = f" (cause: {d['cause']})" if d.get("cause") else ""
                lines.append(f"    {d['rule_id']} lost "
                             f"{d['alternative']}: {d['reason']}{cause}")
    for f in entry["findings"]:
        lines.append(f"  {f['rule_id']} {f['severity']}"
                     + (f" [{f['subject']}]" if f.get("subject") else "")
                     + f": {f['message']}")
    if not entry["findings"]:
        lines.append("  clean: 0 findings")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    explain = "--explain" in argv
    strict = "--strict" in argv
    self_lint = "--self" in argv
    expect = None
    for flag in ("--json", "--explain", "--strict", "--self"):
        while flag in argv:
            argv.remove(flag)
    if "--expect" in argv:
        i = argv.index("--expect")
        try:
            expect = sorted(x for x in argv[i + 1].split(",") if x)
        except IndexError:
            print("--expect needs a rule-id list", file=sys.stderr)
            return 2
        del argv[i:i + 2]

    if self_lint:
        findings = lint_package()
        if as_json:
            print(json.dumps({"self_lint": [f.to_dict() for f in findings],
                              "findings": len(findings)}, indent=1))
        else:
            for f in findings:
                print(f)
            print(f"self-lint: {len(findings)} finding(s) over siddhi_tpu/")
        return 1 if findings else 0

    if not argv:
        print(__doc__.strip(), file=sys.stderr)
        return 2

    apps, failures = [], 0
    for path in argv:
        try:
            extracted = extract_apps(path)
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
        if not extracted:
            print(f"{path}: no app strings found", file=sys.stderr)
            failures += 1
        apps.extend(extracted)

    entries, all_findings = [], []
    for label, text in apps:
        try:
            findings = analyze_source(text)
        except Exception as e:
            findings = [Finding("SA00", "error",
                                f"app does not parse: {e}")]
        entry = {"source": label,
                 "findings": [f.to_dict() for f in findings]}
        if explain and not any(f.severity == "error" for f in findings):
            try:
                entry["explain"] = _explain_app(text)
            except Exception as e:
                entry["explain_error"] = f"{type(e).__name__}: {e}"
        all_findings.extend(findings)
        entries.append(entry)

    counts = {s: sum(1 for f in all_findings if f.severity == s)
              for s in ("error", "warn", "info")}
    if as_json:
        print(json.dumps({"apps": entries, "findings": len(all_findings),
                          "severities": counts}, indent=1))
    else:
        for entry in entries:
            print(_render_text(entry))
        print(f"{len(all_findings)} finding(s): "
              f"{counts['error']} error, {counts['warn']} warn, "
              f"{counts['info']} info over {len(apps)} app(s)")

    if failures:
        return 2
    if expect is not None:
        got = sorted(f.rule_id for f in all_findings)
        if got != expect:
            print(f"--expect mismatch: wanted {expect}, got {got}",
                  file=sys.stderr)
            return 1
        return 0
    if counts["error"] or (strict and counts["warn"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
