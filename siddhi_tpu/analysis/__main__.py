"""CLI for the static analyzer + EXPLAIN plane (docs/ANALYSIS.md).

    python -m siddhi_tpu.analysis [options] <file> [<file> ...]
    python -m siddhi_tpu.analysis --self
    python -m siddhi_tpu.analysis --threads [options] [<file.py> ...]

Inputs: a SiddhiQL app file (.siddhi or any text file), ``-`` for
stdin, or a .py file — every module-level string constant containing
``define stream`` is analyzed as its own app (the samples/*.py shape).

Options:
  --json          machine output (one JSON document on stdout)
  --explain       also BUILD each app and include rt.explain(): per-query
                  placement (device vs interpreter), chosen plan family,
                  geometry provenance, and the Demotion reason chains
  --strict        exit non-zero on warn findings too (the CLI mirror of
                  @app:strictAnalysis)
  --expect IDS    comma-separated rule-id multiset (e.g. SA07,SA07,SA12)
                  the findings must match EXACTLY — the smoke pin for
                  expected-findings corpora; exit non-zero on any drift
  --self          lint siddhi_tpu's own source instead (SL01 silent
                  demotions, SL02 unguarded shared counters); any
                  finding exits non-zero — this is the CI gate
  --threads       concurrency self-analysis (SL03 lockset, SL04
                  lock-order inversion, SL05 blocking-under-lock, SL06
                  thread lifecycle — docs/ANALYSIS.md): over the
                  siddhi_tpu package with no files, or over the given
                  .py files (the seeded-corpus mode; --expect works).
                  Sub-options, package mode only:
                    --witness PATH         cross-check a runtime
                                           lock-witness dump (see
                                           utils/locks.py) against the
                                           static lock graph
                    --baseline PATH        pin the justified-suppression
                                           inventory; any drift fails
                    --write-baseline PATH  regenerate the baseline pin
                                           (use in the same commit that
                                           adds a justified suppression)

Exit status: 0 clean (or --expect matched), 1 findings at error
severity (warn too under --strict), 2 usage/input errors.
"""
from __future__ import annotations

import ast as pyast
import json
import sys

from . import analyze_source
from .rules import Finding
from .selflint import lint_package


def extract_apps(path: str) -> list:
    """[(label, app_text)] from one input path.  .py files contribute
    every module-level string constant that looks like an app; anything
    else is one app string ('-' reads stdin)."""
    if path == "-":
        return [("<stdin>", sys.stdin.read())]
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if not path.endswith(".py"):
        return [(path, text)]
    out = []
    tree = pyast.parse(text)
    for node in tree.body:
        tgt = None
        if isinstance(node, pyast.Assign) and node.targets and \
                isinstance(node.targets[0], pyast.Name):
            tgt, val = node.targets[0].id, node.value
        elif isinstance(node, pyast.AnnAssign) and \
                isinstance(node.target, pyast.Name):
            tgt, val = node.target.id, node.value
        else:
            continue
        if isinstance(val, pyast.Constant) and isinstance(val.value, str) \
                and "define stream" in val.value:
            out.append((f"{path}:{tgt}", val.value))
    return out


def _explain_app(text: str) -> dict:
    """Build the app (device planning included) and return rt.explain().
    Imports JAX — only paid under --explain."""
    import warnings
    from .. import SiddhiManager
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # forced-family fallbacks etc.
        rt = mgr.create_app_runtime(text)
    try:
        return rt.explain()
    finally:
        mgr.shutdown()


def _render_text(entry: dict) -> str:
    lines = [f"== {entry['source']}"]
    ex = entry.get("explain")
    if ex is not None:
        lines.append(f"app {ex['app']!r}: "
                     f"{ex['placement']['device']} device / "
                     f"{ex['placement']['interpreter']} interpreter "
                     f"({ex['placement']['interp_demotions']} demotions)")
        for qn, qd in ex["queries"].items():
            fam = f" family={qd['family']}" if qd.get("family") else ""
            lines.append(f"  {qn}: {qd['path']} [{qd['kind']}]{fam}")
            for d in qd.get("demotions", ()):
                cause = f" (cause: {d['cause']})" if d.get("cause") else ""
                lines.append(f"    {d['rule_id']} lost "
                             f"{d['alternative']}: {d['reason']}{cause}")
    for f in entry["findings"]:
        lines.append(f"  {f['rule_id']} {f['severity']}"
                     + (f" [{f['subject']}]" if f.get("subject") else "")
                     + f": {f['message']}")
    if not entry["findings"]:
        lines.append("  clean: 0 findings")
    return "\n".join(lines)


def _opt_value(argv: list, flag: str):
    """Extract `--flag VALUE` from argv; returns VALUE or None, or
    raises SystemExit-ish usage (handled by caller as 2)."""
    if flag not in argv:
        return None
    i = argv.index(flag)
    try:
        value = argv[i + 1]
    except IndexError:
        raise ValueError(f"{flag} needs a value")
    del argv[i:i + 2]
    return value


def _threads_main(argv: list, as_json: bool, expect,
                  witness_path, baseline_path, write_baseline) -> int:
    """The --threads mode (docs/ANALYSIS.md "Concurrency
    self-analysis").  Package mode with no files; seeded-corpus mode
    over explicit .py files."""
    from .concurrency import (analyze_package, analyze_sources,
                              check_baseline, check_witness,
                              suppression_inventory)
    if write_baseline is not None:
        inv = suppression_inventory()
        with open(write_baseline, "w", encoding="utf-8") as f:
            json.dump(inv, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"baseline: {sum(inv.values())} suppression(s) over "
              f"{len(inv)} file(s) -> {write_baseline}")
        return 0
    if argv:
        sources = []
        for path in argv:
            try:
                with open(path, encoding="utf-8") as f:
                    sources.append((path, f.read()))
            except OSError as e:
                print(f"cannot read {path}: {e}", file=sys.stderr)
                return 2
        result = analyze_sources(sources)
    else:
        result = analyze_package()
    findings = list(result["findings"])
    if witness_path is not None:
        try:
            with open(witness_path, encoding="utf-8") as f:
                witness = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read witness {witness_path}: {e}",
                  file=sys.stderr)
            return 2
        findings += check_witness(witness, result["graph"])
    if baseline_path is not None:
        try:
            findings += check_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    g = result["graph"]
    if as_json:
        print(json.dumps({
            "threads": [f.to_dict() for f in findings],
            "findings": len(findings),
            "suppressions": [list(s) for s in result["suppressions"]],
            "graph": {"nodes": sorted(g["nodes"]),
                      "edges": sorted(
                          [a, b, f"{s[0]}:{s[1]}"]
                          for (a, b), s in g["edges"].items())}},
            indent=1))
    else:
        for f in findings:
            print(f)
        print(f"threads: {len(findings)} finding(s), "
              f"{len(result['suppressions'])} suppressed site(s), "
              f"{len(g['nodes'])} lock(s), {len(g['edges'])} order "
              f"edge(s)")
    if expect is not None:
        got = sorted(f.rule_id for f in findings)
        if got != expect:
            print(f"--expect mismatch: wanted {expect}, got {got}",
                  file=sys.stderr)
            return 1
        return 0
    return 1 if findings else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    explain = "--explain" in argv
    strict = "--strict" in argv
    self_lint = "--self" in argv
    threads = "--threads" in argv
    expect = None
    for flag in ("--json", "--explain", "--strict", "--self", "--threads"):
        while flag in argv:
            argv.remove(flag)
    try:
        witness_path = _opt_value(argv, "--witness")
        baseline_path = _opt_value(argv, "--baseline")
        write_baseline = _opt_value(argv, "--write-baseline")
        expect_raw = _opt_value(argv, "--expect")
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if expect_raw is not None:
        expect = sorted(x for x in expect_raw.split(",") if x)

    if not threads and (witness_path or baseline_path or write_baseline):
        # silently ignoring a gate flag would leave CI weaker than the
        # author believes — misuse is a usage error, never a pass
        print("--witness/--baseline/--write-baseline require --threads",
              file=sys.stderr)
        return 2

    if threads:
        return _threads_main(argv, as_json, expect, witness_path,
                             baseline_path, write_baseline)

    if self_lint:
        findings = lint_package()
        if as_json:
            print(json.dumps({"self_lint": [f.to_dict() for f in findings],
                              "findings": len(findings)}, indent=1))
        else:
            for f in findings:
                print(f)
            print(f"self-lint: {len(findings)} finding(s) over siddhi_tpu/")
        return 1 if findings else 0

    if not argv:
        print(__doc__.strip(), file=sys.stderr)
        return 2

    apps, failures = [], 0
    for path in argv:
        try:
            extracted = extract_apps(path)
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
        if not extracted:
            print(f"{path}: no app strings found", file=sys.stderr)
            failures += 1
        apps.extend(extracted)

    entries, all_findings = [], []
    for label, text in apps:
        try:
            findings = analyze_source(text)
        except Exception as e:
            findings = [Finding("SA00", "error",
                                f"app does not parse: {e}")]
        entry = {"source": label,
                 "findings": [f.to_dict() for f in findings]}
        if explain and not any(f.severity == "error" for f in findings):
            try:
                entry["explain"] = _explain_app(text)
            except Exception as e:
                entry["explain_error"] = f"{type(e).__name__}: {e}"
        all_findings.extend(findings)
        entries.append(entry)

    counts = {s: sum(1 for f in all_findings if f.severity == s)
              for s in ("error", "warn", "info")}
    if as_json:
        print(json.dumps({"apps": entries, "findings": len(all_findings),
                          "severities": counts}, indent=1))
    else:
        for entry in entries:
            print(_render_text(entry))
        print(f"{len(all_findings)} finding(s): "
              f"{counts['error']} error, {counts['warn']} warn, "
              f"{counts['info']} info over {len(apps)} app(s)")

    if failures:
        return 2
    if expect is not None:
        got = sorted(f.rule_id for f in all_findings)
        if got != expect:
            print(f"--expect mismatch: wanted {expect}, got {got}",
                  file=sys.stderr)
            return 1
        return 0
    if counts["error"] or (strict and counts["warn"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
