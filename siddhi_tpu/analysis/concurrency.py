"""Whole-package concurrency self-analysis (SL03–SL06).

`python -m siddhi_tpu.analysis --threads` runs four rule groups over
the engine's own source — the serving plane is a deeply threaded
system, and every review round before this analyzer existed found
lock-discipline bugs by hand:

  SL03  lockset / inconsistent guard — per-class inventory of lock
        attributes, then Eraser-style dominant-lock inference for every
        shared mutable attribute (reads, plain/aug assignment,
        container mutation — generalizing SL02 beyond ``+=``): an
        attribute guarded by a lock at most sites but accessed outside
        it at others is a data race until someone writes down why not.
  SL04  lock-order inversion — a lock-acquisition graph extracted from
        nested ``with <lock>:`` scopes and composed through per-method
        call summaries; cycles are potential deadlocks.
  SL05  blocking call under a lock — socket send/recv/accept/connect,
        ``os.fsync``, ``time.sleep``, thread/queue joins and waits,
        subprocess, and HTTP calls reachable (directly or through the
        call summary) while a named lock is held.
  SL06  thread lifecycle — spawned threads that are neither daemonized
        nor join-tracked, threads without a ``siddhi-<role>`` name, and
        ``Condition.wait`` outside a predicate loop.
  SL07  a ``lint: allow`` annotation with no justification — the
        why is mandatory; a bare pragma suppresses nothing.

Every rule honors ``# lint: allow (<why>)`` on the flagged line (or
the line above); SL03 additionally honors the legacy
``# lint: unlocked-ok (<why>)`` so a site never needs two pragmas.

The analysis is deliberately heuristic and lexical — it resolves
receivers by constructor-assignment attribute typing and
unique-method-name fallback, not real type inference — which is why it
is paired with the runtime *lock-witness* (`siddhi_tpu/utils/locks.py`):
under ``SIDDHI_LOCK_CHECK=1`` every engine lock records the actual
acquisition orders, and ``--threads --witness <dump.json>`` fails if
reality exhibits an order the static graph contradicts or simply does
not know.  The model is validated against the engine, not trusted.

See docs/ANALYSIS.md "Concurrency self-analysis" for the rule catalog,
annotation grammar, and triage runbook.
"""
from __future__ import annotations

import ast as pyast
import json
import re
from dataclasses import dataclass, field
from typing import Optional

from .rules import Finding
from .walker import (MUTATING_METHODS, call_name, class_lock_attrs,
                     comment_map, iter_package, justified_pragma,
                     lock_call_kind, pragma_re, self_attr)

ALLOW = "lint: allow"
ALLOW_LEGACY = "lint: unlocked-ok"      # SL02's pragma; SL03 honors it
ALLOW_SWALLOW = "lint: allow-swallow"   # SL01's pragma (inventory only)

# SL03 dominant-lock inference: the candidate lock must guard at least
# MIN_GUARDED accesses and at least DOMINANCE of the eligible ones
MIN_GUARDED = 2
DOMINANCE = 0.6

_SOCKET_METHODS = {"sendall", "send", "recv", "recvfrom", "recv_into",
                   "accept", "connect", "sendto"}
_SOCKETISH = re.compile(r"sock|conn$|_ws$", re.I)
_THREADISH = re.compile(r"thread|worker|proc|child|ring|persistor", re.I)
_QUEUEISH = re.compile(r"(^|_)q(ueue)?\d*$", re.I)


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

@dataclass
class Access:
    attr: str
    lineno: int
    kind: str                   # "read" | "write"
    held: frozenset             # lock node names held at the access
    method: str
    suppressed: bool = False


@dataclass
class CallSite:
    name: str                   # method/function name
    recv: Optional[str]         # "self" | resolved class name | None
    lineno: int
    held: tuple                 # lock node names held, outermost first
    suppressed: bool = False


@dataclass
class MethodInfo:
    cls: Optional[str]          # class NAME (for messages)
    name: str                   # qualified within the class (a.b for nested)
    cls_id: Optional[str] = None    # "relpath::Class" (for resolution)
    relpath: str = ""
    acquires: dict = field(default_factory=dict)    # node -> first lineno
    edges: list = field(default_factory=list)       # (outer, inner, lineno)
    calls: list = field(default_factory=list)       # [CallSite]
    blocking: list = field(default_factory=list)    # [(line, what, supp, held)]
    accesses: list = field(default_factory=list)    # [Access]
    returns_lock: Optional[str] = None
    exempt: bool = False        # __init__ / *_locked naming convention
    thread_join: bool = False   # joins a thread somewhere (SL06)


@dataclass
class ClassInfo:
    name: str
    relpath: str
    locks: dict = field(default_factory=dict)       # attr -> (kind, node)
    methods: dict = field(default_factory=dict)     # qualname -> MethodInfo
    has_join: bool = False      # joins threads somewhere (SL06)


class PackageModel:
    def __init__(self):
        # classes are keyed by "relpath::name" — two modules may define
        # same-named classes (the engine already has two `Query`s), and
        # merging them would attribute accesses to the wrong file and
        # dilute/invent SL03 dominance.  Name-based resolution goes
        # through by_name and stays conservative on ambiguity.
        self.classes: dict = {}         # "relpath::Class" -> ClassInfo
        self.by_name: dict = {}         # class name -> [class ids]
        self.attr_lock_nodes: dict = {} # lock attr name -> set(node names)
        self.attr_types: dict = {}      # attr name -> set(class ids)
        self.method_owner: dict = {}    # method name -> set(class ids)
        self.module_locks: dict = {}    # module-level const name -> node
        self.modfuncs: dict = {}        # "mod:fn" -> MethodInfo
        self.thread_spawns: list = []   # (relpath, lineno, info dict)
        self.cond_waits: list = []      # (relpath, lineno, in_while, supp)

    def add_class(self, relpath: str, ci: "ClassInfo") -> str:
        cid = f"{relpath}::{ci.name}"
        self.classes[cid] = ci
        self.by_name.setdefault(ci.name, []).append(cid)
        return cid

    def class_id_for_name(self, name: str) -> Optional[str]:
        ids = self.by_name.get(name)
        return ids[0] if ids and len(ids) == 1 else None

    def lock_node_for_attr(self, attr: str) -> Optional[str]:
        nodes = self.attr_lock_nodes.get(attr)
        if nodes and len(nodes) == 1:
            return next(iter(nodes))
        return None

    def all_methods(self):
        for ci in self.classes.values():
            yield from ci.methods.values()
        yield from self.modfuncs.values()


def _err(rule: str, message: str, subject: str) -> Finding:
    return Finding(rule, "error", message, subject)


# ---------------------------------------------------------------------------
# pass A: inventory
# ---------------------------------------------------------------------------

def _mod_base(relpath: str) -> str:
    return relpath.rsplit("/", 1)[-1][:-3]


def _inventory(files: list, model: PackageModel) -> list:
    """files: [(relpath, tree, comments)].  Fills classes/locks/types/
    owners; returns the same list."""
    for relpath, tree, _comments in files:
        base = _mod_base(relpath)
        for node in tree.body:
            if isinstance(node, pyast.Assign) and \
                    (got := lock_call_kind(node.value)) is not None:
                for tgt in node.targets:
                    if isinstance(tgt, pyast.Name):
                        model.module_locks[tgt.id] = \
                            got[1] or f"{base}.{tgt.id}"
        for cls in [n for n in pyast.walk(tree)
                    if isinstance(n, pyast.ClassDef)]:
            ci = ClassInfo(cls.name, relpath)
            for attr, (kind, explicit) in class_lock_attrs(cls).items():
                node_name = explicit or f"{cls.name}.{attr}"
                ci.locks[attr] = (kind, node_name)
                model.attr_lock_nodes.setdefault(attr, set()).add(node_name)
            cid = model.add_class(relpath, ci)
            for stmt in cls.body:
                if isinstance(stmt, (pyast.FunctionDef,
                                     pyast.AsyncFunctionDef)):
                    model.method_owner.setdefault(stmt.name,
                                                  set()).add(cid)
        # non-self lock-attr assignments (rt._net_gate = new_rlock(...))
        for n in pyast.walk(tree):
            if not isinstance(n, pyast.Assign):
                continue
            got = lock_call_kind(n.value)
            if got is None:
                continue
            for tgt in n.targets:
                if isinstance(tgt, pyast.Attribute) and \
                        self_attr(tgt) is None:
                    model.attr_lock_nodes.setdefault(
                        tgt.attr, set()).add(got[1] or tgt.attr)
    # attribute typing: self.X = ClassName(...) (two passes so an
    # attr-to-attr alias like `rt._store = rt.error_store` resolves)
    for _ in range(2):
        for relpath, tree, _comments in files:
            for n in pyast.walk(tree):
                if not isinstance(n, pyast.Assign):
                    continue
                t = _expr_type(n.value, model)
                if t is None:
                    continue
                for tgt in n.targets:
                    if isinstance(tgt, pyast.Attribute):
                        model.attr_types.setdefault(tgt.attr, set()).add(t)
    return files


# sentinel class ID for receivers constructed from stdlib modules:
# their method calls (Thread.start, Event.set, ...) must resolve to
# NOTHING instead of falling back onto same-named engine methods —
# `t = threading.Thread(...); t.start()` used to compose every engine
# `start()` (SiddhiAppRuntime.start included) into the caller's
# blocking closure, minting false SL05 chains through nonblocking
# stdlib calls
_EXTERNAL = "<external>"
_EXTERNAL_MODULES = {"threading", "queue", "socket", "subprocess"}


def _expr_type(value, model: PackageModel) -> Optional[str]:
    """Best-effort class ID for an assigned expression (None when the
    constructor name is ambiguous across modules; the `_EXTERNAL`
    sentinel for stdlib-module constructors)."""
    if isinstance(value, pyast.Call):
        f = value.func
        if isinstance(f, pyast.Attribute) and \
                isinstance(f.value, pyast.Name) and \
                f.value.id in _EXTERNAL_MODULES:
            return _EXTERNAL
        name = call_name(value)
        if name is not None:
            return model.class_id_for_name(name)
    if isinstance(value, pyast.Attribute):
        types = model.attr_types.get(value.attr)
        if types and len(types) == 1:
            return next(iter(types))
    return None


# ---------------------------------------------------------------------------
# pass B: per-function walk
# ---------------------------------------------------------------------------

# names too generic for the unique-method-name call-resolution fallback
_GENERIC = {
    "append", "add", "get", "pop", "update", "clear", "remove", "extend",
    "insert", "sort", "write", "read", "close", "flush", "send", "recv",
    "join", "wait", "put", "keys", "items", "values", "count", "index",
    "copy", "setdefault", "discard", "open", "next", "encode", "decode",
    "name", "release", "acquire", "dump", "dumps", "load", "loads",
}


class _FnWalker:
    """One function/method body: tracks the held-lock stack, local lock
    bindings, attribute accesses, calls, and blocking primitives."""

    def __init__(self, model: PackageModel, cls: Optional[ClassInfo],
                 info: MethodInfo, comments: dict,
                 bindings: Optional[dict] = None):
        self.model = model
        self.cls = cls
        self.info = info
        self.comments = comments        # lineno -> comment token text
        self.held: list = []            # lock node names, outer first
        self.bindings = dict(bindings or {})
        self.while_depth = 0

    # -- resolution ---------------------------------------------------------

    def _suppressed(self, lineno: int, legacy: bool = False) -> bool:
        if justified_pragma(self.comments, lineno, ALLOW):
            return True
        return legacy and justified_pragma(self.comments, lineno,
                                           ALLOW_LEGACY)

    def resolve_lock(self, e) -> Optional[str]:
        """Lock node name for an expression, or None."""
        if isinstance(e, pyast.Name):
            return self.bindings.get(e.id) or \
                self.model.module_locks.get(e.id)
        attr = self_attr(e)
        if attr is not None and self.cls is not None and \
                attr in self.cls.locks:
            return self.cls.locks[attr][1]
        if isinstance(e, pyast.Attribute):
            return self.model.lock_node_for_attr(e.attr)
        if isinstance(e, pyast.Call):
            name = call_name(e)
            if name == "getattr" and len(e.args) >= 2 and \
                    isinstance(e.args[1], pyast.Constant):
                return self.model.lock_node_for_attr(str(e.args[1].value))
            got = lock_call_kind(e)
            if got is not None and got[1]:
                return got[1]
            # own-method call with a known returns-lock summary
            if self_attr(e.func) is not None and self.cls is not None:
                m = self.cls.methods.get(e.func.attr)
                if m is not None:
                    return m.returns_lock
        return None

    def resolve_recv(self, func) -> Optional[str]:
        """Receiver class ID for a method call, or "self", or None."""
        if not isinstance(func, pyast.Attribute):
            return None
        v = func.value
        if isinstance(v, pyast.Name):
            if v.id == "self":
                return "self"
            t = self.bindings.get("type:" + v.id)
            if t:
                return t
        if isinstance(v, pyast.Attribute):
            types = self.model.attr_types.get(v.attr)
            if types and len(types) == 1:
                return next(iter(types))
        # unique-method-name fallback (non-generic names only)
        name = func.attr
        if name not in _GENERIC:
            owners = self.model.method_owner.get(name)
            if owners and len(owners) == 1:
                return next(iter(owners))
        return None

    # -- blocking classification --------------------------------------------

    def blocking_what(self, call: pyast.Call) -> Optional[str]:
        f = call.func
        if not isinstance(f, pyast.Attribute):
            if isinstance(f, pyast.Name) and f.id == "urlopen":
                return "urllib urlopen"
            return None
        recv = f.value
        recv_txt = recv.attr if isinstance(recv, pyast.Attribute) else \
            recv.id if isinstance(recv, pyast.Name) else ""
        m = f.attr
        if m in _SOCKET_METHODS and _SOCKETISH.search(recv_txt):
            return f"socket .{m}()"
        if m == "create_connection" and recv_txt == "socket":
            return "socket connect"
        if m == "sleep" and recv_txt == "time":
            return "time.sleep"
        if m == "fsync" and recv_txt == "os":
            return "os.fsync"
        if m == "wait":
            return f"{recv_txt or '<obj>'}.wait()"
        if m == "join" and (
                any(k.arg == "timeout" for k in call.keywords)
                or _THREADISH.search(recv_txt)):
            return f"{recv_txt or '<obj>'}.join()"
        if m in ("get", "put") and _QUEUEISH.search(recv_txt):
            return f"queue .{m}()"
        if recv_txt == "subprocess" or (
                m in ("communicate", "check_output", "check_call")):
            return f"subprocess {m}"
        if m == "urlopen":
            return "urllib urlopen"
        return None

    # -- the walk -----------------------------------------------------------

    def walk(self, stmts) -> None:
        for s in stmts:
            self.stmt(s)

    def stmt(self, node) -> None:
        if isinstance(node, pyast.With):
            self.handle_with(node)
            return
        if isinstance(node, (pyast.FunctionDef, pyast.AsyncFunctionDef)):
            # a nested function runs LATER, not under the locks held at
            # its definition: fresh held stack, inherited bindings
            self.handle_nested(node)
            return
        if isinstance(node, pyast.ClassDef):
            return                      # nested classes: out of scope
        if isinstance(node, pyast.Assign):
            self.handle_assign(node)
            return
        if isinstance(node, pyast.AugAssign):
            tgt = self_attr(node.target)
            if tgt is not None:
                self.record_access(tgt, node.lineno, "write")
            self.expr(node.value)
            return
        if isinstance(node, pyast.Return):
            if node.value is not None:
                lk = self.resolve_lock(node.value)
                if lk is not None and self.info.returns_lock is None:
                    self.info.returns_lock = lk
                self.expr(node.value)
            return
        if isinstance(node, pyast.While):
            self.expr(node.test)
            self.while_depth += 1
            self.walk(node.body)
            self.walk(node.orelse)
            self.while_depth -= 1
            return
        if isinstance(node, pyast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, pyast.Subscript) else t
                attr = self_attr(base)
                if attr is not None:
                    self.record_access(attr, node.lineno, "write")
            return
        # generic: visit expressions, recurse into bodies
        for fname, value in pyast.iter_fields(node):
            if isinstance(value, pyast.expr):
                self.expr(value)
            elif isinstance(value, list):
                if value and isinstance(value[0], pyast.stmt):
                    self.walk(value)
                elif value and isinstance(value[0], pyast.expr):
                    for v in value:
                        self.expr(v)
                elif value and isinstance(value[0], pyast.excepthandler):
                    for h in value:
                        self.walk(h.body)

    def handle_with(self, node: pyast.With) -> None:
        acquired = []
        for item in node.items:
            self.expr(item.context_expr, as_with=True)
            lk = self.resolve_lock(item.context_expr)
            if lk is not None:
                # edges are FACTS: a suppression only silences the SL04
                # finding, never the graph (the runtime lock-witness is
                # checked against the full graph)
                supp = self._suppressed(node.lineno)
                for outer in self.held:
                    if outer != lk:
                        self.info.edges.append((outer, lk, node.lineno,
                                                supp))
                self.info.acquires.setdefault(lk, node.lineno)
                self.held.append(lk)
                acquired.append(lk)
            if item.optional_vars is not None and lk is not None and \
                    isinstance(item.optional_vars, pyast.Name):
                self.bindings[item.optional_vars.id] = lk
        self.walk(node.body)
        for _ in acquired:
            self.held.pop()

    def handle_nested(self, node) -> None:
        qual = f"{self.info.name}.{node.name}"
        sub = MethodInfo(self.info.cls, qual, cls_id=self.info.cls_id,
                         relpath=self.info.relpath, exempt=self.info.exempt)
        w = _FnWalker(self.model, self.cls, sub, self.comments,
                      self.bindings)
        w.walk(node.body)
        if self.cls is not None:
            self.cls.methods[qual] = sub
        else:
            self.model.modfuncs[f"{self.info.relpath}:{qual}"] = sub

    def handle_assign(self, node: pyast.Assign) -> None:
        lk = self.resolve_lock(node.value)
        t = _expr_type(node.value, self.model)
        for tgt in node.targets:
            if isinstance(tgt, pyast.Name):
                if lk is not None:
                    self.bindings[tgt.id] = lk
                if t is not None:
                    self.bindings["type:" + tgt.id] = t
            attr = self_attr(tgt)
            if attr is not None:
                self.record_access(attr, node.lineno, "write")
            elif isinstance(tgt, pyast.Subscript):
                battr = self_attr(tgt.value)
                if battr is not None:
                    self.record_access(battr, node.lineno, "write")
                else:
                    self.expr(tgt.value)
        self.expr(node.value)

    def record_access(self, attr: str, lineno: int, kind: str) -> None:
        if self.cls is None or attr in self.cls.locks:
            return
        self.info.accesses.append(Access(
            attr, lineno, kind,
            frozenset(self.held), self.info.name,
            suppressed=self._suppressed(lineno, legacy=True)))

    def expr(self, node, as_with: bool = False) -> None:
        if node is None:
            return
        if isinstance(node, pyast.Call):
            self.handle_call(node)
            return
        if isinstance(node, pyast.Lambda):
            sub = MethodInfo(self.info.cls,
                             f"{self.info.name}.<lambda>",
                             cls_id=self.info.cls_id,
                             relpath=self.info.relpath,
                             exempt=self.info.exempt)
            w = _FnWalker(self.model, self.cls, sub, self.comments,
                          self.bindings)
            w.expr(node.body)
            if self.cls is not None:
                self.cls.methods.setdefault(sub.name, sub)
            return
        attr = self_attr(node)
        if attr is not None and isinstance(node.ctx, pyast.Load) \
                and not as_with:
            self.record_access(attr, node.lineno, "read")
            return
        for child in pyast.iter_child_nodes(node):
            if isinstance(child, pyast.expr):
                self.expr(child)
            elif isinstance(child, pyast.comprehension):
                self.expr(child.iter)
                for c in child.ifs:
                    self.expr(c)

    def handle_call(self, call: pyast.Call) -> None:
        f = call.func
        name = call_name(call)
        supp = self._suppressed(call.lineno)
        # thread spawn (SL06)
        if name == "Thread":
            self.model.thread_spawns.append(
                (self.info.relpath, call.lineno, self._thread_info(call),
                 self.info.cls_id, supp))
        # Condition.wait predicate-loop check (SL06).  A wait on an
        # owned Condition RELEASES that lock while parked — the correct
        # idiom, not an SL05 blocking-under-lock
        is_cond_wait = False
        if isinstance(f, pyast.Attribute) and f.attr == "wait":
            cattr = self_attr(f.value)
            if cattr is not None and self.cls is not None and \
                    self.cls.locks.get(cattr, ("", ""))[0] == "condition":
                is_cond_wait = True
                self.model.cond_waits.append(
                    (self.info.relpath, call.lineno,
                     self.while_depth > 0, supp))
        # blocking primitive (SL05, direct)
        what = None if is_cond_wait else self.blocking_what(call)
        if what is not None:
            self.info.blocking.append((call.lineno, what, supp,
                                       tuple(self.held)))
            if what.endswith(".join()"):
                self.info.thread_join = True
        # container mutation through a method (SL03 write); the
        # receiver of a NON-mutating method call is still a read
        if isinstance(f, pyast.Attribute):
            battr = self_attr(f.value)
            if battr is not None:
                self.record_access(
                    battr, call.lineno,
                    "write" if f.attr in MUTATING_METHODS else "read")
        # call-site summary (SL04/SL05 composition)
        if isinstance(f, pyast.Attribute):
            self.info.calls.append(CallSite(
                f.attr, self.resolve_recv(f), call.lineno,
                tuple(self.held), supp))
            if self_attr(f.value) is None and \
                    not (isinstance(f.value, pyast.Name)
                         and f.value.id == "self"):
                self.expr(f.value)
        elif isinstance(f, pyast.Name):
            self.info.calls.append(CallSite(
                f.id, None, call.lineno, tuple(self.held), supp))
        else:
            self.expr(f)
        for a in call.args:
            self.expr(a)
        for k in call.keywords:
            self.expr(k.value)

    @staticmethod
    def _thread_info(call: pyast.Call) -> dict:
        kw = {k.arg: k.value for k in call.keywords}
        daemon = isinstance(kw.get("daemon"), pyast.Constant) and \
            kw["daemon"].value is True
        name_kw = kw.get("name")
        if name_kw is None:
            tname = None
        elif isinstance(name_kw, pyast.Constant):
            tname = str(name_kw.value)
        else:
            tname = "<dynamic>"
        return {"daemon": daemon, "name": tname}


def _walk_files(files: list, model: PackageModel) -> None:
    """Pass B: walk every method twice — the first round computes
    returns-lock summaries, the second resolves bindings made through
    them (e.g. ``gate = self._gate_of(rt)``)."""
    for _round in (1, 2):
        model.thread_spawns.clear()
        model.cond_waits.clear()
        for relpath, tree, comments in files:
            for cls_node in [n for n in pyast.walk(tree)
                             if isinstance(n, pyast.ClassDef)]:
                cid = f"{relpath}::{cls_node.name}"
                ci = model.classes[cid]
                for stmt in cls_node.body:
                    if not isinstance(stmt, (pyast.FunctionDef,
                                             pyast.AsyncFunctionDef)):
                        continue
                    prev = ci.methods.get(stmt.name)
                    info = MethodInfo(
                        ci.name, stmt.name, cls_id=cid, relpath=relpath,
                        # the *_locked SUFFIX is the caller-holds-lock
                        # convention; a substring match would also
                        # exempt e.g. `on_blocked` — the opposite of
                        # the intent in block-policy-heavy code
                        exempt=(stmt.name == "__init__"
                                or stmt.name.endswith("_locked")))
                    if prev is not None:
                        info.returns_lock = prev.returns_lock
                    ci.methods[stmt.name] = info
                    _FnWalker(model, ci, info, comments).walk(stmt.body)
                ci.has_join = ci.has_join or any(
                    m.thread_join for m in ci.methods.values())
            for stmt in tree.body:
                if isinstance(stmt, (pyast.FunctionDef,
                                     pyast.AsyncFunctionDef)):
                    info = MethodInfo(None, stmt.name, relpath=relpath)
                    model.modfuncs[f"{relpath}:{stmt.name}"] = info
                    _FnWalker(model, None, info, comments).walk(stmt.body)


# ---------------------------------------------------------------------------
# the lock graph (SL04) + blocking closure (SL05)
# ---------------------------------------------------------------------------

def _resolve_callees(model: PackageModel, site: CallSite,
                     cls: Optional[str]) -> list:
    """Candidate MethodInfos for a call site.  An unresolved receiver
    with a non-generic method name owned by a FEW classes resolves to
    ALL of them — over-approximation keeps the static graph a superset
    of what the runtime lock-witness can observe.  A receiver typed to
    the stdlib sentinel resolves to nothing: its methods are real but
    not engine code, and the name fallback must not alias them."""
    owner = cls if site.recv == "self" else site.recv
    if owner == _EXTERNAL:
        return []
    if owner is not None:
        ci = model.classes.get(owner)
        m = ci.methods.get(site.name) if ci is not None else None
        if m is not None:
            return [m]
        # fall through: `self.inject(...)` may be a callable ATTRIBUTE
        # (a bound method handed in at construction), not an own method
    if site.name in _GENERIC:
        return []
    owners = model.method_owner.get(site.name) or ()
    if len(owners) > 8:
        return []
    return [m for o in sorted(owners)
            for m in [model.classes[o].methods.get(site.name)]
            if m is not None]


def _closure(model: PackageModel, seed_fn) -> dict:
    """Generic transitive closure over the call graph.  `seed_fn(m)`
    -> set of facts directly true in method m; returns {id(m): facts}
    where facts propagate from callees to callers."""
    facts = {id(m): set(seed_fn(m)) for m in model.all_methods()}
    methods = list(model.all_methods())
    changed = True
    while changed:
        changed = False
        for m in methods:
            mine = facts[id(m)]
            before = len(mine)
            for c in m.calls:
                for callee in _resolve_callees(model, c, m.cls_id):
                    mine |= facts[id(callee)]
            if len(mine) != before:
                changed = True
    return facts


def build_lock_graph(model: PackageModel) -> dict:
    """{"nodes": set, "edges": {(a, b): (relpath, lineno, suppressed)}}
    — direct nesting edges plus call-composed ones (holding A, call a
    method that eventually acquires B => A -> B).  Suppressed edges
    stay IN the graph (they are facts the lock-witness will observe);
    the flag only exempts them from SL04 cycle findings."""
    nodes: set = set(model.module_locks.values())
    for ci in model.classes.values():
        for _a, (_k, node) in ci.locks.items():
            nodes.add(node)
    edges: dict = {}
    acq = _closure(model, lambda m: set(m.acquires))
    for m in model.all_methods():
        for a, b, lineno, supp in m.edges:
            _add_edge(edges, a, b, (m.relpath, lineno, supp))
            nodes.update((a, b))
        for c in m.calls:
            if not c.held:
                continue
            for callee in _resolve_callees(model, c, m.cls_id):
                for inner in acq[id(callee)]:
                    for outer in c.held:
                        if outer != inner:
                            _add_edge(edges, outer, inner,
                                      (m.relpath, c.lineno, c.suppressed))
                            nodes.update((outer, inner))
    return {"nodes": nodes, "edges": edges}


def _add_edge(edges: dict, a: str, b: str, site: tuple) -> None:
    """Keep the first site, but an UNSUPPRESSED sighting always wins
    over a suppressed one (a pragma on one site must not blanket-allow
    the same order somewhere else)."""
    prev = edges.get((a, b))
    if prev is None or (prev[2] and not site[2]):
        edges[(a, b)] = site


def _reaches(edges: dict, src: str, dst: str) -> bool:
    succ: dict = {}
    for (a, b) in edges:
        succ.setdefault(a, set()).add(b)
    seen, todo = set(), [src]
    while todo:
        n = todo.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        todo.extend(succ.get(n, ()))
    return False


def _cycles(graph: dict) -> list:
    """Strongly connected components with >= 2 nodes, as sorted node
    tuples (Tarjan, iterative)."""
    succ: dict = {}
    for (a, b) in graph["edges"]:
        succ.setdefault(a, set()).add(b)
    index: dict = {}
    low: dict = {}
    on: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    def strongconnect(root):
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(succ.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) >= 2:
                    out.append(tuple(sorted(comp)))

    for n in sorted(graph["nodes"]):
        if n not in index:
            strongconnect(n)
    return out


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _sl03(model: PackageModel) -> tuple:
    findings, suppressions = [], []
    for ci in model.classes.values():
        if not ci.locks:
            continue
        own_nodes = {node for _k, node in ci.locks.values()}
        per_attr: dict = {}
        for m in ci.methods.values():
            if m.exempt:
                continue
            for a in m.accesses:
                per_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(per_attr.items()):
            if not any(a.kind == "write" for a in accs):
                continue            # init-only / read-only: not shared-mutable
            eligible = [a for a in accs
                        if (a.held & own_nodes) or not a.suppressed]
            if not eligible:
                continue
            counts: dict = {}
            for a in eligible:
                for lk in (a.held & own_nodes):
                    counts[lk] = counts.get(lk, 0) + 1
            if not counts:
                continue
            dominant, guarded = max(counts.items(), key=lambda kv: kv[1])
            if guarded < MIN_GUARDED or guarded / len(eligible) < DOMINANCE:
                continue
            bad = [a for a in accs
                   if dominant not in a.held and not a.suppressed]
            for a in accs:
                if dominant not in a.held and a.suppressed:
                    suppressions.append(("SL03", ci.relpath, a.lineno))
            if not bad:
                continue
            sites = ", ".join(f"{a.method}:{a.lineno} ({a.kind})"
                              for a in bad[:4])
            more = f" (+{len(bad) - 4} more)" if len(bad) > 4 else ""
            findings.append(_err(
                "SL03",
                f"`self.{attr}` in {ci.name!r} is guarded by "
                f"{dominant!r} at {guarded}/{len(eligible)} accesses but "
                f"accessed without it at {sites}{more} — inconsistent "
                f"guard is a data race; lock it, rename the method "
                f"`*_locked`, or annotate `# {ALLOW} (<why>)`",
                f"{ci.relpath}:{bad[0].lineno}"))
    return findings, suppressions


def _sl04(model: PackageModel, graph: dict) -> list:
    findings = []
    live = {"nodes": graph["nodes"],
            "edges": {k: v for k, v in graph["edges"].items()
                      if not v[2]}}
    for comp in _cycles(live):
        inside = [((a, b), site) for (a, b), site in live["edges"].items()
                  if a in comp and b in comp]
        chain = "; ".join(f"{a} -> {b} at {site[0]}:{site[1]}"
                          for (a, b), site in sorted(inside)[:6])
        findings.append(_err(
            "SL04",
            f"lock-order inversion between {{{', '.join(comp)}}} — "
            f"two threads taking these in opposite orders deadlock; "
            f"break one edge or annotate its `with`/call line "
            f"`# {ALLOW} (<why>)`.  Edges: {chain}",
            f"{sorted(inside)[0][1][0]}:{sorted(inside)[0][1][1]}"))
    return findings


def _sl05(model: PackageModel) -> tuple:
    findings, suppressions = [], []
    blocking = _closure(
        model, lambda m: {(w, f"{m.cls or ''}.{m.name}".lstrip("."))
                          for (_ln, w, supp, _held) in m.blocking
                          if not supp})
    for m in model.all_methods():
        # direct blocking calls inside a with-lock scope
        for lineno, what, supp, held in m.blocking:
            if not held:
                continue
            if supp:
                suppressions.append(("SL05", m.relpath, lineno))
                continue
            findings.append(_err(
                "SL05",
                f"{what} while holding {held[-1]!r} "
                f"(in {m.cls or m.relpath}.{m.name}) — a blocking call "
                f"under a lock stalls every other thread that needs it; "
                f"move it outside the guard or annotate "
                f"`# {ALLOW} (<why>)`",
                f"{m.relpath}:{lineno}"))
        # blocking reached through a callee while a lock is held
        for c in m.calls:
            if not c.held:
                continue
            facts = set()
            for callee in _resolve_callees(model, c, m.cls_id):
                facts |= blocking[id(callee)]
            if not facts:
                continue
            if c.suppressed:
                suppressions.append(("SL05", m.relpath, c.lineno))
                continue
            what, via = sorted(facts)[0]
            findings.append(_err(
                "SL05",
                f"call to {c.name}() while holding {c.held[-1]!r} "
                f"(in {m.cls or m.relpath}.{m.name}) reaches {what} "
                f"via {via} — blocking under a lock; restructure or "
                f"annotate the call line `# {ALLOW} (<why>)`",
                f"{m.relpath}:{c.lineno}"))
    return findings, suppressions


def _sl06(model: PackageModel) -> tuple:
    findings, suppressions = [], []
    for relpath, lineno, info, cls, supp in model.thread_spawns:
        probs = []
        if not info["daemon"] and not (
                cls and model.classes[cls].has_join):
            probs.append("neither daemon=True nor join-tracked by its "
                         "owner (leaks at shutdown)")
        if info["name"] is None:
            probs.append("unnamed — every engine thread must carry "
                         "name='siddhi-<role>' so leak checks and ops "
                         "tooling can attribute it")
        elif info["name"] != "<dynamic>" and \
                not info["name"].startswith("siddhi-"):
            probs.append(f"named {info['name']!r}, not 'siddhi-<role>'")
        if not probs:
            continue
        if supp:
            suppressions.append(("SL06", relpath, lineno))
            continue
        findings.append(_err(
            "SL06",
            "thread spawn is " + " and ".join(probs)
            + f"; fix it or annotate `# {ALLOW} (<why>)`",
            f"{relpath}:{lineno}"))
    for relpath, lineno, in_while, supp in model.cond_waits:
        if in_while:
            continue
        if supp:
            suppressions.append(("SL06", relpath, lineno))
            continue
        findings.append(_err(
            "SL06",
            "Condition.wait outside a predicate loop — spurious wakeups "
            "and missed notifies are real; wrap it in "
            "`while not <predicate>: cond.wait()` or annotate "
            f"`# {ALLOW} (<why>)`",
            f"{relpath}:{lineno}"))
    return findings, suppressions


def _sl07(files: list) -> list:
    """Every `# lint: ...` pragma must carry a (why) — same grammar
    (walker.pragma_re) the suppression check and baseline inventory
    apply, so nothing can suppress without being counted.  Only real
    COMMENT tokens are considered: docstring/string mentions of the
    grammar are prose, not pragmas."""
    out = []
    # longest tag first: "lint: allow" is a prefix of "lint: allow-swallow"
    tags = sorted((ALLOW_SWALLOW, ALLOW_LEGACY, ALLOW), key=len,
                  reverse=True)
    bare = {t: re.compile(r"#\s*" + re.escape(t)) for t in tags}
    just = {t: pragma_re(t) for t in tags}
    for relpath, _tree, comments in files:
        for lineno in sorted(comments):
            text = comments[lineno]
            tag = next((t for t in tags if bare[t].search(text)), None)
            if tag is None or just[tag].search(text):
                continue
            out.append(_err(
                "SL07",
                f"suppression `# {tag}` without a justification — "
                f"the why is mandatory: `# {tag} (<why>)`",
                f"{relpath}:{lineno}"))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _parse_files(sources: list) -> tuple:
    """[(relpath, text)] -> ([(relpath, tree, comments)],
    parse_findings) — `comments` maps lineno to real comment tokens
    (walker.comment_map), the only place pragmas are honored."""
    files, findings = [], []
    for relpath, text in sources:
        try:
            tree = pyast.parse(text)
        except SyntaxError as e:
            findings.append(_err("SL00", f"does not parse: {e}", relpath))
            continue
        files.append((relpath, tree, comment_map(text)))
    return files, findings


def build_model(sources: list) -> tuple:
    """[(relpath, text)] -> (PackageModel, parse_findings)."""
    files, findings = _parse_files(sources)
    model = PackageModel()
    _inventory(files, model)
    _walk_files(files, model)
    return model, findings, files


def analyze_sources(sources: list) -> dict:
    """The full SL03–SL07 pass over [(relpath, text)].  Returns
    {"findings": [Finding], "graph": ..., "suppressions": [...]}."""
    model, findings, files = build_model(sources)
    graph = build_lock_graph(model)
    suppressions: list = []
    f3, s3 = _sl03(model)
    f5, s5 = _sl05(model)
    f6, s6 = _sl06(model)
    findings += f3 + _sl04(model, graph) + f5 + f6 + _sl07(files)
    suppressions += s3 + s5 + s6
    findings.sort(key=lambda f: (f.subject or "", f.rule_id))
    return {"findings": findings, "graph": graph, "model": model,
            "suppressions": sorted(suppressions)}


def analyze_package(root: Optional[str] = None) -> dict:
    return analyze_sources(list(iter_package(root)))


def lint_threads_source(text: str, relpath: str = "<snippet>.py") -> list:
    """SL03–SL07 over ONE module in isolation (the seeded-corpus entry
    point)."""
    return analyze_sources([(relpath, text)])["findings"]


def static_lock_graph(root: Optional[str] = None) -> dict:
    """{"nodes": sorted list, "edges": [[a, b, "file:line"], ...]} —
    the static model the runtime lock-witness is checked against."""
    g = analyze_package(root)["graph"]
    return {"nodes": sorted(g["nodes"]),
            "edges": sorted([a, b, f"{site[0]}:{site[1]}"]
                            for (a, b), site in g["edges"].items()),
            "suppressed_edges": sorted(
                f"{a} -> {b}" for (a, b), site in g["edges"].items()
                if site[2])}


def check_witness(witness: dict, graph: dict) -> list:
    """Compare a runtime lock-witness dump ({"locks": [...], "edges":
    [[outer, inner], ...]}) against the static graph.  A witnessed
    order the static model contradicts (knows only the REVERSE of) or
    does not know at all is a finding — the model must over-approximate
    reality or its SL04 verdicts are worthless."""
    findings = []
    nodes = set(graph["nodes"])
    edges = {(a, b) for (a, b) in graph["edges"]}
    for pair in witness.get("edges", ()):
        a, b = pair[0], pair[1]
        if a not in nodes or b not in nodes:
            missing = a if a not in nodes else b
            findings.append(_err(
                "SL04",
                f"runtime witnessed lock {missing!r} that the static "
                f"model never inventoried — a construction site the "
                f"analyzer cannot see (name it via utils.locks "
                f"factories)",
                f"witness:{a}->{b}"))
            continue
        if _reaches(edges, a, b):
            continue
        if _reaches(edges, b, a):
            findings.append(_err(
                "SL04",
                f"runtime acquisition order {a!r} -> {b!r} CONTRADICTS "
                f"the static graph (which only knows {b!r} -> {a!r}) — "
                f"either a real inversion or a model bug; both block",
                f"witness:{a}->{b}"))
        else:
            findings.append(_err(
                "SL04",
                f"runtime acquisition order {a!r} -> {b!r} is unknown "
                f"to the static graph — the model missed a nesting or "
                f"call edge and its cycle verdicts cannot be trusted",
                f"witness:{a}->{b}"))
    return findings


def check_witness_file(path: str, root: Optional[str] = None) -> list:
    with open(path, encoding="utf-8") as f:
        witness = json.load(f)
    g = analyze_package(root)["graph"]
    return check_witness(witness,
                         {"nodes": g["nodes"], "edges": g["edges"]})


def suppression_inventory(root: Optional[str] = None) -> dict:
    """{relpath: pragma count} over the package — the pinned-baseline
    unit: a NEW suppression anywhere fails CI until the baseline is
    deliberately regenerated (--baseline, scripts/threads_baseline.json).
    Counts REAL comment tokens with walker.pragma_re — the SAME grammar
    that makes a pragma suppress — so no spelling can take effect
    uncounted, and a docstring that merely quotes the grammar is not
    pinned as a suppression."""
    tags = sorted((ALLOW_SWALLOW, ALLOW_LEGACY, ALLOW), key=len,
                  reverse=True)
    rxs = [pragma_re(t) for t in tags]
    out: dict = {}
    for relpath, text in iter_package(root):
        n = sum(next((1 for rx in rxs if rx.search(c)), 0)
                for c in comment_map(text).values())
        if n:
            out[relpath] = n
    return out


def check_baseline(path: str, root: Optional[str] = None) -> list:
    """Compare the live suppression inventory to the pinned baseline;
    every drift (new, removed, or recounted) is a finding."""
    with open(path, encoding="utf-8") as f:
        pinned = json.load(f)
    live = suppression_inventory(root)
    findings = []
    for rel in sorted(set(pinned) | set(live)):
        want, got = pinned.get(rel, 0), live.get(rel, 0)
        if want != got:
            findings.append(_err(
                "SL-BASELINE",
                f"suppression count drifted: {rel} has {got} justified "
                f"pragma(s), baseline pins {want} — if the new "
                f"suppression is legitimate, regenerate the baseline "
                f"(python -m siddhi_tpu.analysis --threads "
                f"--write-baseline <path>) in the same commit",
                rel))
    return findings
