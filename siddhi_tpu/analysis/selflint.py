"""Self-lint: an AST checker over siddhi_tpu's OWN source.

Two bug classes keep coming back in review rounds, and both are
mechanical enough to gate in CI (`scripts/smoke.sh` runs
``python -m siddhi_tpu.analysis --self``):

SL01 — silent demotion.  In a plan-lowering file, an ``except`` handler
  that catches a broad or lowering-related exception and neither
  re-raises nor records a ``Demotion`` (a call named ``demote`` /
  ``record_demotion``) is exactly the bug class PR 5 shipped: a whole
  query class quietly losing its device path.  A legitimate swallow
  (best-effort metrics sampling, probes) must say so on the ``except``
  line with ``# lint: allow-swallow (<why>)`` — the why is mandatory
  culture, not syntax.

SL02 — unguarded shared-counter mutation (the PR-9 lock-discipline
  class).  In a class that owns a ``threading.Lock``/``RLock``
  attribute, an augmented assignment to a counter-named ``self``
  attribute outside a ``with self.<lock>:`` block is a data race with
  whatever thread scrapes or also bumps it.  Methods whose NAME carries
  the convention that the caller holds the lock (``*_locked``) are
  exempt, as is ``# lint: unlocked-ok (<why>)`` on the statement line.

The linter is deliberately lexical: it proves nothing, it just makes
the two recurring mistakes impossible to commit *silently*.
"""
from __future__ import annotations

import ast as pyast
import os
import re
from typing import Optional

from .rules import Finding
from .walker import (class_lock_attrs, has_pragma,  # noqa: F401 (re-export)
                     iter_package, package_root)

# files whose except-handlers are on a plan-lowering path (SL01 scope)
LOWERING_FILES = (
    "core/build.py",
    "core/planner.py",
    "core/partition.py",
    "core/pattern_plan.py",
    "core/window_device.py",
    "core/join_device.py",
    "core/multi_query.py",
    "core/nfa_device.py",
    "core/nfa_parallel.py",
)

# exception type names whose swallow demotes a plan (broad catches plus
# the lowering-unsupported family)
_CHECKED_TYPES = {
    "Exception", "BaseException",
    "DeviceNFAUnsupported", "DeviceWindowUnsupported",
    "DeviceJoinUnsupported", "ParallelUnsupported",
    "PlanError", "ExprError", "AutotuneError", "TableError",
}

_DEMOTE_CALLS = {"demote", "record_demotion"}

_COUNTER_RE = re.compile(
    r"(count|total|hits|misses|dropped|stored|shed|evict|frames|events"
    r"|bytes|errors|retri|publish|fail|credit|pending|admitted|blocked"
    r"|corrupt|demotion)", re.I)

_SL01_PRAGMA = "lint: allow-swallow"
_SL02_PRAGMA = "lint: unlocked-ok"


def _sl(rule_id: str, message: str, subject: str) -> Finding:
    return Finding(rule_id, "error", message, subject)


# shared pragma helper (analysis/walker.py)
_has_pragma = has_pragma


def _etype_names(node) -> set:
    if node is None:                  # bare `except:` — maximally broad
        return {"BaseException"}
    if isinstance(node, pyast.Tuple):
        return set().union(*(_etype_names(e) for e in node.elts))
    if isinstance(node, pyast.Name):
        return {node.id}
    if isinstance(node, pyast.Attribute):
        return {node.attr}
    return set()


def _body_walk(handler: pyast.ExceptHandler):
    for stmt in handler.body:
        yield from pyast.walk(stmt)


def _records_demotion(handler: pyast.ExceptHandler) -> bool:
    for n in _body_walk(handler):
        if isinstance(n, pyast.Call):
            f = n.func
            name = f.attr if isinstance(f, pyast.Attribute) else \
                f.id if isinstance(f, pyast.Name) else None
            if name in _DEMOTE_CALLS:
                return True
    return False


def lint_sl01(tree, lines: list, relpath: str) -> list:
    out: list = []
    for node in pyast.walk(tree):
        if not isinstance(node, pyast.ExceptHandler):
            continue
        if not (_etype_names(node.type) & _CHECKED_TYPES):
            continue
        if _has_pragma(lines, node.lineno, _SL01_PRAGMA):
            continue
        if any(isinstance(n, pyast.Raise) for n in _body_walk(node)):
            continue
        if _records_demotion(node):
            continue
        out.append(_sl(
            "SL01",
            f"except handler swallows a lowering exception without "
            f"re-raising or recording a Demotion "
            f"(rt.placement.demote(...)); if the swallow is legitimate, "
            f"annotate the except line with "
            f"`# {_SL01_PRAGMA} (<why>)`",
            f"{relpath}:{node.lineno}"))
    return out


# ---------------------------------------------------------------------------
# SL02: unguarded counter mutation in lock-owning classes
# ---------------------------------------------------------------------------

def _lock_attrs(cls: pyast.ClassDef) -> set:
    """self attributes assigned a lock anywhere in the class body —
    raw threading.Lock()/RLock() AND the engine's named factories
    (utils.locks new_lock/new_rlock), via the shared walker."""
    return {attr for attr, (kind, _node) in class_lock_attrs(cls).items()
            if kind in ("lock", "rlock")}


def _with_guards(stack: list, locks: set) -> bool:
    """Is any enclosing `with` statement entered on one of the lock
    attributes (`with self._lock:` / `with self._lock, other:`)?"""
    for node in stack:
        if not isinstance(node, pyast.With):
            continue
        for item in node.items:
            e = item.context_expr
            if isinstance(e, pyast.Call):       # e.g. self._lock.acquire()?
                e = e.func
            if isinstance(e, pyast.Attribute) and \
                    isinstance(e.value, pyast.Name) and \
                    e.value.id == "self" and e.attr in locks:
                return True
    return False


def lint_sl02(tree, lines: list, relpath: str) -> list:
    out: list = []

    def visit(node, stack, cls, locks, fn):
        if isinstance(node, pyast.ClassDef):
            cls, locks, fn = node, _lock_attrs(node), None
        elif isinstance(node, (pyast.FunctionDef, pyast.AsyncFunctionDef)):
            fn = node
        elif (isinstance(node, pyast.AugAssign) and cls is not None
                and locks and fn is not None
                and isinstance(node.target, pyast.Attribute)
                and isinstance(node.target.value, pyast.Name)
                and node.target.value.id == "self"
                and _COUNTER_RE.search(node.target.attr)
                and "locked" not in fn.name
                and not _with_guards(stack, locks)
                and not _has_pragma(lines, node.lineno, _SL02_PRAGMA)):
            out.append(_sl(
                "SL02",
                f"augmented assignment to `self.{node.target.attr}` in "
                f"lock-owning class {cls.name!r} outside `with "
                f"self.<lock>:` — shared-counter mutation races the "
                f"scraper/other writers (PR-9 class); guard it, rename "
                f"the method `*_locked`, or annotate "
                f"`# {_SL02_PRAGMA} (<why>)`",
                f"{relpath}:{node.lineno}"))
        stack = stack + [node]
        for child in pyast.iter_child_nodes(node):
            visit(child, stack, cls, locks, fn)

    visit(tree, [], None, set(), None)
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_source(text: str, relpath: str) -> list:
    """Lint one module's source.  `relpath` is the package-relative
    POSIX path (e.g. ``core/build.py``) — it decides SL01 scope."""
    try:
        tree = pyast.parse(text)
    except SyntaxError as e:
        return [_sl("SL00", f"does not parse: {e}", relpath)]
    lines = text.splitlines()
    out: list = []
    if relpath.replace(os.sep, "/") in LOWERING_FILES:
        out += lint_sl01(tree, lines, relpath)
    out += lint_sl02(tree, lines, relpath)
    return out


def lint_package(root: Optional[str] = None) -> list:
    """Lint every .py under the siddhi_tpu package (the CI gate)."""
    out: list = []
    for rel, text in iter_package(root):
        out += lint_source(text, rel)
    return out
