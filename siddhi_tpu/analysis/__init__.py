"""Static query analyzer + EXPLAIN plane (docs/ANALYSIS.md).

Three cooperating parts:

  * ``rules``    — ~12 app-level lint rules over the parsed SiddhiQL AST
    (unbounded state, schema mismatches, dead graph elements, annotation
    conflicts), shared by the ``python -m siddhi_tpu.analysis`` CLI, the
    service deploy endpoint, and ``@app:strictAnalysis``;
  * ``core.placement`` — build-time placement accounting: every
    interpreter fallback records a ``Demotion``, surfaced by
    ``rt.explain()`` / ``GET /siddhi/artifact/explain`` / the CLI;
  * ``selflint`` — an AST checker over siddhi_tpu's OWN source (SL01
    silent-demotion swallows, SL02 unguarded shared counters), the
    ``--self`` CI gate in scripts/smoke.sh;
  * ``concurrency`` — whole-package concurrency self-analysis (SL03
    lockset, SL04 lock-order inversion, SL05 blocking-under-lock, SL06
    thread lifecycle), the ``--threads`` CI gate, validated against the
    runtime lock-witness (``utils/locks.py``, ``SIDDHI_LOCK_CHECK=1``).
"""
from __future__ import annotations

from .concurrency import (analyze_package as analyze_threads,  # noqa: F401
                          check_witness, lint_threads_source,
                          static_lock_graph)
from .rules import RULES, SEVERITIES, Finding, analyze_app  # noqa: F401
from .selflint import lint_package, lint_source             # noqa: F401


class StrictAnalysisError(Exception):
    """`@app:strictAnalysis` found error- or warn-severity findings at
    deploy: the app refuses to start.  `findings` carries the full
    list (info-severity included) for the service's diagnostics JSON."""

    def __init__(self, app_name: str, findings: list):
        self.findings = findings
        bad = [f for f in findings if f.severity in ("error", "warn")]
        lines = "\n  ".join(str(f) for f in bad)
        super().__init__(
            f"@app:strictAnalysis: app {app_name!r} has "
            f"{len(bad)} blocking finding(s) "
            f"(warnings promote to deploy errors):\n  {lines}")


def analyze_source(text: str) -> list:
    """Parse an app string and run every rule (the CLI/service path)."""
    from .rules import analyze_app as _analyze
    from ..query.parser import parse
    return _analyze(parse(text))


def strict_check(rt) -> list:
    """The `@app:strictAnalysis` deploy contract (called by the runtime
    constructor after the build): run the analyzer over the built app
    and raise StrictAnalysisError when anything at error OR warn
    severity is found.  Returns the findings (info included) so the
    service can report a clean-but-noted deploy."""
    findings = analyze_app(rt.app)
    if any(f.severity in ("error", "warn") for f in findings):
        raise StrictAnalysisError(rt.app.name, findings)
    return findings
