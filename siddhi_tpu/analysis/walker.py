"""Shared AST machinery for the self-lint passes.

`selflint.py` (SL01/SL02) and `concurrency.py` (SL03–SL06) walk the
same package with the same primitives: lock-attribute inventory,
`# lint:` pragma handling, `with self.<lock>:` guard resolution, and
package iteration.  This module holds the one implementation.

Lock construction is recognized in two shapes:

    self._lock = threading.Lock() / threading.RLock() /
                 threading.Condition()
    self._lock = new_lock("Class._lock") / new_rlock("Class._lock")

The second is the engine's own convention (`siddhi_tpu/utils/locks.py`
named factories): the string argument IS the canonical node name the
static lock graph and the runtime lock-witness share.
"""
from __future__ import annotations

import ast as pyast
import os
import re
from typing import Optional

# factory call names that create a lock-like object
LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock",
                  "new_lock": "lock", "new_rlock": "rlock",
                  "Condition": "condition", "new_condition": "condition",
                  "Semaphore": "lock", "BoundedSemaphore": "lock"}

# methods whose call on an attribute MUTATES the underlying container
MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse", "__setitem__",
}


def call_name(node: pyast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, pyast.Attribute):
        return f.attr
    if isinstance(f, pyast.Name):
        return f.id
    return None


def self_attr(node) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if isinstance(node, pyast.Attribute) and \
            isinstance(node.value, pyast.Name) and node.value.id == "self":
        return node.attr
    return None


def has_pragma(lines: list, lineno: int, tag: str) -> bool:
    """`tag` on the node's line or the line directly above it."""
    for ln in (lineno - 1, lineno - 2):
        if 0 <= ln < len(lines) and tag in lines[ln]:
            return True
    return False


def pragma_re(tag: str) -> "re.Pattern":
    """ONE grammar for a justified suppression, shared by the
    suppression check (justified_pragma), the bare-pragma rule (SL07),
    and the baseline inventory (suppression_inventory) — the three MUST
    agree or a suppression could take effect without being counted:
    a `#` comment marker, the tag, then `(<non-empty why>`."""
    return re.compile(r"#\s*" + re.escape(tag) + r"\s*\(\s*\S")


def comment_map(text: str) -> dict:
    """{1-based lineno: comment text} for REAL comment tokens only —
    a docstring or string literal that merely mentions the pragma
    grammar must neither suppress findings nor count in the pinned
    baseline."""
    import io
    import tokenize
    out: dict = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparsable tail: fall back to a lexical scan so a pragma
        # never silently stops applying mid-file
        for i, line in enumerate(text.splitlines(), 1):
            if "#" in line:
                out[i] = line[line.index("#"):]
    return out


def justified_pragma(comments: dict, lineno: int, tag: str) -> bool:
    """True when a REAL comment on the node's line (or the line
    directly above) carries the tag with a non-empty justification:
    `# lint: allow (<why>)`.  A bare tag does NOT suppress — the why
    is mandatory."""
    rx = pragma_re(tag)
    return any(rx.search(comments.get(ln, ""))
               for ln in (lineno, lineno - 1))


def lock_call_kind(node) -> Optional[tuple]:
    """If `node` is a lock-factory Call: (kind, explicit_name_or_None).
    The explicit name is the string literal handed to new_lock/new_rlock
    — the canonical graph-node name."""
    if not isinstance(node, pyast.Call):
        return None
    name = call_name(node)
    kind = LOCK_FACTORIES.get(name or "")
    if kind is None:
        return None
    explicit = None
    if name in ("new_lock", "new_rlock", "new_condition") and node.args \
            and isinstance(node.args[0], pyast.Constant) \
            and isinstance(node.args[0].value, str):
        explicit = node.args[0].value
    return kind, explicit


def class_lock_attrs(cls: pyast.ClassDef) -> dict:
    """{attr: (kind, explicit_name)} for every `self.X = <lock>()`
    anywhere in the class body (nested functions included)."""
    locks: dict = {}
    for n in pyast.walk(cls):
        if not isinstance(n, pyast.Assign):
            continue
        got = lock_call_kind(n.value)
        if got is None:
            continue
        for tgt in n.targets:
            attr = self_attr(tgt)
            if attr is not None:
                locks[attr] = got
    return locks


def iter_package(root: Optional[str] = None):
    """Yield (relpath, source_text) for every .py under the package."""
    root = root or package_root()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                yield rel, f.read()


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
