"""Extension documentation generator.

Reference: modules/siddhi-doc-gen (Maven mojo generating mkdocs pages
from @Extension metadata, MarkdownDocumentationGenerationMojo).  Here the
extension surface IS the registries, so the docs are generated from them
directly — every registered window type, aggregator, scalar/stream
function, source/sink/mapper, store type, and statistics reporter.

Run:  python -m siddhi_tpu.docgen [out.md]
"""
from __future__ import annotations

import inspect
from typing import Optional


def _rows(registry: dict, describe=None) -> list:
    out = []
    for key in sorted(registry, key=str):
        obj = registry[key]
        name = key if isinstance(key, str) else \
            (f"{key[0]}:{key[1]}" if key[0] else key[1])
        doc = ""
        if describe is not None:
            doc = describe(obj)
        elif inspect.isclass(obj) or inspect.isfunction(obj):
            doc = (inspect.getdoc(obj) or "").split("\n")[0]
        out.append((name, doc))
    return out


def generate_markdown() -> str:
    """One markdown document covering every extension point."""
    from .core.expr import SCALAR_FUNCTIONS
    from .core.io import SINK_MAPPERS, SINK_TYPES, SOURCE_MAPPERS, SOURCE_TYPES
    from .core.record_table import STORE_TYPES
    from .core.stats import REPORTERS
    from .interp.expr import PY_FUNCTIONS
    from .interp.engine import STREAM_FUNCTIONS, WINDOW_TYPES
    from .interp.aggregators import AGGREGATOR_CLASSES

    sections = [
        ("Custom window types (`#window.<name>(...)`; 15 built-ins are "
         "compiled directly)", WINDOW_TYPES, None),
        ("Aggregators (selector functions)", AGGREGATOR_CLASSES, None),
        ("Scalar functions (device expression compiler)", SCALAR_FUNCTIONS,
         None),
        ("Scalar functions (host interpreter)", PY_FUNCTIONS, None),
        ("Stream functions (`#<ns>:<name>(...)`)", STREAM_FUNCTIONS, None),
        ("Source types (`@source(type=...)`)", SOURCE_TYPES, None),
        ("Sink types (`@sink(type=...)`)", SINK_TYPES, None),
        ("Source mappers (`@map(type=...)`)", SOURCE_MAPPERS, None),
        ("Sink mappers (`@map(type=...)`)", SINK_MAPPERS, None),
        ("Store types (`@store(type=...)`)", STORE_TYPES, None),
        ("Statistics reporters (`@app:statistics(reporter=...)`)",
         REPORTERS, None),
    ]
    lines = ["# siddhi-tpu extension reference", "",
             "Generated from the live extension registries "
             "(`python -m siddhi_tpu.docgen`).", ""]
    for title, registry, describe in sections:
        lines.append(f"## {title}")
        lines.append("")
        lines.append("| name | description |")
        lines.append("|---|---|")
        for name, doc in _rows(registry, describe):
            lines.append(f"| `{name}` | {doc.replace('|', '/')} |")
        lines.append("")
    return "\n".join(lines)


def main(out: Optional[str] = None) -> None:
    md = generate_markdown()
    if out:
        with open(out, "w") as f:
            f.write(md)
        print(f"wrote {out} ({len(md.splitlines())} lines)")
    else:
        print(md)


if __name__ == "__main__":
    import sys
    main(sys.argv[1] if len(sys.argv) > 1 else None)
