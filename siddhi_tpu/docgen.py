"""Extension documentation generator.

Reference: modules/siddhi-doc-gen (Maven mojo generating mkdocs pages
from @Extension metadata — MarkdownDocumentationGenerationMojo renders
name/namespace/description/@Parameter/@Example per extension).  Here
the extension surface is the registries plus the built-in metadata
table (`siddhi_tpu.extension`), so docs generate directly from them:
every built-in window and aggregator gets a full section with
parameters, return contract, and examples; user extensions registered
with `meta=ExtensionMeta(...)` render the same way, others fall back
to a docstring line.

Run:  python -m siddhi_tpu.docgen [out.md]
"""
from __future__ import annotations

import inspect
from typing import Optional

from .extension import ExtensionMeta, all_meta, meta_for


def _meta_section(m: ExtensionMeta, level: str = "###") -> list:
    name = f"{m.namespace}:{m.name}" if m.namespace else m.name
    lines = [f"{level} `{name}`", "", m.description, ""]
    if m.parameters:
        lines += ["| parameter | types | description | optional | default |",
                  "|---|---|---|---|---|"]
        for p in m.parameters:
            lines.append(
                f"| `{p.name}` | {', '.join(str(t) for t in p.type)} | "
                f"{p.description} | {'yes' if p.optional else 'no'} | "
                f"{'' if p.default is None else p.default} |")
        lines.append("")
    if m.returns:
        lines += [f"**Returns**: {m.returns}", ""]
    for e in m.examples:
        lines += ["```siddhi", e.syntax, "```", "", e.description, ""]
    return lines


def _registry_rows(registry: dict, kind: str) -> list:
    """(name, meta-or-docline) rows for a user-extension registry."""
    out = []
    for key in sorted(registry, key=str):
        obj = registry[key]
        if isinstance(key, str):
            ns, name = "", key
        else:
            ns, name = (key[0] or ""), key[1]
        m = meta_for(kind, name, ns)
        if m is not None:
            out.append((name, m))
            continue
        doc = ""
        if inspect.isclass(obj) or inspect.isfunction(obj):
            doc = (inspect.getdoc(obj) or "").split("\n")[0]
        disp = f"{ns}:{name}" if ns else name
        out.append((disp, doc))
    return out


def generate_markdown() -> str:
    """One markdown document covering every extension point."""
    from .core.expr import SCALAR_FUNCTIONS
    from .core.io import SINK_MAPPERS, SINK_TYPES, SOURCE_MAPPERS, SOURCE_TYPES
    from .core.record_table import STORE_TYPES
    from .core.stats import REPORTERS
    from .interp.expr import PY_FUNCTIONS
    from .interp.engine import STREAM_FUNCTIONS, WINDOW_TYPES
    from .interp.aggregators import AGGREGATOR_CLASSES

    lines = ["# siddhi-tpu extension reference", "",
             "Generated from the live extension registries and built-in "
             "metadata (`python -m siddhi_tpu.docgen`).", ""]

    # windows + aggregators: built-ins and meta-registered extensions
    # render full sections; meta-less registered extensions fall back to
    # a docstring table row
    lines += ["## Windows (`#window.<name>(...)`)", ""]
    for m in all_meta("window"):
        lines += _meta_section(m)
    plain = [(n, d) for n, d in _registry_rows(WINDOW_TYPES, "window")
             if not isinstance(d, ExtensionMeta)]
    lines += _plain_table(plain)
    lines += ["## Aggregators (selector functions)", ""]
    for m in all_meta("aggregator"):
        lines += _meta_section(m)
    plain = [(n, d) for n, d in _registry_rows(AGGREGATOR_CLASSES,
                                               "aggregator")
             if not isinstance(d, ExtensionMeta)]
    lines += _plain_table(plain)

    sections = [
        ("Scalar functions (device expression compiler)", SCALAR_FUNCTIONS,
         "function"),
        ("Scalar functions (host interpreter)", PY_FUNCTIONS, "function"),
        ("Stream functions (`#<ns>:<name>(...)`)", STREAM_FUNCTIONS,
         "stream-function"),
        ("Source types (`@source(type=...)`)", SOURCE_TYPES, "source"),
        ("Sink types (`@sink(type=...)`)", SINK_TYPES, "sink"),
        ("Source mappers (`@map(type=...)`)", SOURCE_MAPPERS,
         "source-mapper"),
        ("Sink mappers (`@map(type=...)`)", SINK_MAPPERS, "sink-mapper"),
        ("Store types (`@store(type=...)`)", STORE_TYPES, "store"),
        ("Statistics reporters (`@app:statistics(reporter=...)`)",
         REPORTERS, "stats-reporter"),
    ]
    for title, registry, kind in sections:
        lines += [f"## {title}", ""]
        rows = _registry_rows(registry, kind)
        for _n, m in rows:
            if isinstance(m, ExtensionMeta):
                lines += _meta_section(m)
        lines += _plain_table(
            [(n, d) for n, d in rows if not isinstance(d, ExtensionMeta)])
    return "\n".join(lines)


def _plain_table(rows: list) -> list:
    if not rows:
        return []
    out = ["| name | description |", "|---|---|"]
    for name, doc in rows:
        out.append(f"| `{name}` | {doc.replace('|', '/')} |")
    out.append("")
    return out


def main(out: Optional[str] = None) -> None:
    md = generate_markdown()
    if out:
        with open(out, "w") as f:
            f.write(md)
        print(f"wrote {out} ({len(md.splitlines())} lines)")
    else:
        print(md)


if __name__ == "__main__":
    import sys
    main(sys.argv[1] if len(sys.argv) > 1 else None)
