"""Host (sequential) expression evaluator: AST -> Python closures.

This is the interpreter backend's analog of the reference's
ExpressionExecutor tree (reference: core:executor/ExpressionExecutor.java,
core:util/parser/ExpressionParser.java:231): one closure per AST node,
evaluated per event over a dict env.  It is:
  (a) the differential-test oracle for the TPU expression compiler,
  (b) the measured CPU baseline, and
  (c) the fallback for host-only functions (string ops, UUID, ...).

Env convention matches core.expr: keys "attr", "ref.attr", "ref[i].attr",
"__timestamp__".  Values are Python scalars; strings stay str.  Null (None)
follows Siddhi semantics: comparisons/arithmetic with null yield None
(conditions treat None as false).
"""
from __future__ import annotations

import contextvars
import math
import time
import uuid
from typing import Callable, Optional

from ..query import ast
from ..query.ast import AttrType, CompareOp, MathOp
from .. core.expr import ExprError, promote

PyFn = Callable[[dict], object]


class PyExprContext:
    """Resolution for the host evaluator — same protocol as core.expr
    contexts but string constants stay strings."""

    def __init__(self, schemas: dict, extra: Optional[dict] = None,
                 default_ref: Optional[str] = None,
                 tables: Optional[dict] = None):
        # schemas: ref -> StreamSchema; default_ref: unqualified attr home;
        # tables: id -> InMemoryTable for `in Table` membership conditions
        self.schemas = schemas
        self.extra = extra or {}
        self.default_ref = default_ref
        self.tables = tables or {}

    def resolve(self, var: ast.Variable) -> tuple[str, AttrType]:
        ref = var.stream_ref
        if ref is None:
            if var.attribute in self.extra:
                return self.extra[var.attribute]
            hits = [(r, s) for r, s in self.schemas.items() if var.attribute in s.types]
            if len(hits) > 1 and self.default_ref is not None:
                hits = [h for h in hits if h[0] == self.default_ref]
            if not hits:
                raise ExprError(f"unknown attribute {var.attribute!r}")
            if len(hits) > 1:
                raise ExprError(f"ambiguous attribute {var.attribute!r}")
            r, s = hits[0]
            key = var.attribute if len(self.schemas) == 1 or r == self.default_ref \
                else f"{r}.{var.attribute}"
            return key, s.type_of(var.attribute)
        if ref not in self.schemas:
            raise ExprError(f"unknown stream reference {ref!r}; have {list(self.schemas)}")
        s = self.schemas[ref]
        if var.index is not None:
            return f"{ref}[{var.index}].{var.attribute}", s.type_of(var.attribute)
        if ref == self.default_ref:
            # qualified self-reference (`S.x` in `from S[...]`): the single-
            # stream env carries unqualified keys
            return var.attribute, s.type_of(var.attribute)
        return f"{ref}.{var.attribute}", s.type_of(var.attribute)


# -- function registry (host) ------------------------------------------------

PY_FUNCTIONS: dict = {}


def register_py_function(name: str, builder, namespace: Optional[str] = None,
                         meta=None):
    """builder(args: list[(PyFn, AttrType)]) -> (PyFn, AttrType)"""
    from ..extension import register_meta
    register_meta("function", meta)
    PY_FUNCTIONS[(namespace, name.lower())] = builder


def _num_guard(f):
    def g(*vals):
        if any(v is None for v in vals):
            return None
        return f(*vals)
    return g


def compile_py(expr: ast.Expression, ctx: PyExprContext) -> tuple[PyFn, AttrType]:
    if isinstance(expr, ast.Constant):
        v = expr.value
        return (lambda env: v), expr.type
    if isinstance(expr, ast.TimeConstant):
        ms = expr.millis
        return (lambda env: ms), AttrType.LONG
    if isinstance(expr, ast.Variable):
        key, t = ctx.resolve(expr)
        return (lambda env: env.get(key)), t
    if isinstance(expr, ast.Compare):
        lf, lt = compile_py(expr.left, ctx)
        rf, rt = compile_py(expr.right, ctx)
        op = expr.op
        if AttrType.STRING in (lt, rt) or AttrType.BOOL in (lt, rt):
            if op == CompareOp.EQ:
                fn = lambda env: _nz(lf(env), rf(env), lambda a, b: a == b)
            elif op == CompareOp.NEQ:
                fn = lambda env: _nz(lf(env), rf(env), lambda a, b: a != b)
            elif AttrType.STRING in (lt, rt):
                cmpf = {CompareOp.LT: lambda a, b: a < b, CompareOp.LE: lambda a, b: a <= b,
                        CompareOp.GT: lambda a, b: a > b, CompareOp.GE: lambda a, b: a >= b}[op]
                fn = lambda env: _nz(lf(env), rf(env), cmpf)
            else:
                raise ExprError(f"bad comparison {lt} {op} {rt}")
            return fn, AttrType.BOOL
        cmpf = {CompareOp.LT: lambda a, b: a < b, CompareOp.LE: lambda a, b: a <= b,
                CompareOp.GT: lambda a, b: a > b, CompareOp.GE: lambda a, b: a >= b,
                CompareOp.EQ: lambda a, b: a == b, CompareOp.NEQ: lambda a, b: a != b}[expr.op]
        return (lambda env: _nz(lf(env), rf(env), cmpf)), AttrType.BOOL
    if isinstance(expr, ast.And):
        lf, _ = compile_py(expr.left, ctx)
        rf, _ = compile_py(expr.right, ctx)
        return (lambda env: bool(lf(env)) and bool(rf(env))), AttrType.BOOL
    if isinstance(expr, ast.Or):
        lf, _ = compile_py(expr.left, ctx)
        rf, _ = compile_py(expr.right, ctx)
        return (lambda env: bool(lf(env)) or bool(rf(env))), AttrType.BOOL
    if isinstance(expr, ast.Not):
        f, _ = compile_py(expr.expr, ctx)
        return (lambda env: not bool(f(env))), AttrType.BOOL
    if isinstance(expr, ast.Math):
        return _compile_math(expr, ctx)
    if isinstance(expr, ast.FunctionCall):
        return _compile_fn(expr, ctx)
    if isinstance(expr, ast.IsNull):
        if expr.expr is not None:
            f, _ = compile_py(expr.expr, ctx)
            return (lambda env: f(env) is None), AttrType.BOOL
        ref = expr.stream_ref
        key = f"{ref}.__present__" if expr.index is None \
            else f"{ref}[{expr.index}].__present__"
        return (lambda env: not env.get(key, False)), AttrType.BOOL
    if isinstance(expr, ast.In):
        from .tables import compile_in_table   # late import (cycle)
        return compile_in_table(expr, ctx)
    raise ExprError(f"cannot evaluate {type(expr).__name__}")


def _nz(a, b, f):
    if a is None or b is None:
        return False
    return f(a, b)


def _compile_math(expr: ast.Math, ctx) -> tuple[PyFn, AttrType]:
    lf, lt = compile_py(expr.left, ctx)
    rf, rt = compile_py(expr.right, ctx)
    if expr.op == MathOp.ADD and AttrType.STRING in (lt, rt):
        # Siddhi has no string +; keep numeric only
        raise ExprError("cannot add strings")
    t = promote(lt, rt)
    is_int = t in (AttrType.INT, AttrType.LONG)
    if expr.op == MathOp.ADD:
        f = _num_guard(lambda a, b: a + b)
    elif expr.op == MathOp.SUB:
        f = _num_guard(lambda a, b: a - b)
    elif expr.op == MathOp.MUL:
        f = _num_guard(lambda a, b: a * b)
    elif expr.op == MathOp.DIV:
        if is_int:
            # Java semantics: truncate toward zero
            f = _num_guard(lambda a, b: None if b == 0 else int(a / b))
        else:
            f = _num_guard(lambda a, b: None if b == 0 else a / b)
    elif expr.op == MathOp.MOD:
        if is_int:
            f = _num_guard(lambda a, b: None if b == 0 else int(math.fmod(a, b)))
        else:
            f = _num_guard(lambda a, b: None if b == 0 else math.fmod(a, b))
    else:
        raise ExprError(f"bad op {expr.op}")
    return (lambda env: f(lf(env), rf(env))), t


_CONVERT = {"string": AttrType.STRING, "int": AttrType.INT, "long": AttrType.LONG,
            "float": AttrType.FLOAT, "double": AttrType.DOUBLE, "bool": AttrType.BOOL}


def _compile_fn(expr: ast.FunctionCall, ctx) -> tuple[PyFn, AttrType]:
    name = expr.name.lower()
    ns = expr.namespace.lower() if expr.namespace else None
    if ns is None:
        if name == "ifthenelse":
            c, _ = compile_py(expr.args[0], ctx)
            a, at = compile_py(expr.args[1], ctx)
            b, bt = compile_py(expr.args[2], ctx)
            t = at if at == bt else promote(at, bt)
            return (lambda env: a(env) if c(env) else b(env)), t
        if name == "coalesce":
            fns = [compile_py(a, ctx) for a in expr.args]
            t = fns[0][1]
            def co(env):
                for f, _ in fns:
                    v = f(env)
                    if v is not None:
                        return v
                return None
            return co, t
        if name in ("convert", "cast"):
            f, ft = compile_py(expr.args[0], ctx)
            if not isinstance(expr.args[1], ast.Constant):
                raise ExprError("convert target must be literal")
            t = _CONVERT[str(expr.args[1].value).lower()]
            caster = {AttrType.STRING: _to_str, AttrType.INT: _to_int,
                      AttrType.LONG: _to_int, AttrType.FLOAT: _to_float,
                      AttrType.DOUBLE: _to_float, AttrType.BOOL: _to_bool}[t]
            return (lambda env: caster(f(env))), t
        if name == "createset":
            # reference: core:executor/function/CreateSetFunctionExecutor
            f, _ft = compile_py(expr.args[0], ctx)
            def cs(env):
                v = f(env)
                return set() if v is None else {v}
            return cs, AttrType.OBJECT
        if name == "sizeofset":
            # reference: core:executor/function/SizeOfSetFunctionExecutor
            f, _ft = compile_py(expr.args[0], ctx)
            return (lambda env: len(f(env) or ())), AttrType.INT
        if name == "uuid":
            return (lambda env: str(uuid.uuid4())), AttrType.STRING
        if name == "currenttimemillis":
            return (lambda env: int(time.time() * 1000)), AttrType.LONG
        if name == "eventtimestamp":
            return (lambda env: env.get("__timestamp__")), AttrType.LONG
        if name.startswith("instanceof"):
            kind = name[len("instanceof"):]
            f, ft = compile_py(expr.args[0], ctx)
            expected = {"integer": AttrType.INT, "long": AttrType.LONG,
                        "float": AttrType.FLOAT, "double": AttrType.DOUBLE,
                        "boolean": AttrType.BOOL, "string": AttrType.STRING}.get(kind)
            ok = ft == expected
            return (lambda env: ok), AttrType.BOOL
        if name == "maximum":
            fns = [compile_py(a, ctx) for a in expr.args]
            t = fns[0][1]
            for _, ft in fns[1:]:
                t = promote(t, ft)
            return (lambda env: max(v for v in (f(env) for f, _ in fns) if v is not None)), t
        if name == "minimum":
            fns = [compile_py(a, ctx) for a in expr.args]
            t = fns[0][1]
            for _, ft in fns[1:]:
                t = promote(t, ft)
            return (lambda env: min(v for v in (f(env) for f, _ in fns) if v is not None)), t
        if name == "default":
            f, ft = compile_py(expr.args[0], ctx)
            d, _ = compile_py(expr.args[1], ctx)
            return (lambda env: f(env) if f(env) is not None else d(env)), ft
    udfs = _ACTIVE_UDFS.get() if ns is None else None
    if udfs and name in udfs:
        fn, rtype = udfs[name]
        args = [compile_py(a, ctx) for a in expr.args]
        caster = {AttrType.STRING: _to_str, AttrType.INT: _to_int,
                  AttrType.LONG: _to_int, AttrType.FLOAT: _to_float,
                  AttrType.DOUBLE: _to_float, AttrType.BOOL: _to_bool,
                  AttrType.OBJECT: lambda v: v}[rtype]

        def call(env, _fn=fn, _args=args, _cast=caster):
            return _cast(_fn(tuple(a(env) for a, _t in _args)))
        return call, rtype
    builder = PY_FUNCTIONS.get((ns, name))
    if builder is None:
        raise ExprError(f"unknown function {(ns + ':') if ns else ''}{name}()")
    args = [compile_py(a, ctx) for a in expr.args]
    return builder(args)


# ---------------------------------------------------------------------------
# script UDFs (`define function f[python] return type { body }`)
# ---------------------------------------------------------------------------
# Reference: core:function/Script.java:27 + ScriptExtensionHolder — scripts
# are app-scoped functions receiving the argument array.  Here only
# language `python` executes (body sees the args as `data`, either as a
# bare expression or statements with `return`); other languages raise at
# build time — a silently dropped definition was VERDICT r3 weak spot #5.

_ACTIVE_UDFS: "contextvars.ContextVar[dict]" = contextvars.ContextVar(
    "siddhi_active_udfs", default={})   # name -> (fn, AttrType); build-scoped


class udf_scope:
    """Installs a runtime's script functions for the duration of plan /
    store-query compilation (closures capture the fns, so the scope only
    needs to span compile time).  ContextVar-backed so lazy partition-clone
    compiles on async ingest workers can't clobber a concurrent build in
    another thread (advisor r4)."""

    def __init__(self, udfs: Optional[dict]):
        self.udfs = udfs or {}

    def __enter__(self):
        self._token = _ACTIVE_UDFS.set(self.udfs)
        return self

    def __exit__(self, *exc):
        _ACTIVE_UDFS.reset(self._token)
        return False


def compile_script_function(fd) -> Callable:
    """FunctionDefinition -> python callable(data_tuple) -> value."""
    if fd.language.lower() not in ("python", "py"):
        raise ExprError(
            f"script function {fd.id!r}: language {fd.language!r} is not "
            f"executable here (only [python] scripts run; the reference's "
            f"[javascript]/[scala] engines have no analog in this runtime)")
    import textwrap
    src = textwrap.dedent(fd.body.replace("\t", "    ")).strip()
    if "\n" in src:     # re-dedent the continuation lines against line 1
        first, rest = src.split("\n", 1)
        src = first + "\n" + textwrap.dedent(rest)
    try:
        code = compile(src, f"<function {fd.id}>", "eval")

        def fn(data, _code=code):
            return eval(_code, {"data": data, "math": math})  # noqa: S307
        return fn
    except SyntaxError:
        pass
    indented = "\n".join("    " + ln for ln in src.splitlines())
    ns: dict = {"math": math}
    try:
        exec(compile(f"def __udf__(data):\n{indented}",
                     f"<function {fd.id}>", "exec"), ns)
    except SyntaxError as e:
        raise ExprError(f"script function {fd.id!r}: body does not compile "
                        f"as a python expression or function body: {e}")
    return ns["__udf__"]


def _to_str(v):
    return None if v is None else str(v)


def _to_int(v):
    if v is None:
        return None
    try:
        return int(float(v)) if isinstance(v, str) else int(v)
    except ValueError:
        return None


def _to_float(v):
    if v is None:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def _to_bool(v):
    if v is None:
        return None
    if isinstance(v, str):
        return v.lower() == "true"
    return bool(v)


# -- built-in host function library (str:*, math:*) --------------------------

def _str_fn(pyf, out=AttrType.STRING):
    def build(args):
        fns = [f for f, _ in args]
        def fn(env):
            vals = [f(env) for f in fns]
            if any(v is None for v in vals):
                return None
            return pyf(*vals)
        return fn, out
    return build


register_py_function("concat", _str_fn(lambda *a: "".join(str(x) for x in a)), "str")
register_py_function("length", _str_fn(len, AttrType.INT), "str")
register_py_function("upper", _str_fn(str.upper), "str")
register_py_function("lower", _str_fn(str.lower), "str")
register_py_function("contains", _str_fn(lambda a, b: b in a, AttrType.BOOL), "str")
register_py_function("startsWith", _str_fn(str.startswith, AttrType.BOOL), "str")
register_py_function("endsWith", _str_fn(str.endswith, AttrType.BOOL), "str")
register_py_function("trim", _str_fn(str.strip), "str")
register_py_function("replaceAll", _str_fn(lambda s, a, b: s.replace(a, b)), "str")
register_py_function("substr", _str_fn(lambda s, a, b=None: s[int(a):] if b is None
                                       else s[int(a):int(a) + int(b)]), "str")

for _name, _f, _t in [
    ("abs", abs, None), ("sqrt", math.sqrt, AttrType.DOUBLE),
    ("log", math.log, AttrType.DOUBLE), ("exp", math.exp, AttrType.DOUBLE),
    ("floor", math.floor, AttrType.DOUBLE), ("ceil", math.ceil, AttrType.DOUBLE),
    ("sin", math.sin, AttrType.DOUBLE), ("cos", math.cos, AttrType.DOUBLE),
    ("round", round, None), ("power", pow, None),
]:
    def _mk(f=_f, t=_t):
        def build(args):
            fns = [fn for fn, _ in args]
            ot = t or (args[0][1] if args else AttrType.DOUBLE)
            def fn(env):
                vals = [g(env) for g in fns]
                if any(v is None for v in vals):
                    return None
                return f(*vals)
            return fn, ot
        return build
    register_py_function(_name, _mk(), "math")
