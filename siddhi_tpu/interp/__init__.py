"""Sequential host backend: reference semantics, differential oracle,
CPU baseline, and fallback executor."""
