"""Host window processors — sequential reference semantics.

One class per in-core window of the reference
(reference: core:query/processor/stream/window/*.java, 15 impls; the
current/expired/reset event protocol is documented in the reference's
docs/documentation/siddhi-architecture.md:243-268).

Protocol here: `process(ev, now_ms) -> list[(kind, ev)]` returns the emitted
chunk in reference order (EXPIRED entries precede the CURRENT event that
displaced them; RESET clears aggregators); `on_timer(now_ms)` emits
time-driven expirations; `next_wakeup()` tells the scheduler when to call
back.  Events are runtime.Event objects (timestamp + data tuple).
"""
from __future__ import annotations

import bisect
from collections import deque
from typing import Optional

from ..core.runtime import Event

CURRENT = "current"
EXPIRED = "expired"
RESET = "reset"


class Window:
    needs_timer = False

    def process(self, ev: Event, now_ms: int) -> list:
        raise NotImplementedError

    def on_timer(self, now_ms: int) -> list:
        return []

    def next_wakeup(self) -> Optional[int]:
        return None

    # events currently held (for joins `find` and named-window queries)
    def contents(self) -> list:
        return []

    def state(self) -> dict:
        return {}

    def restore(self, st: dict) -> None:
        pass


class LengthWindow(Window):
    """Sliding last-N (reference: LengthWindowProcessor.java — expired
    event is inserted before the displacing current event)."""

    def __init__(self, length: int):
        self.length = length
        self.buf: deque = deque()

    def process(self, ev, now_ms):
        out = []
        if self.length == 0:
            # zero-length: event expires immediately
            return [(CURRENT, ev), (EXPIRED, Event(now_ms, ev.data, ev.uid)), (RESET, ev)]
        if len(self.buf) >= self.length:
            old = self.buf.popleft()
            out.append((EXPIRED, Event(now_ms, old.data, old.uid)))
        out.append((CURRENT, ev))
        self.buf.append(ev)
        return out

    def contents(self):
        return list(self.buf)

    def state(self):
        return {"buf": [(e.timestamp, e.data) for e in self.buf]}

    def restore(self, st):
        self.buf = deque(Event(t, d) for t, d in st["buf"])


class LengthBatchWindow(Window):
    """Tumbling N (reference: LengthBatchWindowProcessor.java): emits the
    batch of N currents, the previous batch as expired, then RESET."""

    def __init__(self, length: int):
        self.length = length
        self.cur: list = []
        self.prev: list = []

    def process(self, ev, now_ms):
        self.cur.append(ev)
        if len(self.cur) < self.length:
            return []
        out = []
        for old in self.prev:
            out.append((EXPIRED, Event(now_ms, old.data, old.uid)))
        if out:
            out.append((RESET, ev))
        for e in self.cur:
            out.append((CURRENT, e))
        self.prev = self.cur
        self.cur = []
        return out

    def contents(self):
        return list(self.cur)

    def state(self):
        return {"cur": [(e.timestamp, e.data) for e in self.cur],
                "prev": [(e.timestamp, e.data) for e in self.prev]}

    def restore(self, st):
        self.cur = [Event(t, d) for t, d in st["cur"]]
        self.prev = [Event(t, d) for t, d in st["prev"]]


class TimeWindow(Window):
    """Sliding time window (reference: TimeWindowProcessor.java):
    every event expires `duration` ms after arrival, via scheduler."""
    needs_timer = True

    def __init__(self, duration_ms: int):
        self.duration = duration_ms
        self.buf: deque = deque()     # events in arrival order

    def process(self, ev, now_ms):
        out = self._expire(now_ms)
        out.append((CURRENT, ev))
        self.buf.append(ev)
        return out

    def _expire(self, now_ms):
        out = []
        while self.buf and self.buf[0].timestamp + self.duration <= now_ms:
            old = self.buf.popleft()
            out.append((EXPIRED, Event(old.timestamp + self.duration, old.data, old.uid)))
        return out

    def on_timer(self, now_ms):
        return self._expire(now_ms)

    def next_wakeup(self):
        if self.buf:
            return self.buf[0].timestamp + self.duration
        return None

    def contents(self):
        return list(self.buf)

    def state(self):
        return {"buf": [(e.timestamp, e.data) for e in self.buf]}

    def restore(self, st):
        self.buf = deque(Event(t, d) for t, d in st["buf"])


class TimeBatchWindow(Window):
    """Tumbling time window (reference: TimeBatchWindowProcessor.java):
    collects for `duration`, then emits currents + previous as expired."""
    needs_timer = True

    def __init__(self, duration_ms: int, start_time: Optional[int] = None):
        self.duration = duration_ms
        self.start: Optional[int] = start_time
        self.cur: list = []
        self.prev: list = []

    def process(self, ev, now_ms):
        if self.start is None:
            self.start = ev.timestamp
        out = self._maybe_flush(now_ms)
        self.cur.append(ev)
        return out

    def _maybe_flush(self, now_ms):
        out = []
        while self.start is not None and now_ms >= self.start + self.duration:
            end = self.start + self.duration
            for old in self.prev:
                out.append((EXPIRED, Event(end, old.data, old.uid)))
            if self.prev:
                out.append((RESET, None))
            for e in self.cur:
                out.append((CURRENT, e))
            self.prev = self.cur
            self.cur = []
            self.start = end
            if not self.cur and not self.prev and now_ms < end + self.duration:
                break
        return out

    def on_timer(self, now_ms):
        return self._maybe_flush(now_ms)

    def next_wakeup(self):
        if self.start is not None and (self.cur or self.prev):
            return self.start + self.duration
        return None

    def contents(self):
        return list(self.cur)

    def state(self):
        return {"cur": [(e.timestamp, e.data) for e in self.cur],
                "prev": [(e.timestamp, e.data) for e in self.prev],
                "start": self.start}

    def restore(self, st):
        self.cur = [Event(t, d) for t, d in st["cur"]]
        self.prev = [Event(t, d) for t, d in st["prev"]]
        self.start = st["start"]


class ExternalTimeWindow(Window):
    """Sliding window over an event-time attribute (reference:
    ExternalTimeWindowProcessor.java) — no scheduler; expiry driven by the
    timestamps arriving on the stream itself."""

    def __init__(self, ts_getter, duration_ms: int):
        self.get_ts = ts_getter        # ev -> event-time long
        self.duration = duration_ms
        self.buf: deque = deque()

    def process(self, ev, now_ms):
        t = self.get_ts(ev)
        out = []
        while self.buf and self.get_ts(self.buf[0]) + self.duration <= t:
            old = self.buf.popleft()
            out.append((EXPIRED, Event(self.get_ts(old) + self.duration, old.data, old.uid)))
        out.append((CURRENT, ev))
        self.buf.append(ev)
        return out

    def contents(self):
        return list(self.buf)

    def state(self):
        return {"buf": [(e.timestamp, e.data) for e in self.buf]}

    def restore(self, st):
        self.buf = deque(Event(t, d) for t, d in st["buf"])


class ExternalTimeBatchWindow(Window):
    """Tumbling over an event-time attribute (reference:
    ExternalTimeBatchWindowProcessor.java, simplified: bucket boundaries at
    start + k*duration, flush when an event crosses the boundary)."""

    def __init__(self, ts_getter, duration_ms: int, start_time: Optional[int] = None):
        self.get_ts = ts_getter
        self.duration = duration_ms
        self.start = start_time
        self.cur: list = []
        self.prev: list = []

    def process(self, ev, now_ms):
        t = self.get_ts(ev)
        out = []
        if self.start is None:
            self.start = t if self.start is None else self.start
        while t >= self.start + self.duration:
            end = self.start + self.duration
            if self.cur or self.prev:
                for old in self.prev:
                    out.append((EXPIRED, Event(end, old.data, old.uid)))
                if self.prev:
                    out.append((RESET, None))
                for e in self.cur:
                    out.append((CURRENT, e))
                self.prev = self.cur
                self.cur = []
            self.start = end
        self.cur.append(ev)
        return out

    def contents(self):
        return list(self.cur)

    def state(self):
        return {"cur": [(e.timestamp, e.data) for e in self.cur],
                "prev": [(e.timestamp, e.data) for e in self.prev],
                "start": self.start}

    def restore(self, st):
        self.cur = [Event(t, d) for t, d in st["cur"]]
        self.prev = [Event(t, d) for t, d in st["prev"]]
        self.start = st["start"]


class TimeLengthWindow(Window):
    """Sliding window bounded by both time and count (reference:
    TimeLengthWindowProcessor.java)."""
    needs_timer = True

    def __init__(self, duration_ms: int, length: int):
        self.duration = duration_ms
        self.length = length
        self.buf: deque = deque()

    def process(self, ev, now_ms):
        out = self._expire(now_ms)
        if len(self.buf) >= self.length:
            old = self.buf.popleft()
            out.append((EXPIRED, Event(now_ms, old.data, old.uid)))
        out.append((CURRENT, ev))
        self.buf.append(ev)
        return out

    def _expire(self, now_ms):
        out = []
        while self.buf and self.buf[0].timestamp + self.duration <= now_ms:
            old = self.buf.popleft()
            out.append((EXPIRED, Event(old.timestamp + self.duration, old.data, old.uid)))
        return out

    def on_timer(self, now_ms):
        return self._expire(now_ms)

    def next_wakeup(self):
        return self.buf[0].timestamp + self.duration if self.buf else None

    def contents(self):
        return list(self.buf)

    def state(self):
        return {"buf": [(e.timestamp, e.data) for e in self.buf]}

    def restore(self, st):
        self.buf = deque(Event(t, d) for t, d in st["buf"])


class BatchWindow(Window):
    """Chunk-batch window (reference: BatchWindowProcessor.java): each
    incoming micro-chunk is the batch; previous chunk expires."""

    def __init__(self):
        self.prev: list = []
        self._chunk: list = []

    # engine feeds events one at a time but marks chunk boundaries
    def process(self, ev, now_ms):
        self._chunk.append(ev)
        return []

    def end_chunk(self, now_ms) -> list:
        if not self._chunk:
            return []
        out = []
        for old in self.prev:
            out.append((EXPIRED, Event(now_ms, old.data, old.uid)))
        if self.prev:
            out.append((RESET, None))
        for e in self._chunk:
            out.append((CURRENT, e))
        self.prev = self._chunk
        self._chunk = []
        return out

    def contents(self):
        return list(self.prev)

    def state(self):
        return {"prev": [(e.timestamp, e.data) for e in self.prev]}

    def restore(self, st):
        self.prev = [Event(t, d) for t, d in st["prev"]]


class SessionWindow(Window):
    """Session window with gap (+ optional allowed latency), per session key
    (reference: SessionWindowProcessor.java:577 LoC; simplified — sessions
    close `gap` ms after the last event; closed sessions emit their events
    as EXPIRED batch)."""
    needs_timer = True

    def __init__(self, gap_ms: int, key_getter=None, allowed_latency_ms: int = 0):
        self.gap = gap_ms
        self.key = key_getter or (lambda ev: "")
        self.latency = allowed_latency_ms
        self.sessions: dict = {}      # key -> [events]
        self.last_ts: dict = {}

    def process(self, ev, now_ms):
        out = self._close(now_ms)
        k = self.key(ev)
        self.sessions.setdefault(k, []).append(ev)
        self.last_ts[k] = ev.timestamp
        out.append((CURRENT, ev))
        return out

    def _close(self, now_ms):
        out = []
        for k in list(self.sessions):
            if self.last_ts[k] + self.gap + self.latency <= now_ms:
                for e in self.sessions[k]:
                    out.append((EXPIRED, Event(now_ms, e.data, e.uid)))
                out.append((RESET, None))
                del self.sessions[k]
                del self.last_ts[k]
        return out

    def on_timer(self, now_ms):
        return self._close(now_ms)

    def next_wakeup(self):
        if not self.last_ts:
            return None
        return min(self.last_ts.values()) + self.gap + self.latency

    def contents(self):
        return [e for evs in self.sessions.values() for e in evs]

    def state(self):
        return {"sessions": {k: [(e.timestamp, e.data) for e in v]
                             for k, v in self.sessions.items()},
                "last": dict(self.last_ts)}

    def restore(self, st):
        self.sessions = {k: [Event(t, d) for t, d in v]
                         for k, v in st["sessions"].items()}
        self.last_ts = dict(st["last"])


class SortWindow(Window):
    """Keeps the top/bottom N by sort key (reference: SortWindowProcessor.java):
    when over capacity, evicts the greatest (asc) / least (desc) element."""

    def __init__(self, length: int, key_getter, descending: bool = False):
        self.length = length
        self.key = key_getter
        self.desc = descending
        self.keys: list = []
        self.evs: list = []

    def process(self, ev, now_ms):
        k = self.key(ev)
        if self.desc:
            k = _Neg(k)
        i = bisect.bisect_right(self.keys, k)
        self.keys.insert(i, k)
        self.evs.insert(i, ev)
        out = [(CURRENT, ev)]
        if len(self.evs) > self.length:
            evicted = self.evs.pop()
            self.keys.pop()
            out.append((EXPIRED, Event(now_ms, evicted.data, evicted.uid)))
        return out

    def contents(self):
        return list(self.evs)

    def state(self):
        return {"evs": [(e.timestamp, e.data) for e in self.evs]}

    def restore(self, st):
        self.evs = [Event(t, d) for t, d in st["evs"]]
        self.keys = [(_Neg(self.key(e)) if self.desc else self.key(e)) for e in self.evs]


class _Neg:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, o):
        return o.v < self.v

    def __le__(self, o):
        return o.v <= self.v

    def __eq__(self, o):
        return o.v == self.v


class DelayWindow(Window):
    """Delays events by T (reference: DelayWindowProcessor.java): events
    emerge as CURRENT only after T ms."""
    needs_timer = True

    def __init__(self, duration_ms: int):
        self.duration = duration_ms
        self.buf: deque = deque()

    def process(self, ev, now_ms):
        self.buf.append(ev)
        return self._release(now_ms)

    def _release(self, now_ms):
        out = []
        while self.buf and self.buf[0].timestamp + self.duration <= now_ms:
            old = self.buf.popleft()
            out.append((CURRENT, Event(old.timestamp, old.data, old.uid)))
        return out

    def on_timer(self, now_ms):
        return self._release(now_ms)

    def next_wakeup(self):
        return self.buf[0].timestamp + self.duration if self.buf else None

    def contents(self):
        return list(self.buf)

    def state(self):
        return {"buf": [(e.timestamp, e.data) for e in self.buf]}

    def restore(self, st):
        self.buf = deque(Event(t, d) for t, d in st["buf"])


class FrequentWindow(Window):
    """Misra-Gries frequent-items window (reference:
    FrequentWindowProcessor.java): keeps events whose key is among the
    top-N candidates; evicted keys' events expire."""

    def __init__(self, count: int, key_getter=None):
        self.count = count
        self.key = key_getter or (lambda ev: ev.data)
        self.counts: dict = {}
        self.events: dict = {}      # key -> latest event

    def process(self, ev, now_ms):
        k = self.key(ev)
        out = []
        if k in self.counts:
            self.counts[k] += 1
            out.append((EXPIRED, Event(now_ms, self.events[k].data, self.events[k].uid)))
            self.events[k] = ev
            out.append((CURRENT, ev))
        elif len(self.counts) < self.count:
            self.counts[k] = 1
            self.events[k] = ev
            out.append((CURRENT, ev))
        else:
            # decrement all; drop zeros (their events expire)
            for kk in list(self.counts):
                self.counts[kk] -= 1
                if self.counts[kk] == 0:
                    out.append((EXPIRED, Event(now_ms, self.events[kk].data, self.events[kk].uid)))
                    del self.counts[kk]
                    del self.events[kk]
        return out

    def contents(self):
        return list(self.events.values())

    def state(self):
        return {"counts": dict(self.counts),
                "events": {k: (e.timestamp, e.data) for k, e in self.events.items()}}

    def restore(self, st):
        self.counts = dict(st["counts"])
        self.events = {k: Event(t, d) for k, (t, d) in st["events"].items()}


class LossyFrequentWindow(Window):
    """Lossy-counting frequent window (reference:
    LossyFrequentWindowProcessor.java)."""

    def __init__(self, support: float, error: Optional[float] = None, key_getter=None):
        self.support = support
        self.error = error if error is not None else support / 10.0
        self.key = key_getter or (lambda ev: ev.data)
        self.width = int(1.0 / self.error)
        self.total = 0
        self.counts: dict = {}     # key -> [count, bucket_delta]
        self.events: dict = {}

    def process(self, ev, now_ms):
        k = self.key(ev)
        self.total += 1
        bucket = (self.total // self.width) + 1
        out = []
        if k in self.counts:
            self.counts[k][0] += 1
            out.append((EXPIRED, Event(now_ms, self.events[k].data, self.events[k].uid)))
        else:
            self.counts[k] = [1, bucket - 1]
        self.events[k] = ev
        out.append((CURRENT, ev))
        if self.total % self.width == 0:
            for kk in list(self.counts):
                c, d = self.counts[kk]
                if c + d <= bucket:
                    out.append((EXPIRED, Event(now_ms, self.events[kk].data, self.events[kk].uid)))
                    del self.counts[kk]
                    del self.events[kk]
        return out

    def contents(self):
        thresh = (self.support - self.error) * self.total
        return [self.events[k] for k, (c, d) in self.counts.items() if c >= thresh]

    def state(self):
        return {"counts": {k: list(v) for k, v in self.counts.items()},
                "events": {k: (e.timestamp, e.data) for k, e in self.events.items()},
                "total": self.total}

    def restore(self, st):
        self.counts = {k: list(v) for k, v in st["counts"].items()}
        self.events = {k: Event(t, d) for k, (t, d) in st["events"].items()}
        self.total = st["total"]


class CronWindow(Window):
    """Cron-scheduled tumbling window (reference: CronWindowProcessor.java).
    Uses a simplified cron evaluator (utils.cron)."""
    needs_timer = True

    def __init__(self, cron_expr: str):
        from ..utils.cron import CronSchedule
        self.cron = CronSchedule(cron_expr)
        self.cur: list = []
        self.prev: list = []
        self._next: Optional[int] = None

    def process(self, ev, now_ms):
        if self._next is None:
            self._next = self.cron.next_fire(now_ms)
        self.cur.append(ev)
        return []

    def on_timer(self, now_ms):
        if self._next is None or now_ms < self._next:
            return []
        out = []
        for old in self.prev:
            out.append((EXPIRED, Event(now_ms, old.data, old.uid)))
        if self.prev:
            out.append((RESET, None))
        for e in self.cur:
            out.append((CURRENT, e))
        self.prev = self.cur
        self.cur = []
        self._next = self.cron.next_fire(now_ms)
        return out

    def next_wakeup(self):
        return self._next

    def contents(self):
        return list(self.cur)

    def state(self):
        return {"cur": [(e.timestamp, e.data) for e in self.cur],
                "prev": [(e.timestamp, e.data) for e in self.prev],
                "next": self._next}

    def restore(self, st):
        self.cur = [Event(t, d) for t, d in st["cur"]]
        self.prev = [Event(t, d) for t, d in st["prev"]]
        self._next = st["next"]
