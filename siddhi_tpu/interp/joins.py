"""Stream-stream window joins — sequential backend.

Reference semantics (core:query/input/stream/join/JoinProcessor.java:62-126,
built by core:util/parser/JoinInputStreamParser.java): each side owns a
window; an arriving event (after its side's filters) probes the OPPOSITE
side's current window content with the compiled `on` condition and emits
one joined event per match.  Left/right/full outer joins emit the arriving
event with nulls for the other side when nothing matches; `unidirectional`
restricts which side's arrivals trigger output.

Implementation detail: instead of reaching into each window's internals,
every side keeps a `retained` list driven by the window's own
current/expired/reset emission protocol — so ALL window types compose with
joins for free.  The arriving event probes the opposite side BEFORE being
retained on its own side (self-joins don't match an event with itself).
"""
from __future__ import annotations

from typing import Optional

from ..query import ast
from ..core.batch import BatchBuilder, EventBatch
from ..core.planner import OutputBatch, PlanError, QueryPlan
from ..core.runtime import Event
from .expr import PyExprContext, compile_py
from . import windows as W

CURRENT, EXPIRED, RESET = W.CURRENT, W.EXPIRED, W.RESET


class JoinSide:
    def __init__(self, inp: ast.SingleInputStream, rt):
        from .engine import make_window
        if inp.stream_id not in rt.schemas:
            raise PlanError(f"join: unknown stream {inp.stream_id!r}")
        self.ref = inp.alias
        self.stream_id = inp.stream_id
        self.schema = rt.schemas[inp.stream_id]
        # named-window side: probe the shared window's live contents (the
        # find facade, reference: WindowWindowProcessor) instead of keeping
        # a retained copy; its current-event republications still trigger
        self.named_window = rt.named_windows.get(inp.stream_id)
        ctx = PyExprContext({inp.alias: self.schema,
                             inp.stream_id: self.schema},
                            default_ref=inp.alias, tables=rt.tables)
        self.filters = [compile_py(f.expr, ctx)[0] for f in inp.filters]
        for h in inp.handlers:
            if isinstance(h, ast.StreamFunction):
                raise PlanError("join: stream functions on join sides "
                                "not supported")
        self.window: Optional[W.Window] = None
        if inp.window is not None:
            if self.named_window is not None:
                raise PlanError(f"join: cannot apply a window to named "
                                f"window {inp.stream_id!r}")
            self.window = make_window(inp.window, ctx, self.schema)
        self.retained: list[Event] = []

    def probe_events(self) -> list:
        if self.named_window is not None:
            evs = self.named_window.contents()
            if self.filters:
                return [e for e in evs if self.passes(self.env_of(e))]
            return evs
        return self.retained

    def passes(self, env: dict) -> bool:
        return all(f(env) for f in self.filters)

    def env_of(self, ev: Event) -> dict:
        env = {f"{self.ref}.{n}": v for n, v in zip(self.schema.names, ev.data)}
        for n, v in zip(self.schema.names, ev.data):
            env[n] = v
        env["__timestamp__"] = ev.timestamp
        return env

    def apply_emissions(self, emissions: list) -> None:
        for kind, ev in emissions:
            if kind == CURRENT:
                self.retained.append(ev)
            elif kind == EXPIRED:
                # windows re-stamp expired events with their expiry time but
                # preserve uid — remove the exact retained instance; data-FIFO
                # fallback covers uid-less events (post-restore window state)
                hit = None
                if ev.uid:
                    for i, r in enumerate(self.retained):
                        if r.uid == ev.uid:
                            hit = i
                            break
                if hit is None:
                    for i, r in enumerate(self.retained):
                        if r.data == ev.data:
                            hit = i
                            break
                if hit is not None:
                    del self.retained[hit]
            elif kind == RESET:
                self.retained.clear()

    def retain(self, ev: Event, now_ms: int) -> None:
        if self.window is None:
            return                    # windowless side keeps nothing
        self.apply_emissions(self.window.process(ev, now_ms))

    def on_timer(self, now_ms: int) -> None:
        if self.window is not None:
            self.apply_emissions(self.window.on_timer(now_ms))

    def next_wakeup(self):
        return self.window.next_wakeup() if self.window is not None else None

    def state(self) -> dict:
        return {"window": self.window.state() if self.window else None,
                "retained": [(e.timestamp, e.data) for e in self.retained]}

    def restore(self, st: dict) -> None:
        if self.window is not None and st.get("window") is not None:
            self.window.restore(st["window"])
        # uid intentionally dropped: restored window state emits uid-less
        # expirations, so removal falls back to data matching either way
        self.retained = [Event(t, tuple(d)) for t, d in st["retained"]]


class TableJoinSide:
    """A table participating in a join (reference: TableWindowProcessor
    adapter inside JoinInputStreamParser — the stream side probes the
    table's compiled condition via `find`; the table never triggers)."""

    is_table = True

    def __init__(self, inp: ast.SingleInputStream, rt, table):
        if inp.window is not None or inp.filters or inp.handlers:
            raise PlanError(f"join: table {inp.stream_id!r} side cannot have "
                            f"windows/filters")
        self.ref = inp.alias
        self.stream_id = inp.stream_id
        self.table = table
        self.schema = table.schema

    def on_timer(self, now_ms: int) -> None:
        pass

    def next_wakeup(self):
        return None

    def state(self) -> dict:
        return {}          # table contents snapshot with rt.tables

    def restore(self, st: dict) -> None:
        pass


class AggregationJoinSide:
    """An incremental aggregation in a join: `from S join A on ...
    within t1, t2 per 'seconds'` (reference: AggregateWindowProcessor +
    IncrementalAggregateCompileCondition.java:277).  The stream side's
    arrivals select bucket rows at `per` granularity inside `within`."""

    is_table = True        # never triggers; no retained state

    def __init__(self, inp: ast.SingleInputStream, rt, agg):
        if inp.window is not None or inp.filters or inp.handlers:
            raise PlanError(f"join: aggregation {inp.stream_id!r} side "
                            f"cannot have windows/filters")
        self.ref = inp.alias
        self.stream_id = inp.stream_id
        self.agg = agg
        self.schema = agg.out_schema

    def on_timer(self, now_ms: int) -> None:
        pass

    def next_wakeup(self):
        return None

    def state(self) -> dict:
        return {}

    def restore(self, st: dict) -> None:
        pass


class InterpJoinQueryPlan(QueryPlan):
    """`from A#win as a join B#win as b on a.x == b.y select ...`
    Either side may be a table (probed via its index-aware compiled
    condition) or an incremental aggregation (within/per bucket rows)."""

    def __init__(self, name: str, rt, q: ast.Query,
                 inp: ast.JoinInputStream, target: Optional[str]):
        from .engine import InterpSelector, make_rate_limiter
        from ..core.table import compile_table_condition
        self.name = name
        self.rt = rt
        self.output_target = target
        self.events_for = getattr(q.output, "events_for",
                                  ast.OutputEventsFor.CURRENT)

        def side_of(sinp):
            if sinp.stream_id in rt.tables:
                return TableJoinSide(sinp, rt, rt.tables[sinp.stream_id])
            if sinp.stream_id in rt.aggregations:
                return AggregationJoinSide(sinp, rt,
                                           rt.aggregations[sinp.stream_id])
            return JoinSide(sinp, rt)

        self.left = side_of(inp.left)
        self.right = side_of(inp.right)
        if self.left.ref == self.right.ref:
            raise PlanError(f"join {name!r}: both sides named "
                            f"{self.left.ref!r}; alias one with `as`")
        left_t = isinstance(self.left, (TableJoinSide, AggregationJoinSide))
        right_t = isinstance(self.right, (TableJoinSide, AggregationJoinSide))
        if left_t and right_t:
            raise PlanError(f"join {name!r}: cannot join two stores in a "
                            f"streaming query; use a store query")
        self.join_type = inp.join_type
        self.trigger = inp.trigger       # "all" | "left" | "right"
        # a table/aggregation never triggers output (reference: implicitly
        # unidirectional from the stream side)
        if left_t:
            self.trigger = "right"
        elif right_t:
            self.trigger = "left"
        schemas = {self.left.ref: self.left.schema,
                   self.right.ref: self.right.schema}
        ctx = PyExprContext(schemas, tables=rt.tables)
        self.on = compile_py(inp.on, ctx)[0] if inp.on is not None else None
        # index-aware probe plan for the table side (reference:
        # CollectionExpressionParser compiled condition)
        self.table_cond = None
        self.agg_per = None
        self.agg_within = None
        store_side = self.left if left_t else self.right if right_t else None
        if isinstance(store_side, TableJoinSide):
            sside = self.right if left_t else self.left
            sctx = PyExprContext({sside.ref: sside.schema,
                                  sside.stream_id: sside.schema},
                                 default_ref=sside.ref, tables=rt.tables)
            self.table_cond = compile_table_condition(
                inp.on, store_side.table, (store_side.ref, store_side.stream_id),
                sctx)
        if isinstance(store_side, AggregationJoinSide):
            from ..core.aggregation import per_duration_of, within_range_of
            if inp.per is None:
                raise PlanError(f"join {name!r}: aggregation join needs "
                                f"`per '<duration>'`")
            self.agg_per = per_duration_of(inp.per)
            sside = self.right if left_t else self.left
            sctx = PyExprContext({sside.ref: sside.schema,
                                  sside.stream_id: sside.schema},
                                 default_ref=sside.ref, tables=rt.tables)
            self.agg_within = within_range_of(
                inp.within, lambda e: compile_py(e, sctx)[0],
                lambda: rt.now_ms())
        elif inp.per is not None or inp.within is not None:
            raise PlanError(f"query {name!r}: within/per only apply to "
                            f"aggregation joins")
        self.sel = InterpSelector(_join_selector(q.selector, self), ctx,
                                  None, target or f"#{name}")
        self.out_schema = self.sel.out_schema
        self.rate = make_rate_limiter(q.rate, q.selector)
        self.input_streams = tuple(
            {s.stream_id for s in (self.left, self.right)
             if not getattr(s, "is_table", False)})
        self._buffer: list = []          # (seq, stream_id, Event)

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        rows = batch.rows(self.rt.strings)
        seqs = batch.seqs if batch.seqs is not None else range(batch.n)
        for seq, ts, row in zip(seqs, batch.timestamps, rows):
            # global arrival seq doubles as instance uid (nonzero)
            self._buffer.append((int(seq), stream_id,
                                 Event(int(ts), row, uid=int(seq) + 1)))
        return []

    def finalize(self) -> list:
        if not self._buffer:
            return []
        buf = sorted(self._buffer, key=lambda t: t[0])
        self._buffer = []
        out_rows: list = []
        for _seq, sid, ev in buf:
            now = ev.timestamp if self.rt._playback else self.rt.now_ms()
            # self-join: one arrival drives both sides — all probes run
            # before either side retains, so an event never joins itself
            arrivals = []
            if sid == self.left.stream_id:
                arrivals.append((self.left, self.right, "left"))
            if sid == self.right.stream_id:
                arrivals.append((self.right, self.left, "right"))
            passed = []
            for side, other, side_name in arrivals:
                if side.passes(side.env_of(ev)):
                    passed.append((side, other, side_name))
                    out_rows.extend(self._probe(side, other, side_name, ev))
            for side, _other, _sn in passed:
                side.retain(ev, now)
        out_rows = self._post(out_rows)
        return self._to_batches(out_rows)

    def _probe(self, side: JoinSide, other, side_name: str,
               ev: Event) -> list:
        if self.trigger not in ("all", side_name):
            return []
        rows = []
        base = {f"{side.ref}.{n}": v
                for n, v in zip(side.schema.names, ev.data)}
        base["__timestamp__"] = ev.timestamp
        matched = False
        if isinstance(other, TableJoinSide):
            # index-aware seek: `on` is already folded into table_cond
            idx = self.table_cond.find(side.env_of(ev))
            for i in idx:
                env = dict(base)
                env.update(other.table.row_env(int(i), (other.ref,)))
                matched = True
                row = self.sel.process(CURRENT, env)
                if row is not None:
                    rows.append((CURRENT, ev.timestamp, row))
            return rows + self._outer_miss(side, other, side_name, base, matched)
        if isinstance(other, AggregationJoinSide):
            t0, t1 = self.agg_within(side.env_of(ev))
            names = other.schema.names
            from ..core.aggregation import AGG_TIMESTAMP
            for start, _renv, arow in other.agg.rows_between(
                    self.agg_per, t0, t1):
                env = dict(base)
                for n, v in zip(names, arow):
                    env[f"{other.ref}.{n}"] = v
                env[f"{other.ref}.{AGG_TIMESTAMP}"] = start
                if self.on is not None and not self.on(env):
                    continue
                matched = True
                row = self.sel.process(CURRENT, env)
                if row is not None:
                    rows.append((CURRENT, ev.timestamp, row))
            return rows + self._outer_miss(side, other, side_name, base, matched)
        for oev in other.probe_events():
            env = dict(base)
            for n, v in zip(other.schema.names, oev.data):
                env[f"{other.ref}.{n}"] = v
            if self.on is not None and not self.on(env):
                continue
            matched = True
            row = self.sel.process(CURRENT, env)
            if row is not None:
                rows.append((CURRENT, ev.timestamp, row))
        return rows + self._outer_miss(side, other, side_name, base, matched)

    def _outer_miss(self, side, other, side_name: str, base: dict,
                    matched: bool) -> list:
        """Outer-join miss: emit the arriving event with nulls for the
        absent side (reference: JoinProcessor outer handling)."""
        outer = (self.join_type == ast.JoinType.FULL_OUTER
                 or (self.join_type == ast.JoinType.LEFT_OUTER
                     and side_name == "left")
                 or (self.join_type == ast.JoinType.RIGHT_OUTER
                     and side_name == "right"))
        if matched or not outer:
            return []
        env = dict(base)
        for n in other.schema.names:
            env[f"{other.ref}.{n}"] = None
        row = self.sel.process(CURRENT, env)
        if row is None:
            return []
        return [(CURRENT, int(env["__timestamp__"]), row)]

    def _post(self, rows: list) -> list:
        if self.sel.order_by or self.sel.selector.limit is not None \
                or self.sel.selector.offset:
            cur = [(t, r) for _k, t, r in rows]
            rows = [(CURRENT, t, r) for t, r in self.sel.order_limit(cur)]
        if self.rate is not None:
            rows = [r for k, t, row in rows for r in self.rate.feed(k, t, row)]
        return rows

    def on_timer(self, now_ms: int) -> list:
        self.left.on_timer(now_ms)
        self.right.on_timer(now_ms)
        rows = []
        if self.rate is not None:
            rows = self.rate.on_timer(now_ms)
        return self._to_batches(rows)

    def next_wakeup(self):
        cands = [w for w in (self.left.next_wakeup(), self.right.next_wakeup(),
                             self.rate.next_wakeup() if self.rate else None)
                 if w is not None]
        return min(cands) if cands else None

    def _to_batches(self, rows: list) -> list:
        if not rows or self.events_for == ast.OutputEventsFor.EXPIRED:
            return []
        bb = BatchBuilder(self.out_schema, self.rt.strings)
        for _k, t, r in rows:
            bb.append(t, tuple(r))
        return [OutputBatch(self.output_target, bb.freeze())]

    def state_dict(self) -> dict:
        return {"left": self.left.state(), "right": self.right.state(),
                "selector": self.sel.state(),
                "rate": self.rate.state() if self.rate else None}

    def load_state_dict(self, d: dict) -> None:
        self.left.restore(d["left"])
        self.right.restore(d["right"])
        self.sel.restore(d["selector"])
        if self.rate is not None and d.get("rate") is not None:
            self.rate.restore(d["rate"])


def _join_selector(sel: ast.Selector, plan: InterpJoinQueryPlan) -> ast.Selector:
    """Expand `select *` to both sides' attributes (left then right;
    duplicate names get a ref prefix — reference raises instead, we rename)."""
    if not sel.select_all:
        return sel
    attrs = []
    seen = set()
    for side in (plan.left, plan.right):
        for a in side.schema.attributes:
            nm = a.name if a.name not in seen else f"{side.ref}_{a.name}"
            seen.add(nm)
            attrs.append(ast.OutputAttribute(
                ast.Variable(a.name, stream_ref=side.ref), nm))
    return ast.Selector(False, tuple(attrs), sel.group_by, sel.having,
                        sel.order_by, sel.limit, sel.offset)
