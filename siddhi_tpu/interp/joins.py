"""Stream-stream window joins — sequential backend.

Reference semantics (core:query/input/stream/join/JoinProcessor.java:62-126,
built by core:util/parser/JoinInputStreamParser.java): each side owns a
window; an arriving event (after its side's filters) probes the OPPOSITE
side's current window content with the compiled `on` condition and emits
one joined event per match.  Left/right/full outer joins emit the arriving
event with nulls for the other side when nothing matches; `unidirectional`
restricts which side's arrivals trigger output.

Implementation detail: instead of reaching into each window's internals,
every side keeps a `retained` list driven by the window's own
current/expired/reset emission protocol — so ALL window types compose with
joins for free.  The arriving event probes the opposite side BEFORE being
retained on its own side (self-joins don't match an event with itself).
"""
from __future__ import annotations

from typing import Optional

from ..query import ast
from ..core.batch import BatchBuilder, EventBatch
from ..core.planner import OutputBatch, PlanError, QueryPlan
from ..core.runtime import Event
from .expr import PyExprContext, compile_py
from . import windows as W

CURRENT, EXPIRED, RESET = W.CURRENT, W.EXPIRED, W.RESET


class JoinSide:
    def __init__(self, inp: ast.SingleInputStream, rt):
        from .engine import make_window
        if inp.stream_id not in rt.schemas:
            raise PlanError(f"join: unknown stream {inp.stream_id!r}")
        self.ref = inp.alias
        self.stream_id = inp.stream_id
        self.schema = rt.schemas[inp.stream_id]
        ctx = PyExprContext({inp.alias: self.schema,
                             inp.stream_id: self.schema},
                            default_ref=inp.alias)
        self.filters = [compile_py(f.expr, ctx)[0] for f in inp.filters]
        for h in inp.handlers:
            if isinstance(h, ast.StreamFunction):
                raise PlanError("join: stream functions on join sides "
                                "not supported")
        self.window: Optional[W.Window] = None
        if inp.window is not None:
            self.window = make_window(inp.window, ctx, self.schema)
        self.retained: list[Event] = []

    def passes(self, env: dict) -> bool:
        return all(f(env) for f in self.filters)

    def env_of(self, ev: Event) -> dict:
        env = {f"{self.ref}.{n}": v for n, v in zip(self.schema.names, ev.data)}
        for n, v in zip(self.schema.names, ev.data):
            env[n] = v
        env["__timestamp__"] = ev.timestamp
        return env

    def apply_emissions(self, emissions: list) -> None:
        for kind, ev in emissions:
            if kind == CURRENT:
                self.retained.append(ev)
            elif kind == EXPIRED:
                # windows re-stamp expired events with their expiry time
                # (reference current/expired protocol) — match on data,
                # FIFO, which mirrors window expiry order
                for i, r in enumerate(self.retained):
                    if r.data == ev.data:
                        del self.retained[i]
                        break
            elif kind == RESET:
                self.retained.clear()

    def retain(self, ev: Event, now_ms: int) -> None:
        if self.window is None:
            return                    # windowless side keeps nothing
        self.apply_emissions(self.window.process(ev, now_ms))

    def on_timer(self, now_ms: int) -> None:
        if self.window is not None:
            self.apply_emissions(self.window.on_timer(now_ms))

    def next_wakeup(self):
        return self.window.next_wakeup() if self.window is not None else None

    def state(self) -> dict:
        return {"window": self.window.state() if self.window else None,
                "retained": [(e.timestamp, e.data) for e in self.retained]}

    def restore(self, st: dict) -> None:
        if self.window is not None and st.get("window") is not None:
            self.window.restore(st["window"])
        self.retained = [Event(t, tuple(d)) for t, d in st["retained"]]


class InterpJoinQueryPlan(QueryPlan):
    """`from A#win as a join B#win as b on a.x == b.y select ...`"""

    def __init__(self, name: str, rt, q: ast.Query,
                 inp: ast.JoinInputStream, target: Optional[str]):
        from .engine import InterpSelector, make_rate_limiter
        self.name = name
        self.rt = rt
        self.output_target = target
        self.events_for = getattr(q.output, "events_for",
                                  ast.OutputEventsFor.CURRENT)
        self.left = JoinSide(inp.left, rt)
        self.right = JoinSide(inp.right, rt)
        if self.left.ref == self.right.ref:
            raise PlanError(f"join {name!r}: both sides named "
                            f"{self.left.ref!r}; alias one with `as`")
        self.join_type = inp.join_type
        self.trigger = inp.trigger       # "all" | "left" | "right"
        schemas = {self.left.ref: self.left.schema,
                   self.right.ref: self.right.schema}
        ctx = PyExprContext(schemas)
        self.on = compile_py(inp.on, ctx)[0] if inp.on is not None else None
        self.sel = InterpSelector(_join_selector(q.selector, self), ctx,
                                  None, target or f"#{name}")
        self.out_schema = self.sel.out_schema
        self.rate = make_rate_limiter(q.rate)
        self.input_streams = tuple({self.left.stream_id, self.right.stream_id})
        self._buffer: list = []          # (seq, stream_id, Event)

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        rows = batch.rows(self.rt.strings)
        seqs = batch.seqs if batch.seqs is not None else range(batch.n)
        for seq, ts, row in zip(seqs, batch.timestamps, rows):
            self._buffer.append((int(seq), stream_id, Event(int(ts), row)))
        return []

    def finalize(self) -> list:
        if not self._buffer:
            return []
        buf = sorted(self._buffer, key=lambda t: t[0])
        self._buffer = []
        out_rows: list = []
        for _seq, sid, ev in buf:
            now = ev.timestamp if self.rt._playback else self.rt.now_ms()
            # self-join: one arrival drives both sides — all probes run
            # before either side retains, so an event never joins itself
            arrivals = []
            if sid == self.left.stream_id:
                arrivals.append((self.left, self.right, "left"))
            if sid == self.right.stream_id:
                arrivals.append((self.right, self.left, "right"))
            passed = []
            for side, other, side_name in arrivals:
                if side.passes(side.env_of(ev)):
                    passed.append((side, other, side_name))
                    out_rows.extend(self._probe(side, other, side_name, ev))
            for side, _other, _sn in passed:
                side.retain(ev, now)
        out_rows = self._post(out_rows)
        return self._to_batches(out_rows)

    def _probe(self, side: JoinSide, other: JoinSide, side_name: str,
               ev: Event) -> list:
        if self.trigger not in ("all", side_name):
            return []
        rows = []
        base = {f"{side.ref}.{n}": v
                for n, v in zip(side.schema.names, ev.data)}
        base["__timestamp__"] = ev.timestamp
        matched = False
        for oev in other.retained:
            env = dict(base)
            for n, v in zip(other.schema.names, oev.data):
                env[f"{other.ref}.{n}"] = v
            if self.on is not None and not self.on(env):
                continue
            matched = True
            row = self.sel.process(CURRENT, env)
            if row is not None:
                rows.append((CURRENT, ev.timestamp, row))
        outer = (self.join_type == ast.JoinType.FULL_OUTER
                 or (self.join_type == ast.JoinType.LEFT_OUTER
                     and side_name == "left")
                 or (self.join_type == ast.JoinType.RIGHT_OUTER
                     and side_name == "right"))
        if not matched and outer:
            env = dict(base)
            for n in other.schema.names:
                env[f"{other.ref}.{n}"] = None
            row = self.sel.process(CURRENT, env)
            if row is not None:
                rows.append((CURRENT, ev.timestamp, row))
        return rows

    def _post(self, rows: list) -> list:
        if self.sel.order_by or self.sel.selector.limit is not None \
                or self.sel.selector.offset:
            cur = [(t, r) for _k, t, r in rows]
            rows = [(CURRENT, t, r) for t, r in self.sel.order_limit(cur)]
        if self.rate is not None:
            rows = [r for k, t, row in rows for r in self.rate.feed(k, t, row)]
        return rows

    def on_timer(self, now_ms: int) -> list:
        self.left.on_timer(now_ms)
        self.right.on_timer(now_ms)
        rows = []
        if self.rate is not None:
            rows = self.rate.on_timer(now_ms)
        return self._to_batches(rows)

    def next_wakeup(self):
        cands = [w for w in (self.left.next_wakeup(), self.right.next_wakeup(),
                             self.rate.next_wakeup() if self.rate else None)
                 if w is not None]
        return min(cands) if cands else None

    def _to_batches(self, rows: list) -> list:
        if not rows or self.events_for == ast.OutputEventsFor.EXPIRED:
            return []
        bb = BatchBuilder(self.out_schema, self.rt.strings)
        for _k, t, r in rows:
            bb.append(t, tuple(r))
        return [OutputBatch(self.output_target, bb.freeze())]

    def state_dict(self) -> dict:
        return {"left": self.left.state(), "right": self.right.state(),
                "selector": self.sel.state(),
                "rate": self.rate.state() if self.rate else None}

    def load_state_dict(self, d: dict) -> None:
        self.left.restore(d["left"])
        self.right.restore(d["right"])
        self.sel.restore(d["selector"])
        if self.rate is not None and d.get("rate") is not None:
            self.rate.restore(d["rate"])


def _join_selector(sel: ast.Selector, plan: InterpJoinQueryPlan) -> ast.Selector:
    """Expand `select *` to both sides' attributes (left then right;
    duplicate names get a ref prefix — reference raises instead, we rename)."""
    if not sel.select_all:
        return sel
    attrs = []
    seen = set()
    for side in (plan.left, plan.right):
        for a in side.schema.attributes:
            nm = a.name if a.name not in seen else f"{side.ref}_{a.name}"
            seen.add(nm)
            attrs.append(ast.OutputAttribute(
                ast.Variable(a.name, stream_ref=side.ref), nm))
    return ast.Selector(False, tuple(attrs), sel.group_by, sel.having,
                        sel.order_by, sel.limit, sel.offset)
