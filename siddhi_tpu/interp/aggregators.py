"""Host attribute aggregators implementing the current/expired/reset
protocol (reference: core:query/selector/attribute/aggregator/*.java —
sum:334, avg:408, min:428/max:425 with expired-recompute deques, count,
distinctCount, stdDev:303, minForever/maxForever, and/or, unionSet)."""
from __future__ import annotations

import bisect
import math
from typing import Optional

from ..query.ast import AttrType
from ..core.expr import ExprError, promote


class Aggregator:
    type: AttrType = AttrType.DOUBLE

    def add(self, v):
        raise NotImplementedError

    def remove(self, v):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def value(self):
        raise NotImplementedError

    def state(self):
        return self.__dict__.copy()

    def restore(self, st):
        self.__dict__.update(st)


class SumAgg(Aggregator):
    def __init__(self, in_type: AttrType):
        self.type = AttrType.LONG if in_type in (AttrType.INT, AttrType.LONG) \
            else AttrType.DOUBLE
        self.s = None

    def add(self, v):
        if v is None:
            return
        self.s = v if self.s is None else self.s + v

    def remove(self, v):
        if v is None or self.s is None:
            return
        self.s -= v

    def reset(self):
        self.s = None

    def value(self):
        return self.s


class CountAgg(Aggregator):
    type = AttrType.LONG

    def __init__(self, in_type=None):
        self.n = 0

    def add(self, v):
        self.n += 1

    def remove(self, v):
        self.n -= 1

    def reset(self):
        self.n = 0

    def value(self):
        return self.n


class AvgAgg(Aggregator):
    type = AttrType.DOUBLE

    def __init__(self, in_type=None):
        self.s = 0.0
        self.n = 0

    def add(self, v):
        if v is None:
            return
        self.s += v
        self.n += 1

    def remove(self, v):
        if v is None:
            return
        self.s -= v
        self.n -= 1

    def reset(self):
        self.s, self.n = 0.0, 0

    def value(self):
        return None if self.n == 0 else self.s / self.n


class _OrderedAgg(Aggregator):
    """min/max with expiry — sorted multiset (reference keeps deques and
    recomputes; a sorted list gives O(log n) adds and exact removal)."""

    def __init__(self, in_type: AttrType):
        self.type = in_type
        self.vals: list = []

    def add(self, v):
        if v is None:
            return
        bisect.insort(self.vals, v)

    def remove(self, v):
        if v is None:
            return
        i = bisect.bisect_left(self.vals, v)
        if i < len(self.vals) and self.vals[i] == v:
            self.vals.pop(i)

    def reset(self):
        self.vals = []


class MinAgg(_OrderedAgg):
    def value(self):
        return self.vals[0] if self.vals else None


class MaxAgg(_OrderedAgg):
    def value(self):
        return self.vals[-1] if self.vals else None


class MinForeverAgg(Aggregator):
    def __init__(self, in_type: AttrType):
        self.type = in_type
        self.m = None

    def add(self, v):
        if v is not None and (self.m is None or v < self.m):
            self.m = v

    def remove(self, v):      # forever aggregators ignore expiry
        pass

    def reset(self):
        pass

    def value(self):
        return self.m


class MaxForeverAgg(MinForeverAgg):
    def add(self, v):
        if v is not None and (self.m is None or v > self.m):
            self.m = v


class StdDevAgg(Aggregator):
    type = AttrType.DOUBLE

    def __init__(self, in_type=None):
        self.n = 0
        self.s = 0.0
        self.sq = 0.0

    def add(self, v):
        if v is None:
            return
        self.n += 1
        self.s += v
        self.sq += v * v

    def remove(self, v):
        if v is None:
            return
        self.n -= 1
        self.s -= v
        self.sq -= v * v

    def reset(self):
        self.n, self.s, self.sq = 0, 0.0, 0.0

    def value(self):
        if self.n < 1:
            return None
        mean = self.s / self.n
        var = max(self.sq / self.n - mean * mean, 0.0)
        return math.sqrt(var)


class DistinctCountAgg(Aggregator):
    type = AttrType.LONG

    def __init__(self, in_type=None):
        self.counts: dict = {}

    def add(self, v):
        self.counts[v] = self.counts.get(v, 0) + 1

    def remove(self, v):
        c = self.counts.get(v)
        if c is not None:
            if c <= 1:
                del self.counts[v]
            else:
                self.counts[v] = c - 1

    def reset(self):
        self.counts = {}

    def value(self):
        return len(self.counts)


class AndAgg(Aggregator):
    type = AttrType.BOOL

    def __init__(self, in_type=None):
        self.false_n = 0
        self.n = 0

    def add(self, v):
        self.n += 1
        if not v:
            self.false_n += 1

    def remove(self, v):
        self.n -= 1
        if not v:
            self.false_n -= 1

    def reset(self):
        self.n = self.false_n = 0

    def value(self):
        return self.false_n == 0


class OrAgg(Aggregator):
    type = AttrType.BOOL

    def __init__(self, in_type=None):
        self.true_n = 0

    def add(self, v):
        if v:
            self.true_n += 1

    def remove(self, v):
        if v:
            self.true_n -= 1

    def reset(self):
        self.true_n = 0

    def value(self):
        return self.true_n > 0


class UnionSetAgg(Aggregator):
    type = AttrType.OBJECT

    def __init__(self, in_type=None):
        self.counts: dict = {}

    def add(self, v):
        if isinstance(v, (set, frozenset, list, tuple)):
            for x in v:
                self.counts[x] = self.counts.get(x, 0) + 1
        elif v is not None:
            self.counts[v] = self.counts.get(v, 0) + 1

    def remove(self, v):
        items = v if isinstance(v, (set, frozenset, list, tuple)) else [v]
        for x in items:
            c = self.counts.get(x)
            if c is not None:
                if c <= 1:
                    del self.counts[x]
                else:
                    self.counts[x] = c - 1

    def reset(self):
        self.counts = {}

    def value(self):
        return set(self.counts)


AGGREGATOR_CLASSES = {
    "sum": SumAgg, "count": CountAgg, "avg": AvgAgg, "min": MinAgg,
    "max": MaxAgg, "minforever": MinForeverAgg, "maxforever": MaxForeverAgg,
    "stddev": StdDevAgg, "distinctcount": DistinctCountAgg,
    "and": AndAgg, "or": OrAgg, "unionset": UnionSetAgg,
}


def make_aggregator(name: str, in_type: Optional[AttrType]) -> Aggregator:
    cls = AGGREGATOR_CLASSES.get(name.lower())
    if cls is None:
        raise ExprError(f"unknown aggregator {name!r}")
    return cls(in_type)


def aggregator_out_type(name: str, in_type: Optional[AttrType]) -> AttrType:
    return make_aggregator(name, in_type).type


def register_aggregator(name: str, cls, meta=None) -> None:
    """Extension point: a custom attribute aggregator class (ctor takes
    in_type; implements add/remove/reset/value/state/restore — the
    reference's @Extension AttributeAggregator protocol)."""
    from ..core.planner import AGGREGATOR_NAMES
    from ..extension import register_meta
    register_meta("aggregator", meta)
    AGGREGATOR_CLASSES[name.lower()] = cls
    AGGREGATOR_NAMES.add(name.lower())
