"""Sequential pattern/sequence (CEP NFA) matcher — reference semantics.

The host oracle for the north-star component (reference:
core:query/input/stream/state/* — StreamPre/PostStateProcessor,
LogicalPre/Post, CountPre/Post, Absent*, 2,980 LoC; lowering in
core:util/parser/StateInputStreamParser.java:77-143).

Design (clean-room, semantics-first):
  * the StateElement tree lowers to a linear list of `Node`s
    (stream / absent, count bounds, logical partner links, within bounds);
  * a partial match (`PM`) holds captured events per state ref and the set
    of nodes where it is pending — the analog of a reference StateEvent in
    a pendingStateEventList;
  * `every` lowers to *sticky* entry nodes: a sticky pending PM clones on
    match and stays armed, which subsumes the reference's
    addEveryState re-arming (StreamPostStateProcessor.java:66-68);
  * two-phase commit per event: transitions stage their registrations and
    apply after the event is fully processed, so one event can't climb two
    chained states (the reference's updateState() protocol);
  * sequences add strictness: any PM with captures that was eligible but
    did not transition on an event is killed
    (StreamPreStateProcessor.java:317-330).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..query import ast
from ..core.planner import PlanError
from ..core.runtime import Event

FINAL = None


@dataclass
class Node:
    id: int
    stream_id: str
    ref: str
    filter_fn: Optional[Callable]          # env -> bool
    kind: str = "stream"                   # "stream" | "absent"
    min_count: int = 1
    max_count: int = 1
    within_ms: Optional[int] = None        # expiry for PMs pending here
    waiting_ms: Optional[int] = None       # absent: `for T`
    next_id: Optional[int] = FINAL
    sticky: bool = False                   # `every`-armed entry
    partner_id: Optional[int] = None       # logical pair
    partner_op: Optional[str] = None       # "and" | "or"
    is_entry: bool = False


class PM:
    """Partial match (reference: StateEvent + pending-list membership)."""
    _ids = itertools.count()

    __slots__ = ("captures", "first_ts", "nodes", "deadlines", "filled",
                 "alive", "uid", "armed_ts", "sticky_at")

    def __init__(self):
        self.captures: dict = {}          # ref -> [Event]
        self.first_ts: Optional[int] = None
        self.nodes: set = set()           # node ids where pending
        self.deadlines: dict = {}         # node id -> ms (absent)
        self.filled: dict = {}            # node id -> bool (logical)
        self.alive = True
        self.uid = next(PM._ids)
        self.armed_ts: Optional[int] = None
        # node ids where THIS pm is the standing `every` arm: on match it
        # clones forward and stays (a clone is an ordinary pm again)
        self.sticky_at: set = set()

    def clone(self) -> "PM":
        p = PM()
        p.captures = {k: list(v) for k, v in self.captures.items()}
        p.first_ts = self.first_ts
        p.nodes = set()
        p.deadlines = dict(self.deadlines)
        p.filled = dict(self.filled)
        p.armed_ts = self.armed_ts
        return p

    def state(self) -> dict:
        return {"captures": {k: [(e.timestamp, e.data) for e in v]
                             for k, v in self.captures.items()},
                "first_ts": self.first_ts, "nodes": sorted(self.nodes),
                "deadlines": dict(self.deadlines),
                "filled": dict(self.filled),
                "armed_ts": self.armed_ts,
                "sticky_at": sorted(self.sticky_at)}

    @classmethod
    def from_state(cls, st: dict) -> "PM":
        p = cls()
        p.captures = {k: [Event(t, tuple(d)) for t, d in v]
                      for k, v in st["captures"].items()}
        p.first_ts = st["first_ts"]
        p.nodes = set(st["nodes"])
        p.deadlines = {int(k): v for k, v in st["deadlines"].items()}
        p.filled = {int(k): v for k, v in st["filled"].items()}
        p.armed_ts = st["armed_ts"]
        p.sticky_at = set(st.get("sticky_at", ()))
        return p


# ---------------------------------------------------------------------------
# lowering: StateElement tree -> nodes
# ---------------------------------------------------------------------------

class NFACompiler:
    def __init__(self):
        self.nodes: list[Node] = []
        self._anon = itertools.count()

    def _new_node(self, stream: ast.SingleInputStream, kind: str = "stream",
                  waiting_ms=None) -> Node:
        ref = stream.ref_id or f"_s{next(self._anon)}"
        n = Node(id=len(self.nodes), stream_id=stream.stream_id, ref=ref,
                 filter_fn=None, kind=kind, waiting_ms=waiting_ms)
        self.nodes.append(n)
        return n

    def lower(self, elem: ast.StateElement, within: Optional[int] = None
              ) -> tuple[list[Node], list[Node]]:
        """Returns (entry_nodes, exit_nodes)."""
        if isinstance(elem, ast.StreamStateElement):
            n = self._new_node(elem.stream)
            n.within_ms = _min_ms(within, elem.within)
            return [n], [n]
        if isinstance(elem, ast.AbsentStreamStateElement):
            n = self._new_node(elem.stream, kind="absent",
                               waiting_ms=elem.waiting_time.millis
                               if elem.waiting_time else None)
            n.within_ms = _min_ms(within, elem.within)
            return [n], [n]
        if isinstance(elem, ast.CountStateElement):
            n = self._new_node(elem.stream.stream)
            n.min_count = elem.min_count
            n.max_count = elem.max_count if elem.max_count != ast.CountStateElement.ANY \
                else 10**9
            n.within_ms = _min_ms(within, elem.within)
            return [n], [n]
        if isinstance(elem, ast.LogicalStateElement):
            ln = self._lower_logical_side(elem.left)
            rn = self._lower_logical_side(elem.right)
            ln.partner_id, rn.partner_id = rn.id, ln.id
            ln.partner_op = rn.partner_op = elem.op
            w = _min_ms(within, elem.within)
            ln.within_ms = rn.within_ms = w
            return [ln, rn], [ln, rn]
        if isinstance(elem, ast.NextStateElement):
            e1, x1 = self.lower(elem.state, within)
            e2, x2 = self.lower(elem.next, within)
            for x in x1:
                x.next_id = e2[0].id   # logical pairs register both (see advance)
            return e1, x2
        if isinstance(elem, ast.EveryStateElement):
            w = _min_ms(within, elem.within)
            e, x = self.lower(elem.state, w)
            for n in e:
                n.sticky = True
            return e, x
        raise PlanError(f"cannot lower state element {type(elem).__name__}")

    def _lower_logical_side(self, side: ast.StateElement) -> Node:
        if isinstance(side, ast.StreamStateElement):
            return self._new_node(side.stream)
        if isinstance(side, ast.AbsentStreamStateElement):
            return self._new_node(side.stream, kind="absent",
                                  waiting_ms=side.waiting_time.millis
                                  if side.waiting_time else None)
        raise PlanError("logical and/or sides must be simple stream states")


def _min_ms(a: Optional[int], b) -> Optional[int]:
    bm = b.millis if isinstance(b, ast.TimeConstant) else b
    if a is None:
        return bm
    if bm is None:
        return a
    return min(a, bm)


# ---------------------------------------------------------------------------
# the matcher
# ---------------------------------------------------------------------------

class PatternMatcher:
    def __init__(self, nodes: list[Node], entry_ids: list[int],
                 is_sequence: bool, query_within_ms: Optional[int]):
        self.nodes = nodes
        self.entry_ids = entry_ids
        self.is_sequence = is_sequence
        self.query_within = query_within_ms
        self.pendings: dict = {n.id: [] for n in nodes}
        self.by_stream: dict = {}
        for n in nodes:
            self.by_stream.setdefault(n.stream_id, []).append(n)
        self.started = False
        self._schema_names: dict = {}   # stream_id -> attr names (set by plan)
        self._names_by_ref: Optional[dict] = None   # lazy ref -> attr names

    # -- lifecycle ----------------------------------------------------------

    def start(self, now_ms: int) -> None:
        if self.started:
            return
        self.started = True
        pm = PM()
        pm.armed_ts = now_ms
        for nid in self.entry_ids:
            self._register(pm, nid, now_ms)
        # logical entry pairs share one PM; counts with min 0 epsilon-advance
        self._commit_epsilons(pm, now_ms)

    def _register(self, pm: PM, nid: int, now_ms: int) -> None:
        node = self.nodes[nid]
        if pm not in self.pendings[nid]:
            self.pendings[nid].append(pm)
        if nid not in pm.nodes and node.sticky:
            # entering an `every` scope from outside: become its standing arm
            pm.sticky_at.add(nid)
        pm.nodes.add(nid)
        if node.kind == "absent" and node.waiting_ms is not None \
                and nid not in pm.deadlines:
            base = pm.first_ts if pm.first_ts is not None else \
                (pm.armed_ts if pm.armed_ts is not None else now_ms)
            last = self._last_capture_ts(pm)
            base = last if last is not None else base
            pm.deadlines[nid] = base + node.waiting_ms

    def _last_capture_ts(self, pm: PM) -> Optional[int]:
        best = None
        for evs in pm.captures.values():
            for e in evs:
                if best is None or e.timestamp > best:
                    best = e.timestamp
        return best

    def _commit_epsilons(self, pm: PM, now_ms: int) -> None:
        """count nodes with min 0 also arm their successor immediately
        (cascades through consecutive optional states)."""
        changed = True
        while changed:
            changed = False
            for nid in list(pm.nodes):
                node = self.nodes[nid]
                if node.min_count == 0 and node.next_id is not FINAL \
                        and node.next_id not in pm.nodes:
                    self._register(pm, node.next_id, now_ms)
                    nxt = self.nodes[node.next_id]
                    if nxt.partner_id is not None:
                        self._register(pm, nxt.partner_id, now_ms)
                    changed = True

    # -- event processing ---------------------------------------------------

    def on_event(self, stream_id: str, ev: Event) -> list[dict]:
        """Returns completed matches as capture dicts."""
        matches: list = []
        staged: list = []          # (pm, node_id) to register after the event
        transitioned: set = set()  # pm uids that advanced/collected

        for node in self.by_stream.get(stream_id, ()):
            for pm in list(self.pendings[node.id]):
                if not pm.alive or node.id not in pm.nodes:
                    self.pendings[node.id].remove(pm)
                    continue
                # within expiry (lazy)
                if self._expired(pm, node, ev.timestamp):
                    self._kill(pm)
                    continue
                if node.kind == "absent":
                    if self._eval(node, pm, ev):
                        self._absent_stream_arrived(pm, node, matches, ev)
                    continue
                if self._eval(node, pm, ev):
                    self._transition(pm, node, ev, staged, matches, transitioned)

        # commit staged registrations
        for pm, nid in staged:
            if pm.alive:
                self._register(pm, nid, ev.timestamp)
                self._commit_epsilons(pm, ev.timestamp)

        # sequence strictness: ANY event in the query's stream set breaks
        # contiguity for every started PM that didn't transition on it
        if self.is_sequence:
            for lst in self.pendings.values():
                for pm in list(lst):
                    if pm.alive and pm.first_ts is not None \
                            and pm.uid not in transitioned:
                        self._kill(pm)
        self._gc()
        return matches

    def _expired(self, pm: PM, node: Node, now_ms: int) -> bool:
        if pm.first_ts is None:
            return False
        w = node.within_ms if node.within_ms is not None else self.query_within
        if w is None:
            return False
        return now_ms - pm.first_ts > w

    def _eval(self, node: Node, pm: PM, ev: Event) -> bool:
        if node.filter_fn is None:
            return True
        env = self.env_of_captures(pm.captures)
        # current event bound to the node's own ref (and unqualified attrs)
        for k, v in self._event_env(node, ev).items():
            env[k] = v
        return bool(node.filter_fn(env))

    def _event_env(self, node: Node, ev: Event) -> dict:
        env = {"__timestamp__": ev.timestamp}
        names = self._schema_names[node.stream_id]
        for nm, v in zip(names, ev.data):
            env[nm] = v
            env[f"{node.ref}.{nm}"] = v
        env[f"{node.ref}.__present__"] = True
        return env

    def env_of_captures(self, captures: dict) -> dict:
        names_by_ref = self._names_by_ref
        if names_by_ref is None:
            names_by_ref = self._names_by_ref = {
                n.ref: self._schema_names[n.stream_id] for n in self.nodes}
        env: dict = {}
        for ref, evs in captures.items():
            names = names_by_ref.get(ref, ())
            if not evs:
                continue
            last = evs[-1]
            env[f"{ref}.__present__"] = True
            for nm, v in zip(names, last.data):
                env[f"{ref}.{nm}"] = v
                env[f"{ref}[last].{nm}"] = v
            for i, e in enumerate(evs):
                for nm, v in zip(names, e.data):
                    env[f"{ref}[{i}].{nm}"] = v
            if len(evs) >= 2:
                for nm, v in zip(names, evs[-2].data):
                    env[f"{ref}[last-1].{nm}"] = v
        return env

    def _transition(self, pm: PM, node: Node, ev: Event, staged: list,
                    matches: list, transitioned: set) -> None:
        # standing `every` arms clone; the armed original never leaves
        if node.id in pm.sticky_at:
            work = pm.clone()
            # a fresh clone is pending at the same node (non-sticky semantics);
            # logical pairs pend at BOTH partners so the other side can fill
            # (reference: both Pre processors share the pending StateEvent)
            work.nodes.add(node.id)
            self.pendings[node.id].append(work)
            if node.partner_id is not None:
                work.nodes.add(node.partner_id)
                self.pendings[node.partner_id].append(work)
            work_is_clone = True
        else:
            work = pm
            work_is_clone = False
        transitioned.add(pm.uid)
        transitioned.add(work.uid)

        work.captures.setdefault(node.ref, []).append(ev)
        if work.first_ts is None:
            work.first_ts = ev.timestamp

        if node.partner_id is not None:
            self._logical_fill(work, node, ev, staged, matches)
        elif node.max_count > 1 or node.min_count != 1:
            n = len(work.captures[node.ref])
            if n >= node.max_count:
                self._leave(work, node.id)
            if n == node.min_count:
                self._advance(work, node, ev, staged, matches)
            elif n > node.min_count and node.next_id is FINAL:
                self._emit_or_stage(work, node, ev, staged, matches)
        else:
            self._leave(work, node.id)
            self._advance(work, node, ev, staged, matches)

        if work_is_clone and node.min_count == 0:
            pass  # epsilon successors handled at registration

    def _logical_fill(self, pm: PM, node: Node, ev: Event, staged, matches):
        pm.filled[node.id] = True
        partner = self.nodes[node.partner_id]
        if node.partner_op == "or":
            done = True
        elif partner.kind == "absent":
            # `not B and e2=C`: if B had arrived this PM would be dead, so
            # the present side completing the pair suffices
            done = True
        else:
            done = pm.filled.get(node.partner_id, False)
        if done:
            self._leave(pm, node.id)
            self._leave(pm, node.partner_id)
            self._advance(pm, node, ev, staged, matches)

    def _advance(self, pm: PM, node: Node, ev: Event, staged, matches):
        if node.next_id is FINAL:
            self._emit_or_stage(pm, node, ev, staged, matches)
            return
        nxt = self.nodes[node.next_id]
        staged.append((pm, nxt.id))
        if nxt.partner_id is not None:
            staged.append((pm, nxt.partner_id))

    def _emit_or_stage(self, pm: PM, node: Node, ev: Event, staged, matches):
        if self.query_within is not None and pm.first_ts is not None \
                and ev.timestamp - pm.first_ts > self.query_within:
            self._kill(pm)
            return
        matches.append({"captures": {k: list(v) for k, v in pm.captures.items()},
                        "ts": ev.timestamp})
        # count-final PMs may continue collecting (still pending at count node)
        if not any(self.nodes[nid].max_count > 1 for nid in pm.nodes):
            self._kill(pm)

    def _absent_stream_arrived(self, pm: PM, node: Node, matches, ev):
        """The forbidden stream fired for a pending absent node."""
        if node.partner_id is not None and node.partner_op == "or":
            self._leave(pm, node.id)
            return
        if node.partner_id is not None:  # and-with-absent: whole pm dies
            self._kill(pm)
            return
        if node.id in pm.sticky_at:
            # every not-X: re-arm deadline after the offending event
            pm.deadlines[node.id] = ev.timestamp + (node.waiting_ms or 0)
            return
        self._kill(pm)

    # -- timers (absent states) ---------------------------------------------

    def on_timer(self, now_ms: int) -> list[dict]:
        matches: list = []
        staged: list = []
        for node in self.nodes:
            if node.kind != "absent":
                continue
            for pm in list(self.pendings[node.id]):
                if not pm.alive:
                    self.pendings[node.id].remove(pm)
                    continue
                dl = pm.deadlines.get(node.id)
                if dl is None or now_ms < dl:
                    continue
                # waiting period elapsed with no forbidden event
                if node.id in pm.sticky_at:
                    work = pm.clone()
                    pm.deadlines[node.id] = dl + (node.waiting_ms or 1)
                else:
                    work = pm
                    self._leave(work, node.id)
                    if node.partner_id is not None:
                        self._leave(work, node.partner_id)
                if work.first_ts is None:
                    work.first_ts = dl
                if node.next_id is FINAL:
                    matches.append({"captures": {k: list(v) for k, v
                                                 in work.captures.items()},
                                    "ts": dl})
                    if work is pm and not node.sticky:
                        self._kill(work)
                else:
                    staged.append((work, node.next_id))
        for pm, nid in staged:
            if pm.alive:
                self._register(pm, nid, now_ms)
                self._commit_epsilons(pm, now_ms)
        self._gc()
        return matches

    def next_wakeup(self) -> Optional[int]:
        best = None
        for node in self.nodes:
            if node.kind != "absent":
                continue
            for pm in self.pendings[node.id]:
                if not pm.alive:
                    continue
                dl = pm.deadlines.get(node.id)
                if dl is not None and (best is None or dl < best):
                    best = dl
        return best

    # -- bookkeeping ---------------------------------------------------------

    def _leave(self, pm: PM, nid: int) -> None:
        pm.nodes.discard(nid)
        try:
            self.pendings[nid].remove(pm)
        except ValueError:
            pass

    def _kill(self, pm: PM) -> None:
        pm.alive = False
        for nid in list(pm.nodes):
            self._leave(pm, nid)

    def _gc(self) -> None:
        for lst in self.pendings.values():
            lst[:] = [p for p in lst if p.alive]

    # -- snapshot ------------------------------------------------------------

    def state(self) -> dict:
        pms: dict = {}
        order: dict = {}
        for nid, lst in self.pendings.items():
            order[nid] = []
            for pm in lst:
                pms[pm.uid] = pm
                order[nid].append(pm.uid)
        return {"pms": {uid: pm.state() for uid, pm in pms.items()},
                "order": order, "started": self.started}

    def restore(self, st: dict) -> None:
        rebuilt = {int(uid): PM.from_state(s) for uid, s in st["pms"].items()}
        self.pendings = {n.id: [] for n in self.nodes}
        for nid, uids in st["order"].items():
            for uid in uids:
                self.pendings[int(nid)].append(rebuilt[int(uid)])
        self.started = st["started"]
