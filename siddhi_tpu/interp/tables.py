"""`expr in Table` membership conditions (reference: the In expression is
compiled into a table condition + containsEvent probe —
core:util/parser/ExpressionParser.java:451-461,
core:executor/condition/InConditionExpressionExecutor.java:58)."""
from __future__ import annotations

from ..core.expr import ExprError
from ..query.ast import AttrType


def compile_in_table(expr, ctx):
    table = getattr(ctx, "tables", {}).get(expr.table_id)
    if table is None:
        raise ExprError(f"'in {expr.table_id}': unknown table")
    from ..core.table import compile_table_condition
    cond = compile_table_condition(expr.expr, table, (table.id,), ctx)
    return (lambda env: cond.contains(env)), AttrType.BOOL
