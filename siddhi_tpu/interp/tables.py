"""Host in-memory tables (reference: core:table/InMemoryTable.java:225 over
EventHolders, core:table/holder/IndexEventHolder.java:59 primary-key map +
secondary indexes).  Filled in by the tables milestone; `compile_in_table`
lowers `expr in Table` membership tests."""
from __future__ import annotations

from ..core.expr import ExprError
from ..query.ast import AttrType


def compile_in_table(expr, ctx):
    table = getattr(ctx, "tables", {}).get(expr.table_id)
    if table is None:
        raise ExprError(f"'in {expr.table_id}': unknown table")
    from .expr import compile_py
    f, t = compile_py(expr.expr, ctx)
    return (lambda env: table.contains_value(f(env))), AttrType.BOOL
