"""Named windows: `define window W (…) length(5) output all events`.

A shared window instance living outside any single query (reference:
core:window/Window.java:63-154).  Queries insert into it like a stream
target; its emissions are republished so that any number of queries can
consume them:

    current events  -> stream  "W"
    expired events  -> stream  "#W.expired"
    reset signals   -> stream  "#W.reset"   (empty batch)

Queries reading `from W` subscribe to all three (see engine.py) so their
aggregates track window contents exactly; joins probe `contents()` — the
find facade — instead (reference: WindowWindowProcessor adapter).
"""
from __future__ import annotations

from typing import Optional

from ..query import ast
from ..core.batch import BatchBuilder, EventBatch
from ..core.planner import OutputBatch, PlanError, QueryPlan
from ..core.runtime import Event
from ..core.schema import StreamSchema
from .expr import PyExprContext
from . import windows as W

CURRENT, EXPIRED, RESET = W.CURRENT, W.EXPIRED, W.RESET


def expired_stream_of(wid: str) -> str:
    return f"#{wid}.expired"


def reset_stream_of(wid: str) -> str:
    return f"#{wid}.reset"


class NamedWindowRuntime(QueryPlan):
    """Holds the shared window; registered in rt._plans for timer service
    and snapshotting, but subscribes to nothing — writes arrive through
    the runtime's insert routing (like table writers)."""

    def __init__(self, rt, wd: ast.WindowDefinition):
        from .engine import make_window
        self.rt = rt
        self.wid = wd.id
        self.name = f"#window_{wd.id}"
        self.schema = StreamSchema(wd.id, tuple(wd.attributes))
        self.output_events = wd.output_events
        ctx = PyExprContext({wd.id: self.schema}, default_ref=wd.id,
                            tables=rt.tables)
        self.window = make_window(wd.window, ctx, self.schema)
        self.input_streams = ()
        self.output_target = None
        self.out_schema = self.schema
        self._uid = 0

    # -- write side ----------------------------------------------------------

    def insert(self, batch: EventBatch) -> list:
        """Run an inserted batch through the window; return the republished
        emissions as OutputBatches (contiguous same-kind runs preserve the
        reference's expired-before-displacing-current interleaving)."""
        rows = batch.rows(self.rt.strings)
        emissions: list = []
        for ts, row in zip(batch.timestamps, rows):
            self._uid += 1
            ev = Event(int(ts), row, uid=self._uid)
            now = ev.timestamp if self.rt._playback else self.rt.now_ms()
            emissions.extend(self.window.process(ev, now))
        if isinstance(self.window, W.BatchWindow):
            emissions.extend(self.window.end_chunk(self.rt.now_ms()))
        return self._republish(emissions)

    def on_timer(self, now_ms: int) -> list:
        return self._republish(self.window.on_timer(now_ms))

    def next_wakeup(self) -> Optional[int]:
        return self.window.next_wakeup()

    def _republish(self, emissions: list) -> list:
        want_cur = self.output_events in (ast.OutputEventsFor.CURRENT,
                                          ast.OutputEventsFor.ALL)
        want_exp = self.output_events in (ast.OutputEventsFor.EXPIRED,
                                          ast.OutputEventsFor.ALL)
        out: list = []
        run_kind, bb = None, None

        def flush_run():
            nonlocal bb, run_kind
            if run_kind is None:
                return
            if run_kind == RESET:
                out.append(OutputBatch(reset_stream_of(self.wid),
                                       EventBatch.empty(self.schema),
                                       is_signal=True))
            elif bb is not None and len(bb):
                if run_kind == CURRENT:
                    out.append(OutputBatch(self.wid, bb.freeze()))
                else:
                    out.append(OutputBatch(expired_stream_of(self.wid),
                                           bb.freeze(), is_expired=True))
            bb, run_kind = None, None

        for kind, ev in emissions:
            if kind == CURRENT and not want_cur:
                continue
            if kind == EXPIRED and not want_exp:
                continue
            if kind != run_kind:
                flush_run()
                run_kind = kind
                if kind != RESET:
                    bb = BatchBuilder(self.schema, self.rt.strings)
            if kind != RESET:
                bb.append(ev.timestamp, ev.data)
        flush_run()
        return out

    # -- read side (find facade, reference: Window.find) ---------------------

    def contents(self) -> list:
        return self.window.contents()

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        return []       # writes come via runtime insert routing

    def state_dict(self) -> dict:
        return {"window": self.window.state(), "uid": self._uid}

    def load_state_dict(self, d: dict) -> None:
        self.window.restore(d["window"])
        self._uid = d.get("uid", 0)
