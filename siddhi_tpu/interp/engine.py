"""Sequential (host) query engine — the reference-semantics backend.

Event-at-a-time execution mirroring the reference's processor chains
(reference: core:query/input/ProcessStreamReceiver.java:106 ->
FilterProcessor -> WindowProcessor -> QuerySelector -> OutputRateLimiter
-> OutputCallback).  Roles:
  1. differential-test oracle for the batched TPU plans,
  2. measured CPU baseline for bench.py,
  3. fallback executor for features the TPU backend doesn't cover yet.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Callable, Optional

from ..query import ast
from ..query.ast import AttrType
from ..core.batch import BatchBuilder, EventBatch
from ..core.planner import OutputBatch, PlanError, QueryPlan
from ..core.runtime import Event
from ..core.schema import StreamSchema, StringTable
from .aggregators import make_aggregator
from .expr import PyExprContext, compile_py
from . import windows as W

CURRENT, EXPIRED, RESET = W.CURRENT, W.EXPIRED, W.RESET


# ---------------------------------------------------------------------------
# selector compilation (aggregator site extraction)
# ---------------------------------------------------------------------------

class AggSite:
    __slots__ = ("name", "arg_fns", "in_type", "out_type", "key")

    def __init__(self, name, arg_fns, in_type, out_type, key):
        self.name = name
        self.arg_fns = arg_fns      # compiled arg getters (first arg aggregated)
        self.in_type = in_type
        self.out_type = out_type
        self.key = key              # env key "__agg<i>"


def extract_aggregators(expr: ast.Expression, sites: list, ctx) -> ast.Expression:
    """Replace aggregator calls with placeholder variables; append AggSite."""
    from ..core.planner import AGGREGATOR_NAMES
    if isinstance(expr, ast.FunctionCall) and expr.namespace is None \
            and expr.name.lower() in AGGREGATOR_NAMES:
        arg_fns = [compile_py(a, ctx) for a in expr.args]
        in_type = arg_fns[0][1] if arg_fns else None
        agg = make_aggregator(expr.name, in_type)
        key = f"__agg{len(sites)}"
        sites.append(AggSite(expr.name.lower(), [f for f, _ in arg_fns],
                             in_type, agg.type, key))
        return ast.Variable(key)
    if isinstance(expr, ast.Math):
        return ast.Math(extract_aggregators(expr.left, sites, ctx), expr.op,
                        extract_aggregators(expr.right, sites, ctx))
    if isinstance(expr, ast.Compare):
        return ast.Compare(extract_aggregators(expr.left, sites, ctx), expr.op,
                           extract_aggregators(expr.right, sites, ctx))
    if isinstance(expr, ast.And):
        return ast.And(extract_aggregators(expr.left, sites, ctx),
                       extract_aggregators(expr.right, sites, ctx))
    if isinstance(expr, ast.Or):
        return ast.Or(extract_aggregators(expr.left, sites, ctx),
                      extract_aggregators(expr.right, sites, ctx))
    if isinstance(expr, ast.Not):
        return ast.Not(extract_aggregators(expr.expr, sites, ctx))
    if isinstance(expr, ast.FunctionCall):
        return ast.FunctionCall(expr.name,
                                tuple(extract_aggregators(a, sites, ctx)
                                      for a in expr.args), expr.namespace)
    return expr


class InterpSelector:
    """QuerySelector analog (reference: core:query/selector/QuerySelector.java:76):
    group-by keyed aggregator banks, having, order-by, limit/offset."""

    def __init__(self, selector: ast.Selector, ctx: PyExprContext,
                 in_schema: Optional[StreamSchema], out_stream_id: str):
        self.selector = selector
        self.sites: list[AggSite] = []
        names, types, fns = [], [], []
        if selector.select_all:
            if in_schema is None:
                raise PlanError("select * needs a single input schema")
            for a in in_schema.attributes:
                f, t = compile_py(ast.Variable(a.name), ctx)
                names.append(a.name)
                types.append(t)
                fns.append(f)
        else:
            for oa in selector.attributes:
                rewritten = extract_aggregators(oa.expr, self.sites, ctx)
                site_extra = {s.key: (s.key, s.out_type) for s in self.sites}
                ctx2 = PyExprContext(ctx.schemas, {**ctx.extra, **site_extra},
                                     ctx.default_ref, tables=ctx.tables)
                f, t = compile_py(rewritten, ctx2)
                names.append(oa.name)
                types.append(t)
                fns.append(f)
        self.names, self.types, self.fns = names, types, fns
        self.group_fns = [compile_py(g, ctx)[0] for g in selector.group_by]
        self.having = None
        if selector.having is not None:
            extra = {n: (n, t) for n, t in zip(names, types)}
            extra.update({s.key: (s.key, s.out_type) for s in self.sites})
            hctx = PyExprContext(ctx.schemas, {**ctx.extra, **extra},
                                 ctx.default_ref, tables=ctx.tables)
            h_rewritten = extract_aggregators(selector.having, self.sites, hctx)
            extra.update({s.key: (s.key, s.out_type) for s in self.sites})
            hctx = PyExprContext(ctx.schemas, {**ctx.extra, **extra},
                                 ctx.default_ref, tables=ctx.tables)
            self.having, _ = compile_py(h_rewritten, hctx)
        self.order_by = [(compile_py(ob.var, PyExprContext(
            ctx.schemas, {n: (n, t) for n, t in zip(names, types)},
            ctx.default_ref))[0], ob.order == ast.OrderDir.DESC)
            for ob in selector.order_by]
        # group key -> [Aggregator]
        self._groups: dict = defaultdict(self._new_bank)
        self.out_schema = StreamSchema(out_stream_id, tuple(
            ast.Attribute(n, t) for n, t in zip(names, types)))

    def _new_bank(self):
        return [make_aggregator(s.name, s.in_type) for s in self.sites]

    def _bank_for(self, env) -> list:
        key = tuple(f(env) for f in self.group_fns) if self.group_fns else ()
        return self._groups[key]

    def process(self, kind: str, env: dict):
        """Run one window-emitted event through the selector.
        Returns an output row (list) or None (reset/having-filtered)."""
        if kind == RESET:
            for bank in self._groups.values():
                for a in bank:
                    a.reset()
            return None
        bank = self._bank_for(env)
        for site, agg in zip(self.sites, bank):
            v = site.arg_fns[0](env) if site.arg_fns else None
            if kind == CURRENT:
                agg.add(v)
            else:
                agg.remove(v)
        for site, agg in zip(self.sites, bank):
            env[site.key] = agg.value()
        row = [f(env) for f in self.fns]
        if self.having is not None:
            for n, v in zip(self.names, row):
                env[n] = v
            if not self.having(env):
                return None
        return row

    def order_limit(self, rows: list) -> list:
        """Apply order-by / offset / limit to one output chunk of (ts, row)."""
        for fn, desc in reversed(self.order_by):
            rows.sort(key=lambda tr: fn(dict(zip(self.names, tr[1]))), reverse=desc)
        off = self.selector.offset or 0
        if off:
            rows = rows[off:]
        if self.selector.limit is not None:
            rows = rows[:self.selector.limit]
        return rows

    def state(self):
        # group keys are tuples of scalars — serialize structurally (never
        # repr/eval: snapshots must not be able to execute code on restore)
        return [(k, [a.state() for a in bank])
                for k, bank in self._groups.items()]

    def restore(self, st):
        self._groups.clear()
        if isinstance(st, dict):     # legacy snapshot format: drop aggregates
            st = []
        for k, states in st:
            bank = self._new_bank()
            for a, s in zip(bank, states):
                a.restore(s)
            self._groups[tuple(k)] = bank


# ---------------------------------------------------------------------------
# output rate limiting (reference: core:query/output/ratelimit/*, 12 impls)
# ---------------------------------------------------------------------------

class RateLimiter:
    """Pass-through base; subclasses buffer/emit per policy."""
    needs_timer = False

    def feed(self, kind: str, ts: int, row: list) -> list:
        return [(kind, ts, row)]

    def on_timer(self, now_ms: int) -> list:
        return []

    def next_wakeup(self) -> Optional[int]:
        return None

    def state(self) -> dict:
        return {}

    def restore(self, st) -> None:
        pass


class EventRateLimiter(RateLimiter):
    def __init__(self, count: int, mode: ast.RateType):
        self.count = count
        self.mode = mode
        self.buf: list = []
        self.n = 0

    def feed(self, kind, ts, row):
        if kind != CURRENT:
            return []        # rate limiting applies to output (current) events
        self.n += 1
        if self.mode == ast.RateType.FIRST:
            first = self.n % self.count == 1 or self.count == 1
            return [(kind, ts, row)] if first else []
        self.buf.append((kind, ts, row))
        if self.n % self.count == 0:
            out, self.buf = self.buf, []
            if self.mode == ast.RateType.LAST:
                return [out[-1]]
            return out
        return []

    def state(self):
        return {"buf": self.buf, "n": self.n}

    def restore(self, st):
        self.buf, self.n = list(st["buf"]), st["n"]


class TimeRateLimiter(RateLimiter):
    needs_timer = True

    def __init__(self, millis: int, mode: ast.RateType):
        self.millis = millis
        self.mode = mode
        self.buf: list = []
        self.window_start: Optional[int] = None
        self.emitted_this_window = False

    def feed(self, kind, ts, row):
        if kind != CURRENT:
            return []
        if self.window_start is None:
            self.window_start = ts
        if self.mode == ast.RateType.FIRST:
            if not self.emitted_this_window:
                self.emitted_this_window = True
                return [(kind, ts, row)]
            return []
        self.buf.append((kind, ts, row))
        return []

    def on_timer(self, now_ms):
        if self.window_start is None:
            return []
        out = []
        while now_ms >= self.window_start + self.millis:
            self.window_start += self.millis
            self.emitted_this_window = False
            if self.buf:
                if self.mode == ast.RateType.LAST:
                    out.append(self.buf[-1])
                else:
                    out.extend(self.buf)
                self.buf = []
        return out

    def next_wakeup(self):
        if self.window_start is None:
            return None
        return self.window_start + self.millis

    def state(self):
        return {"buf": self.buf, "ws": self.window_start,
                "em": self.emitted_this_window}

    def restore(self, st):
        self.buf = list(st["buf"])
        self.window_start = st["ws"]
        self.emitted_this_window = st["em"]


class SnapshotRateLimiter(RateLimiter):
    """Emits, every interval, the latest live output rows (reference:
    WrappedSnapshotOutputRateLimiter re-plays window snapshots)."""
    needs_timer = True

    def __init__(self, millis: int):
        self.millis = millis
        self.live: dict = {}       # source seq -> (ts, row)
        self.seq = 0
        self.window_start: Optional[int] = None

    def feed(self, kind, ts, row):
        if self.window_start is None:
            self.window_start = ts
        if kind == CURRENT:
            self.live[self.seq] = (ts, row)
            self.seq += 1
        elif kind == EXPIRED and self.live:
            self.live.pop(next(iter(self.live)), None)
        return []

    def on_timer(self, now_ms):
        if self.window_start is None:
            return []
        out = []
        while now_ms >= self.window_start + self.millis:
            self.window_start += self.millis
            out.extend((CURRENT, now_ms, row) for _, row in self.live.values())
        return out

    def next_wakeup(self):
        if self.window_start is None:
            return None
        return self.window_start + self.millis

    def state(self):
        return {"live": list(self.live.items()), "seq": self.seq,
                "ws": self.window_start}

    def restore(self, st):
        self.live = dict(st["live"])
        self.seq = st["seq"]
        self.window_start = st["ws"]


class GroupedRateLimiter(RateLimiter):
    """Per-group first/last rate limiting (reference: the GroupByPer*
    OutputRateLimiter family, e.g. core:query/output/ratelimit/event/
    GroupByPerEventOutputRateLimiter.java): one child limiter per group
    key, keyed by the selected group-by columns."""

    def __init__(self, factory: Callable, key_idx: list):
        self.factory = factory
        self.key_idx = key_idx
        self.subs: dict = {}
        self.needs_timer = factory().needs_timer

    def _sub(self, row):
        key = tuple(row[i] for i in self.key_idx)
        sub = self.subs.get(key)
        if sub is None:
            sub = self.subs[key] = self.factory()
        return sub

    def feed(self, kind, ts, row):
        return self._sub(row).feed(kind, ts, row)

    def on_timer(self, now_ms):
        out = []
        for sub in self.subs.values():
            out.extend(sub.on_timer(now_ms))
        return out

    def next_wakeup(self):
        ws = [w for s in self.subs.values()
              for w in [s.next_wakeup()] if w is not None]
        return min(ws) if ws else None

    def state(self):
        return {"groups": [(k, s.state()) for k, s in self.subs.items()]}

    def restore(self, st):
        self.subs = {}
        for k, sub_st in st["groups"]:
            sub = self.factory()
            sub.restore(sub_st)
            self.subs[tuple(k)] = sub


def _group_key_positions(selector) -> Optional[list]:
    """Output-row positions of the group-by attributes (None when the
    selection doesn't carry them — falls back to a global limiter)."""
    if selector is None or not selector.group_by or selector.select_all:
        return None
    pos = []
    for g in selector.group_by:
        for i, oa in enumerate(selector.attributes):
            e = oa.expr
            if isinstance(e, ast.Variable) and e.attribute == g.attribute \
                    and e.index is None:
                pos.append(i)
                break
        else:
            return None
    return pos


def make_rate_limiter(rate, selector=None) -> Optional[RateLimiter]:
    if rate is None:
        return None
    if isinstance(rate, ast.EventOutputRate):
        factory = lambda: EventRateLimiter(rate.count, rate.type)
    elif isinstance(rate, ast.TimeOutputRate):
        factory = lambda: TimeRateLimiter(rate.millis, rate.type)
    elif isinstance(rate, ast.SnapshotOutputRate):
        return SnapshotRateLimiter(rate.millis)
    else:
        raise PlanError(f"unknown output rate {rate}")
    # per-group first/last (reference GroupByPer* limiter family)
    if rate.type in (ast.RateType.FIRST, ast.RateType.LAST):
        pos = _group_key_positions(selector)
        if pos is not None:
            return GroupedRateLimiter(factory, pos)
    return factory()


# ---------------------------------------------------------------------------
# window factory
# ---------------------------------------------------------------------------

def _const(e, what="argument"):
    if isinstance(e, ast.TimeConstant):
        return e.millis
    if isinstance(e, ast.Constant):
        return e.value
    raise PlanError(f"window {what} must be constant, got {e}")


def make_window(h: ast.WindowHandler, ctx: PyExprContext,
                schema: StreamSchema) -> W.Window:
    name = h.name.lower()
    args = h.args

    def getter(i):
        f, _ = compile_py(args[i], ctx)
        return lambda ev_env: f(ev_env)

    def ev_getter(i):
        f, _ = compile_py(args[i], ctx)
        names = schema.names
        def g(ev):
            env = dict(zip(names, ev.data))
            env["__timestamp__"] = ev.timestamp
            return f(env)
        return g

    if name == "length":
        return W.LengthWindow(int(_const(args[0])))
    if name == "lengthbatch":
        return W.LengthBatchWindow(int(_const(args[0])))
    if name == "time":
        return W.TimeWindow(int(_const(args[0])))
    if name == "timebatch":
        start = int(_const(args[1])) if len(args) > 1 else None
        return W.TimeBatchWindow(int(_const(args[0])), start)
    if name == "externaltime":
        return W.ExternalTimeWindow(ev_getter(0), int(_const(args[1])))
    if name == "externaltimebatch":
        start = int(_const(args[2])) if len(args) > 2 else None
        return W.ExternalTimeBatchWindow(ev_getter(0), int(_const(args[1])), start)
    if name == "timelength":
        return W.TimeLengthWindow(int(_const(args[0])), int(_const(args[1])))
    if name == "batch":
        return W.BatchWindow()
    if name == "session":
        key = ev_getter(1) if len(args) > 1 else None
        latency = int(_const(args[2])) if len(args) > 2 else 0
        return W.SessionWindow(int(_const(args[0])), key, latency)
    if name == "sort":
        desc = False
        if len(args) > 2 and isinstance(args[2], ast.Constant):
            desc = str(args[2].value).lower() == "desc"
        return W.SortWindow(int(_const(args[0])), ev_getter(1), desc)
    if name == "delay":
        return W.DelayWindow(int(_const(args[0])))
    if name == "frequent":
        key = ev_getter(1) if len(args) > 1 else None
        return W.FrequentWindow(int(_const(args[0])), key)
    if name == "lossyfrequent":
        err = float(_const(args[1])) if len(args) > 1 else None
        key = ev_getter(2) if len(args) > 2 else None
        return W.LossyFrequentWindow(float(_const(args[0])), err, key)
    if name == "cron":
        return W.CronWindow(str(_const(args[0])))
    builder = WINDOW_TYPES.get((h.namespace.lower() if h.namespace else None,
                                name))
    if builder is not None:
        return builder(args, ctx, schema)
    raise PlanError(f"unknown window type {h.name!r}")


# extension point: custom window processors (reference: @Extension windows
# discovered by SiddhiExtensionLoader; here an explicit registry)
WINDOW_TYPES: dict = {}


def register_window_type(name: str, builder, namespace: str = None,
                         meta=None) -> None:
    """builder(args: tuple[ast expr], ctx: PyExprContext, schema) -> Window"""
    from ..extension import register_meta
    register_meta("window", meta)
    WINDOW_TYPES[(namespace.lower() if namespace else None,
                  name.lower())] = builder


# ---------------------------------------------------------------------------
# stream functions (reference: core:query/processor/stream/
# LogStreamProcessor.java, Pol2CartStreamProcessor; extension point ≅
# @Extension StreamFunctionProcessor)
# ---------------------------------------------------------------------------

STREAM_FUNCTIONS: dict = {}


def register_stream_function(name: str, builder, namespace: str = None,
                             meta=None) -> None:
    """builder(args, ctx, in_schema, query_name) ->
    (out_schema, fn(Event) -> list[row_tuple])"""
    from ..extension import register_meta
    register_meta("stream-function", meta)
    STREAM_FUNCTIONS[(namespace.lower() if namespace else None,
                      name.lower())] = builder


def _log_stream_fn(args, ctx, in_schema, query_name):
    msg_fns = [compile_py(a, ctx)[0] for a in args]
    names = in_schema.names

    def fn(ev: Event) -> list:
        env = dict(zip(names, ev.data))
        env["__timestamp__"] = ev.timestamp
        extra = ", ".join(str(f(env)) for f in msg_fns)
        prefix = f"{query_name}: " + (f"{extra}, " if extra else "")
        print(f"{prefix}{ev.timestamp}, {ev.data}")
        return [ev.data]
    return in_schema, fn


def _pol2cart_stream_fn(args, ctx, in_schema, query_name):
    import math as _m
    theta_f = compile_py(args[0], ctx)[0]
    rho_f = compile_py(args[1], ctx)[0]
    z_f = compile_py(args[2], ctx)[0] if len(args) > 2 else None
    names = in_schema.names
    extra = (ast.Attribute("x", AttrType.DOUBLE),
             ast.Attribute("y", AttrType.DOUBLE)) + (
        (ast.Attribute("z", AttrType.DOUBLE),) if z_f else ())
    out_schema = StreamSchema(in_schema.id, in_schema.attributes + extra)

    def fn(ev: Event) -> list:
        env = dict(zip(names, ev.data))
        env["__timestamp__"] = ev.timestamp
        theta, rho = theta_f(env), rho_f(env)
        x = rho * _m.cos(_m.radians(theta))
        y = rho * _m.sin(_m.radians(theta))
        row = ev.data + ((x, y, z_f(env)) if z_f else (x, y))
        return [row]
    return out_schema, fn


register_stream_function("log", _log_stream_fn)
register_stream_function("pol2cart", _pol2cart_stream_fn)


# ---------------------------------------------------------------------------
# single-stream query plan
# ---------------------------------------------------------------------------

class InterpSingleQueryPlan(QueryPlan):
    """from S[f]#window.w(...) select ... group by ... having ...
    output rate ... insert <events_for> into Target — sequential backend."""

    def __init__(self, name: str, rt, q: ast.Query, inp: ast.SingleInputStream,
                 target: Optional[str]):
        from .named_window import expired_stream_of, reset_stream_of
        self.name = name
        self.rt = rt
        schema = rt.schemas[inp.stream_id]
        self.in_schema = schema
        self.input_streams = (inp.stream_id,)
        # reading from a named window: also consume its expired/reset
        # republications so aggregates track window contents (reference:
        # the Window forwards current+expired chunks to reading queries)
        self._nw_expired = self._nw_reset = None
        if inp.stream_id in rt.named_windows:
            if inp.window is not None:
                raise PlanError(f"query {name!r}: cannot apply a window to "
                                f"named window {inp.stream_id!r}")
            self._nw_expired = expired_stream_of(inp.stream_id)
            self._nw_reset = reset_stream_of(inp.stream_id)
            self.input_streams = (inp.stream_id, self._nw_expired,
                                  self._nw_reset)
        self.output_target = target
        self.events_for = getattr(q.output, "events_for", ast.OutputEventsFor.CURRENT)
        ctx = PyExprContext({inp.alias: schema, inp.stream_id: schema},
                            default_ref=inp.alias, tables=rt.tables)
        self.ctx = ctx
        self.filters = [compile_py(f.expr, ctx)[0] for f in inp.filters]
        # stream functions chain (reference: StreamFunctionProcessor
        # subclasses; extension point instead of hardcoded built-ins).
        # Filters apply first, then stream functions in handler order.
        self._stream_fns: list = []
        work_schema = schema
        for h in inp.handlers:
            if isinstance(h, ast.StreamFunction):
                key = (h.namespace.lower() if h.namespace else None,
                       h.name.lower())
                builder = STREAM_FUNCTIONS.get(key)
                if builder is None:
                    raise PlanError(f"query {name!r}: unknown stream function "
                                    f"{h.name!r}")
                hctx = PyExprContext({inp.alias: work_schema,
                                      inp.stream_id: work_schema},
                                     default_ref=inp.alias, tables=rt.tables)
                work_schema, fn = builder(h.args, hctx, work_schema, name)
                self._stream_fns.append(fn)
        self.work_schema = work_schema
        sctx = ctx if work_schema is schema else PyExprContext(
            {inp.alias: work_schema, inp.stream_id: work_schema},
            default_ref=inp.alias, tables=rt.tables)
        self.window: Optional[W.Window] = None
        wh = inp.window
        if wh is not None:
            self.window = make_window(wh, sctx, work_schema)
        self.sel = InterpSelector(q.selector, sctx, work_schema,
                                  target or f"#{name}")
        self.out_schema = self.sel.out_schema
        self.rate = make_rate_limiter(q.rate, q.selector)
        self._names = work_schema.names
        self._in_names = schema.names

    # -- helpers -------------------------------------------------------------

    def _env_of(self, ev: Event) -> dict:
        env = dict(zip(self._names, ev.data))
        env["__timestamp__"] = ev.timestamp
        return env

    def _run_selector(self, emissions: list) -> list:
        """window emissions [(kind, ev)] -> [(kind, ts, row)] post-rate-limit."""
        out = []
        for kind, ev in emissions:
            if kind == RESET:
                self.sel.process(RESET, {})
                continue
            env = self._env_of(ev)
            row = self.sel.process(kind, env)
            if row is None:
                continue
            out.append((kind, ev.timestamp, row))
        # order-by/limit apply per chunk on current rows
        if self.sel.order_by or self.sel.selector.limit is not None \
                or self.sel.selector.offset:
            cur = [(t, r) for k, t, r in out if k == CURRENT]
            cur = self.sel.order_limit(cur)
            out = [(k, t, r) for k, t, r in out if k != CURRENT] + \
                  [(CURRENT, t, r) for t, r in cur]
        if self.rate is not None:
            out2 = []
            for k, t, r in out:
                out2.extend(self.rate.feed(k, t, r))
            out = out2
        return out

    def _to_output_batches(self, rows: list) -> list:
        """[(kind, ts, row)] -> [OutputBatch] honoring events_for."""
        want_current = self.events_for in (ast.OutputEventsFor.CURRENT,
                                           ast.OutputEventsFor.ALL)
        want_expired = self.events_for in (ast.OutputEventsFor.EXPIRED,
                                           ast.OutputEventsFor.ALL)
        cur = [(t, r) for k, t, r in rows if k == CURRENT and want_current]
        exp = [(t, r) for k, t, r in rows if k == EXPIRED and want_expired]
        out = []
        for subset, is_exp in ((cur, False), (exp, True)):
            if not subset:
                continue
            bb = BatchBuilder(self.out_schema, self.rt.strings)
            for t, r in subset:
                bb.append(t, tuple(r))
            out.append(OutputBatch(self.output_target, bb.freeze(), is_exp))
        return out

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        if stream_id == self._nw_reset:
            self.sel.process(RESET, {})
            return []
        kind = EXPIRED if stream_id == self._nw_expired else CURRENT
        rows = batch.rows(self.rt.strings)
        emitted: list = []
        for ts, row in zip(batch.timestamps, rows):
            ev = Event(int(ts), row)
            env = dict(zip(self._in_names, ev.data))
            env["__timestamp__"] = ev.timestamp
            if any(not f(env) for f in self.filters):
                continue
            evs = [ev]
            for fn in self._stream_fns:
                evs = [Event(e.timestamp, r) for e in evs for r in fn(e)]
            now = self.rt.now_ms() if not self.rt._playback else ev.timestamp
            for e2 in evs:
                if self.window is None:
                    emitted.append((kind, e2))
                else:
                    emitted.extend(self.window.process(e2, now))
        if isinstance(self.window, W.BatchWindow):
            emitted.extend(self.window.end_chunk(self.rt.now_ms()))
        out_rows = self._run_selector(emitted)
        return self._to_output_batches(out_rows)

    def on_timer(self, now_ms: int) -> list:
        rows = []
        if self.window is not None:
            rows.extend(self._run_selector(self.window.on_timer(now_ms)))
        if self.rate is not None:
            rows.extend(self.rate.on_timer(now_ms))
        return self._to_output_batches(rows)

    def next_wakeup(self) -> Optional[int]:
        cands = []
        if self.window is not None:
            w = self.window.next_wakeup()
            if w is not None:
                cands.append(w)
        if self.rate is not None:
            w = self.rate.next_wakeup()
            if w is not None:
                cands.append(w)
        return min(cands) if cands else None

    def state_dict(self) -> dict:
        return {
            "window": self.window.state() if self.window else None,
            "selector": self.sel.state(),
            "rate": self.rate.state() if self.rate else None,
        }

    def load_state_dict(self, d: dict) -> None:
        if self.window is not None and d.get("window") is not None:
            self.window.restore(d["window"])
        self.sel.restore(d["selector"])
        if self.rate is not None and d.get("rate") is not None:
            self.rate.restore(d["rate"])


# ---------------------------------------------------------------------------
# pattern / sequence query plan
# ---------------------------------------------------------------------------

class InterpPatternQueryPlan(QueryPlan):
    """from [every] e1=A[...] -> e2=B[...] within T select ... — sequential
    backend over the NFA matcher (reference call stack: SURVEY §3.3)."""

    def __init__(self, name: str, rt, q: ast.Query,
                 state_input, target: Optional[str]):
        from .nfa import NFACompiler, PatternMatcher
        from ..query.ast import StateType
        self.name = name
        self.rt = rt
        self.output_target = target
        self.events_for = getattr(q.output, "events_for", ast.OutputEventsFor.CURRENT)

        comp = NFACompiler()
        entries, _exits = comp.lower(state_input.state)
        self.nodes = comp.nodes
        qw = state_input.within.millis if state_input.within else None
        self.matcher = PatternMatcher(
            self.nodes, [n.id for n in entries],
            state_input.type == StateType.SEQUENCE, qw)

        # schemas per ref + per stream for filter/selector contexts
        schemas: dict = {}
        for n in self.nodes:
            if n.stream_id not in rt.schemas:
                raise PlanError(f"query {name!r}: unknown stream {n.stream_id!r}")
            schemas[n.ref] = rt.schemas[n.stream_id]
        self.matcher._schema_names = {
            sid: rt.schemas[sid].names for sid in {n.stream_id for n in self.nodes}}
        self.input_streams = tuple({n.stream_id for n in self.nodes})

        # node filters: current event attrs unqualified + own ref; other refs
        for n, elem_filters in zip(self.nodes, _collect_filters(state_input.state)):
            if elem_filters:
                own = rt.schemas[n.stream_id]
                ctx = PyExprContext({**schemas, n.ref: own}, default_ref=n.ref,
                                    tables=rt.tables)
                fns = [compile_py(f.expr, ctx)[0] for f in elem_filters]
                if len(fns) == 1:
                    n.filter_fn = fns[0]
                else:
                    n.filter_fn = lambda env, _fns=fns: all(f(env) for f in _fns)

        # selector over capture refs
        sel_ast = q.selector
        if sel_ast.select_all:
            # select * on patterns: concatenation of each ref's attributes
            attrs = []
            seen = set()
            for n in self.nodes:
                for a in rt.schemas[n.stream_id].attributes:
                    nm = a.name if a.name not in seen else f"{n.ref}_{a.name}"
                    seen.add(nm)
                    attrs.append(ast.OutputAttribute(
                        ast.Variable(a.name, stream_ref=n.ref), nm))
            sel_ast = ast.Selector(False, tuple(attrs), sel_ast.group_by,
                                   sel_ast.having, sel_ast.order_by,
                                   sel_ast.limit, sel_ast.offset)
        ctx = PyExprContext(schemas, tables=rt.tables)
        self.sel = InterpSelector(sel_ast, ctx, None, target or f"#{name}")
        self.out_schema = self.sel.out_schema
        self.rate = make_rate_limiter(q.rate, q.selector)
        self._buffer: list = []      # (seq, stream_id, Event)

    # -- QueryPlan interface -------------------------------------------------

    def process(self, stream_id: str, batch: EventBatch) -> list:
        rows = batch.rows(self.rt.strings)
        seqs = batch.seqs if batch.seqs is not None else range(batch.n)
        for seq, ts, row in zip(seqs, batch.timestamps, rows):
            self._buffer.append((int(seq), stream_id, Event(int(ts), row)))
        return []

    def finalize(self) -> list:
        if not self._buffer:
            return []
        now = self.rt.now_ms()
        if self.rt._playback and self.rt._clock_ms is None:
            # playback, virtual clock not yet entered: anchor absent
            # wait-clocks on the event timeline, not the wall clock
            now = min(ev.timestamp for _seq, _sid, ev in self._buffer)
        self.matcher.start(now)
        buf = sorted(self._buffer, key=lambda t: t[0])
        self._buffer = []
        out_rows: list = []
        for _seq, sid, ev in buf:
            if self.rt._playback:
                # fire absent-state deadlines that precede this event
                while True:
                    w = self.matcher.next_wakeup()
                    if w is None or w > ev.timestamp:
                        break
                    out_rows.extend(self._matches_to_rows(
                        self.matcher.on_timer(w)))
            out_rows.extend(self._matches_to_rows(
                self.matcher.on_event(sid, ev)))
        if self.sel.order_by or self.sel.selector.limit is not None \
                or self.sel.selector.offset:
            cur = [(t, r) for _k, t, r in out_rows]
            out_rows = [(CURRENT, t, r) for t, r in self.sel.order_limit(cur)]
        if self.rate is not None:
            out_rows = [r for k, t, row in out_rows
                        for r in self.rate.feed(k, t, row)]
        return self._to_batches(out_rows)

    def on_timer(self, now_ms: int) -> list:
        self.matcher.start(now_ms)
        rows = self._matches_to_rows(self.matcher.on_timer(now_ms))
        if self.rate is not None:
            rows = [r for k, t, row in rows for r in self.rate.feed(k, t, row)]
            rows.extend(self.rate.on_timer(now_ms))
        return self._to_batches(rows)

    def next_wakeup(self):
        self.matcher.start(self.rt.now_ms())
        cands = []
        w = self.matcher.next_wakeup()
        if w is not None:
            cands.append(w)
        if self.rate is not None:
            w = self.rate.next_wakeup()
            if w is not None:
                cands.append(w)
        return min(cands) if cands else None

    # -- helpers -------------------------------------------------------------

    def _matches_to_rows(self, matches: list) -> list:
        rows = []
        for m in matches:
            env = self.matcher.env_of_captures(m["captures"])
            env["__timestamp__"] = m["ts"]
            row = self.sel.process(CURRENT, env)
            if row is not None:
                rows.append((CURRENT, m["ts"], row))
        return rows

    def _to_batches(self, rows: list) -> list:
        if not rows or self.events_for == ast.OutputEventsFor.EXPIRED:
            return []
        bb = BatchBuilder(self.out_schema, self.rt.strings)
        for _k, t, r in rows:
            bb.append(t, tuple(r))
        return [OutputBatch(self.output_target, bb.freeze())]

    def state_dict(self) -> dict:
        return {"matcher": self.matcher.state(),
                "selector": self.sel.state(),
                "rate": self.rate.state() if self.rate else None}

    def load_state_dict(self, d: dict) -> None:
        self.matcher.restore(d["matcher"])
        self.sel.restore(d["selector"])
        if self.rate is not None and d.get("rate") is not None:
            self.rate.restore(d["rate"])


def _collect_filters(elem) -> list:
    """Filters per lowered node, in the same order NFACompiler.lower
    creates nodes (depends on tree shape)."""
    out: list = []

    def walk(e):
        if isinstance(e, ast.StreamStateElement):
            out.append(e.stream.filters)
        elif isinstance(e, ast.AbsentStreamStateElement):
            out.append(e.stream.filters)
        elif isinstance(e, ast.CountStateElement):
            out.append(e.stream.stream.filters)
        elif isinstance(e, ast.LogicalStateElement):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, ast.NextStateElement):
            walk(e.state)
            walk(e.next)
        elif isinstance(e, ast.EveryStateElement):
            walk(e.state)
        else:
            raise PlanError(f"unknown state element {type(e).__name__}")

    walk(elem)
    return out
