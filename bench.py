#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline (BASELINE.json config 4 shape): partitioned 3-state CEP pattern
`every e1 -> e2 -> e3` by key over 1k partitions — the north-star
workload.  Device path: all per-key NFA instances advance as one batched
kernel (partition axis P).  Baseline: the sequential host interpreter
with per-key cloned matchers — our measured stand-in for the single-JVM
reference engine (the reference publishes no numbers, BASELINE.md).

vs_baseline = device events/sec ÷ host-interpreter events/sec.
"""
import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np

KEYS = 1000

APP = """
define stream S (sym string, p double);
partition with (sym of S)
begin
  @info(name='q')
  from every e1=S[p > 100.0] -> e2=S[p > e1.p] -> e3=S[p > e2.p]
    within 10 sec
  select e1.p as p1, e2.p as p2, e3.p as p3 insert into M;
end;
"""


def make_batches(rt, n_events, batch):
    from siddhi_tpu.core.batch import EventBatch

    schema = rt.schemas["S"]
    rng = np.random.default_rng(0)
    sym_codes = np.array([rt.strings.encode(f"K{i}") for i in range(KEYS)],
                         dtype=np.int32)
    batches = []
    seq0 = 1
    ts0 = 1_700_000_000_000
    for start in range(0, n_events, batch):
        n = min(batch, n_events - start)
        cols = {
            "sym": rng.choice(sym_codes, size=n),
            "p": rng.uniform(90.0, 130.0, size=n),
        }
        ts = ts0 + np.arange(start, start + n, dtype=np.int64)
        seqs = np.arange(seq0 + start, seq0 + start + n, dtype=np.int64)
        batches.append(EventBatch(schema, ts, cols, n, seqs))
    return batches


def run(mode: str, n_events: int, batch: int):
    """Returns (events/sec, match_count)."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        f"@app:devicePatterns('{mode}')\n@app:partitionCapacity({KEYS})\n"
        f"@app:deviceSlots(32)\n" + APP)
    counted = [0]
    rt.add_batch_callback("M", lambda b: counted.__setitem__(0, counted[0] + b.n))
    rt.start()
    batches = make_batches(rt, n_events + batch, batch)

    # warmup: covers all keys -> device kernel compiles / host clones build
    rt._pending.append(("S", batches[0]))
    rt._drain()
    warm = counted[0]

    t0 = time.perf_counter()
    for b in batches[1:]:
        rt._pending.append(("S", b))
        rt._drain()
    dt = time.perf_counter() - t0
    return n_events / dt, counted[0] - warm


def main():
    # event counts are whole multiples of the batch size: a straggler batch
    # would land in a fresh (T, M) jit bucket and pay a recompile mid-run
    dev_eps, dev_matches = run("auto", 4 << 18, 1 << 18)
    cpu_eps, cpu_matches = run("never", 1 << 16, 1 << 16)
    assert dev_matches > 0 and cpu_matches > 0, \
        f"no matches (dev={dev_matches}, cpu={cpu_matches}) — kernel broken?"
    print(json.dumps({
        "metric": "partitioned_pattern_throughput_1k_keys",
        "value": round(dev_eps),
        "unit": "events/sec",
        "vs_baseline": round(dev_eps / cpu_eps, 2),
    }))


if __name__ == "__main__":
    main()
