#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Current headline: filter-query throughput (BASELINE.json config 1) on the
TPU fast path vs. the sequential host interpreter (our measured CPU stand-in
for the single-JVM reference; see BASELINE.md — the reference publishes no
numbers, so vs_baseline is measured-TPU / measured-CPU-interpreter).

Will be upgraded to the north-star metric (events/sec/chip on partitioned
patterns, DEBS-2016 shape) as the batched NFA lands.
"""
import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def build_runtime(tpu: bool):
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core import build as build_mod
    from siddhi_tpu.interp.engine import InterpSingleQueryPlan

    mgr = SiddhiManager()
    app = """
    define stream StockStream (symbol string, price double, volume int);
    @info(name='q1')
    from StockStream[price > 100.0] select symbol, price insert into OutStream;
    """
    if not tpu:
        # force the sequential backend by monkey-scoping the planner choice
        orig = build_mod.plan_query

        def plan_seq(rt, q, default_name):
            name = q.name(default_name)
            from siddhi_tpu.core.planner import output_target_of
            return InterpSingleQueryPlan(name, rt, q, q.input,
                                         output_target_of(q))
        build_mod.plan_query = plan_seq
        try:
            rt = mgr.create_app_runtime(app)
        finally:
            build_mod.plan_query = orig
    else:
        rt = mgr.create_app_runtime(app)
    return rt


def run(rt, n_events: int, batch: int) -> float:
    """Returns events/sec pushed through the query."""
    from siddhi_tpu.core.batch import EventBatch
    from siddhi_tpu.core.schema import TIMESTAMP_DTYPE

    schema = rt.schemas["StockStream"]
    rng = np.random.default_rng(0)
    sym_codes = np.array([rt.strings.encode(s) for s in
                          ("IBM", "WSO2", "GOOG", "MSFT")], dtype=np.int32)
    counted = [0]
    rt.add_batch_callback("OutStream", lambda b: counted.__setitem__(0, counted[0] + b.n))
    rt.start()

    batches = []
    for start in range(0, n_events, batch):
        n = min(batch, n_events - start)
        cols = {
            "symbol": rng.choice(sym_codes, size=n),
            "price": rng.uniform(50, 150, size=n),
            "volume": rng.integers(1, 1000, size=n, dtype=np.int32),
        }
        ts = np.full(n, 1_700_000_000_000, dtype=TIMESTAMP_DTYPE)
        batches.append(EventBatch(schema, ts, cols, n))

    # warmup (compile)
    rt._pending.append(("StockStream", batches[0]))
    rt._drain()

    t0 = time.perf_counter()
    for b in batches:
        rt._pending.append(("StockStream", b))
        rt._drain()
    dt = time.perf_counter() - t0
    assert counted[0] > 0
    return n_events / dt


def main():
    # Host<->device transfer through the tunnel is the bottleneck for this
    # shallow query (~30 MB/s measured); use large micro-batches to amortize
    # the ~200 ms per-call latency.
    n = 2_000_000
    tpu_rt = build_runtime(tpu=True)
    tpu_eps = run(tpu_rt, n, 1 << 18)
    cpu_rt = build_runtime(tpu=False)
    cpu_eps = run(cpu_rt, min(n, 200_000), 8192)
    print(json.dumps({
        "metric": "filter_query_throughput",
        "value": round(tpu_eps),
        "unit": "events/sec",
        "vs_baseline": round(tpu_eps / cpu_eps, 2),
    }))


if __name__ == "__main__":
    main()
