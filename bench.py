#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Covers all five BASELINE.json configs under MATCHED conditions: device and
host modes process the SAME event tapes with the SAME batch sizes and event
counts (round-1/2 advisor finding).  The headline is config 4 (partitioned
3-state CEP pattern over 1k keys — the north-star workload); `vs_baseline`
is device events/sec over the sequential host interpreter on that config.
p99 detect-latency (event ingest -> match delivery, small batches) is
reported for the pattern configs.

The host interpreter is our measured stand-in for the single-JVM reference
engine (the reference publishes no numbers — BASELINE.md); the JSON also
carries `vs_production_claim` = headline / 300k events/sec, the reference
README's production-deployment claim, so the result can be read against a
real-world anchor.

Config 5 (1k concurrent mixed queries incl. not/within) fuses on device:
structurally identical queries become lanes of one batched kernel
(multi_query.py), so the 1000 matchers run as 4 kernels of 250 lanes.
"""
import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np

PROD_CLAIM_EPS = 300_000     # reference README.md:33-34 (~20B events/day)


def q4(x):
    """Quarter-step rounding: exactly representable in f32 (the device
    computes DOUBLE in f32 by default; keeps device/host tapes comparable)."""
    return np.round(np.asarray(x) * 4) / 4


# ---------------------------------------------------------------------------
# tape + harness
# ---------------------------------------------------------------------------

def make_tape(n_events, batch, keys=8, seed=0, dt_ms=1):
    """Runtime-independent event tape: symbol as key INDEX (encoded to the
    per-runtime string dictionary at feed time so device and host runtimes
    see identical events)."""
    rng = np.random.default_rng(seed)
    tape = []
    ts0 = 1_700_000_000_000
    for start in range(0, n_events, batch):
        n = min(batch, n_events - start)
        tape.append({
            "sym_idx": rng.integers(0, keys, size=n).astype(np.int32),
            "price": q4(rng.uniform(90.0, 130.0, size=n)),
            "volume": rng.integers(1, 1000, size=n).astype(np.int32),
            "ts": ts0 + np.arange(start, start + n, dtype=np.int64) * dt_ms,
            "seqs": np.arange(1 + start, 1 + start + n, dtype=np.int64),
            "n": n,
        })
    return tape


def _columnar(rt, stream, tape, keys):
    """Tape -> list of send_batch argument dicts (symbol pre-encoded to
    this runtime's string-dictionary codes — the public API accepts both
    str arrays and int32 codes)."""
    codes = np.array([rt.strings.encode(f"K{i}") for i in range(keys)],
                     dtype=np.int32)
    return [({"symbol": codes[t["sym_idx"]], "price": t["price"],
              "volume": t["volume"]}, t["ts"]) for t in tape]


def run_tape(app, stream, tape, keys, out_streams=("Out",), warm=1,
             repeats=1, stats_out=None):
    """Feed the tape through a fresh runtime via the PUBLIC columnar
    ingest path (InputHandler.send_batch).  The timed region is split
    into `repeats` equal segments measured independently (state carries
    across segments — a continuous stream); returns
    (median events/sec, matches in segment 1, [per-segment eps]).
    Callers compare segment-1 match counts across engines.
    `stats_out`: dict to fill with the runtime's device gauges (overlap
    ratio, queue depth — pipeline.py telemetry) before shutdown."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    counted = [0]
    for s in out_streams:
        rt.add_batch_callback(s, lambda b: counted.__setitem__(0, counted[0] + b.n))
    rt.start()
    h = rt.input_handler(stream)
    batches = _columnar(rt, stream, tape, keys)
    for cols, ts in batches[:warm]:
        h.send_batch(cols, ts)
    rt.flush()                   # pipelined plans: deliver warm leftovers
    warm_matches = counted[0]
    timed = batches[warm:]
    seg_len = max(1, len(timed) // repeats)
    eps_runs, seg1_matches = [], 0
    for r in range(repeats):
        seg = timed[r * seg_len:(r + 1) * seg_len]
        if not seg:
            break
        n_seg = sum(int(t[1].shape[0]) for t in seg)
        t0 = time.perf_counter()
        for cols, ts in seg:
            h.send_batch(cols, ts)
        rt.flush()               # barrier: all outputs delivered in-window
        eps_runs.append(n_seg / (time.perf_counter() - t0))
        if r == 0:
            seg1_matches = counted[0] - warm_matches
    if stats_out is not None:
        stats_out["device"] = rt.statistics().get("device", {})
        stats_out["placement"] = rt.statistics().get("placement", {})
    mgr.shutdown()
    return float(np.median(eps_runs)), seg1_matches, \
        [round(e) for e in eps_runs]


def _placement_summary(stats: dict) -> dict:
    """The per-config placement column (core/placement.py): device vs
    interpreter query counts + recorded interpreter demotions, so any
    future SILENT demotion shows up as a shifted count in the bench
    trajectory instead of only as a quietly slower eps."""
    pl = stats.get("placement") or {}
    if not pl:
        return {}
    return {"placement": {"device": pl.get("device", 0),
                          "interpreter": pl.get("interpreter", 0),
                          "interp_demotions": pl.get("interp_demotions",
                                                     0)}}


def _overlap_summary(stats: dict) -> dict:
    """Pull the pipeline gauges (pipeline.py) out of a stats_out dict:
    the max overlap_ratio across plans plus total dispatch count."""
    dev = stats.get("device", {})
    ratios = [m["overlap_ratio"] for m in dev.values()
              if "overlap_ratio" in m]
    return {
        "overlap_ratio": max(ratios) if ratios else None,
        "plans_with_overlap": len(ratios),
        "dispatches": sum(int(m.get("pipeline_dispatches", 0))
                          for m in dev.values()),
    }


def p99_latency(app, stream, tape, keys, out_stream="Out", warm=10):
    """Per-match detect latency: batch-ingest start -> callback delivery
    through the public path.  Returns p99 in ms (None if no matches).
    Warm batches run (and FLUSH) before the timed window so compiles and
    deferred pipeline deliveries land outside it — the treatment config 6
    got in PR 5; without the post-warm flush the largest frontier points
    could time a compile and report null/outlier p99s."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    lat: list = []
    t_start = [0.0]
    rt.add_batch_callback(
        out_stream,
        lambda b: lat.extend([(time.perf_counter() - t_start[0]) * 1e3] * b.n))
    rt.start()
    h = rt.input_handler(stream)
    batches = _columnar(rt, stream, tape, keys)
    for i, (cols, ts) in enumerate(batches):
        if i == warm:
            rt.flush()          # drain warm leftovers OUTSIDE the window
            lat.clear()
        t_start[0] = time.perf_counter()
        h.send_batch(cols, ts)
        if i >= warm:
            # unconditional per-batch flush inside the timed window:
            # every batch's deliveries land while ITS t_start is live,
            # so the histogram can neither attribute a batch's latency
            # to the next batch's clock nor end up empty (the frontier
            # "p99_ms": null failure shape, BENCH_r05)
            rt.flush()
    rt.flush()                  # deliver anything still in flight
    mgr.shutdown()
    return round(float(np.percentile(lat, 99)), 1) if lat else None


# ---------------------------------------------------------------------------
# the five BASELINE.json configs
# ---------------------------------------------------------------------------

STOCK = "define stream StockStream (symbol string, price double, volume int);\n"

C1 = STOCK + "@info(name='q') from StockStream[price > 100] select * insert into Out;\n"

C2 = STOCK + ("@info(name='q') from StockStream#window.length(1000) "
              "select avg(price) as ap insert into Out;\n")

# extra window-family row (VERDICT r4 #4): event-time tumbling buckets
STOCK_ET = ("define stream StockStream (symbol string, price double, "
            "volume int, et long);\n")
C2B = STOCK_ET + ("@info(name='q') from StockStream"
                  "#window.externalTimeBatch(et, 64) "
                  "select symbol, sum(price) as sp, count() as c "
                  "group by symbol insert into Out;\n")

C3 = STOCK + ("@info(name='q') from every e1=StockStream[price > 100] -> "
              "e2=StockStream[price > e1.price] within 1 sec "
              "select e1.price as p1, e2.price as p2 insert into Out;\n")

# static-transition variant of config 3 (no capture-dependent filter):
# the shape the bit-packed multi-stride "dfa" plan family accepts — used
# for the per-family kernel roofline sweep
C3S = STOCK + ("@info(name='q') from every e1=StockStream[price > 100] -> "
               "e2=StockStream[price < 95] within 1 sec "
               "select e1.price as p1, e2.price as p2 insert into Out;\n")

C4 = STOCK + """
partition with (symbol of StockStream)
begin
  @info(name='q')
  from every e1=StockStream[price > 100] -> e2=StockStream[price > e1.price]
    -> e3=StockStream[price > e2.price] within 10 sec
  select e1.price as p1, e2.price as p2, e3.price as p3 insert into Out;
end;
"""


def c5_app(n_queries=1000):
    """1k concurrent mixed pattern/sequence queries (incl. not/within) over
    one shared input stream.  Thresholds sit in the tape's upper tail so
    per-query pending-match populations stay realistic (the matcher — ours
    AND the reference's — is O(pending x events) on this shape)."""
    parts = ["@app:playback\n" + STOCK]   # historical tape: event-time
    for i in range(n_queries):            # deadlines fire in-scan, not via
        lo = 123 + (i % 6)                # the wall-clock pump
        shape = i % 4
        if shape == 0:
            parts.append(
                f"@info(name='q{i}') from every e1=StockStream[price > {lo}] -> "
                f"e2=StockStream[price > e1.price] within 1 sec "
                f"select e1.price as p1, e2.price as p2 insert into Out{i % 16};")
        elif shape == 1:
            parts.append(
                f"@info(name='q{i}') from e1=StockStream[price > {lo}], "
                f"e2=StockStream[price > e1.price] "
                f"select e1.price as p1, e2.price as p2 insert into Out{i % 16};")
        elif shape == 2:
            parts.append(
                f"@info(name='q{i}') from e1=StockStream[price > {lo + 1}] -> "
                f"not StockStream[price < {lo - 30}] for 500 milliseconds "
                f"select e1.price as p1 insert into Out{i % 16};")
        else:
            parts.append(
                f"@info(name='q{i}') from every e1=StockStream[price > {lo}] -> "
                f"e2=StockStream[price > e1.price] -> "
                f"e3=StockStream[price > e2.price] within 2 sec "
                f"select e1.price as p1, e3.price as p3 insert into Out{i % 16};")
    return "\n".join(parts) + "\n"


DEV = {"filters": "@app:deviceFilters('auto')\n",
       "windows": "@app:deviceWindows('auto')\n",
       "patterns": "@app:devicePatterns('always')\n"}
HOST = {"filters": "@app:deviceFilters('never')\n",
        "windows": "@app:deviceWindows('never')\n",
        "patterns": "@app:devicePatterns('never')\n"}
# throughput mode: overlap batch i's device->host pull with batch i+1..i+3
# (outputs deliver late; the flush barrier inside the timed window drains
# them).  Latency runs do NOT use this — p99 is measured unpipelined.
PIPE = "@app:devicePipeline(3)\n"


STREAM = "StockStream"


def bench_config(name, dev_app, host_app, n, batch, keys=8, dt_ms=1,
                 out_streams=("Out",), warm=1, check_matches=True,
                 latency=False, lat_dev_app=None, repeats=3):
    """Matched-conditions measurement; returns a result dict.
    Device eps = median of `repeats` independently-timed tape segments
    (VERDICT r4 weak #1: repeat-and-median inside the bench, not across
    hand-picked runs).  The host interpreter runs ONE segment (it is the
    slow, low-variance side); zero-false-match compares segment-1 counts
    (both engines consume the identical segment-1 event stream).
    `lat_dev_app` (default dev_app) measures p99 — throughput apps may
    enable output pipelining, which must NOT be active for latency."""
    tape = make_tape(n * repeats + warm * batch, batch, keys=keys,
                     dt_ms=dt_ms)
    dev_stats: dict = {}
    dev_eps, dev_matches, dev_runs = run_tape(
        dev_app, STREAM, tape, keys, out_streams, warm, repeats=repeats,
        stats_out=dev_stats)
    # host consumes exactly the device's segment 1 (seg_len batches), so
    # the zero-false-match counts compare identical event streams
    seg_len = max(1, (len(tape) - warm) // repeats)
    host_tape = tape[:warm + seg_len]
    if host_app == dev_app:        # same engine both modes: one measurement
        host_eps, host_matches = dev_eps, dev_matches
    else:
        host_eps, host_matches, _ = run_tape(host_app, STREAM, host_tape,
                                             keys, out_streams, warm)
    if check_matches:
        assert dev_matches > 0, f"{name}: no matches — kernel broken?"
        assert dev_matches == host_matches, \
            (f"{name}: match-count mismatch device={dev_matches} "
             f"host={host_matches} — zero-false-match check FAILED")
    res = {
        "device_eps": round(dev_eps),
        "device_eps_runs": dev_runs,
        "host_eps": round(host_eps),
        "speedup": round(dev_eps / host_eps, 2),
        "events": n, "batch": batch, "matches": dev_matches,
    }
    res.update({k: v for k, v in _overlap_summary(dev_stats).items()
                if v is not None})
    res.update(_placement_summary(dev_stats))
    if latency:
        lat_tape = make_tape(2048 * 16, 2048, keys=keys, dt_ms=dt_ms)
        lat_app = lat_dev_app or dev_app
        res["p99_detect_ms"] = p99_latency(lat_app, STREAM, lat_tape, keys,
                                           warm=6)
        res["host_p99_detect_ms"] = p99_latency(host_app, STREAM, lat_tape,
                                                keys, warm=6)
    return res


def _wrap_kernel_factory(obj, name, store):
    """Wrap a jitted-block factory so the last (fn, args) pair is kept
    for device-resident re-invocation (kernel-only probes)."""
    orig = getattr(obj, name)

    def factory(*a, **k):
        fn = orig(*a, **k)

        def wrapped(*fa):
            store["fn"], store["args"] = fn, fa
            return fn(*fa)
        return wrapped
    setattr(obj, name, factory)


def _capture_pattern_kernels(plan, store):
    """Instrument EVERY pattern execution family's block factory on one
    plan (sequential NFAKernel, chunked-halo per-K kernels, and the
    scan/dfa parallel kernels) so kernel-only probes capture whichever
    family the plan actually dispatches."""
    _wrap_kernel_factory(plan.kernel, "block_fn", store)
    orig_ck = plan._chunk_kernel

    def chunk_kernel(K):
        kern = orig_ck(K)
        if not getattr(kern, "_bench_wrapped", False):
            _wrap_kernel_factory(kern, "block_fn", store)
            kern._bench_wrapped = True
        return kern
    plan._chunk_kernel = chunk_kernel
    orig_pk = plan._parallel_kernel

    def par_kernel():
        kern = orig_pk()
        if not getattr(kern, "_bench_wrapped", False):
            _wrap_kernel_factory(kern, "block_fn", store)
            kern._bench_wrapped = True
        return kern
    plan._parallel_kernel = par_kernel


def kernel_p99_ms(app, batch, keys=8, dt_ms=1, chains=8, per=16):
    """Kernel-COMPUTE-only detect latency at this micro-batch size: the
    captured jitted NFA block re-runs in `chains` chains of `per` calls on
    device-resident inputs; each chain's per-call mean is one sample
    (amortizes the tunnel's per-sync RTT), p99 over samples.  This is the
    latency a locally-attached chip adds per micro-batch — reported next
    to the end-to-end p99, which rides the tunnel (VERDICT r4 weak #3)."""
    import jax
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan

    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.start()
    h = rt.input_handler(STREAM)
    store: dict = {}
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    _capture_pattern_kernels(plan, store)

    tape = make_tape(2 * batch, batch, keys=keys, dt_ms=dt_ms)
    for cols, ts in _columnar(rt, STREAM, tape, keys):
        h.send_batch(cols, ts)
    rt.flush()
    if "fn" not in store:
        mgr.shutdown()
        return None
    fn, args = store["fn"], store["args"]
    jax.block_until_ready(fn(*args))        # warm
    samples = []
    for _ in range(chains):
        t0 = time.perf_counter()
        jax.block_until_ready([fn(*args) for _ in range(per)])
        samples.append((time.perf_counter() - t0) * 1e3 / per)
    mgr.shutdown()
    return round(float(np.percentile(samples, 99)), 2)


def frontier(dev_app, host_app=None, keys=8, dt_ms=1,
             batches=(2048, 16384), deadline=None):
    """Latency/throughput frontier: micro-batch size vs (end-to-end eps,
    end-to-end p99, kernel-only p99), with the HOST engine measured at
    the SAME operating point for the matched comparison (VERDICT r4 #5).
    Warm batches absorb compiles so the measured window reflects the
    steady state; eps = median of 3 segments.  Points past `deadline`
    are skipped — a partial frontier beats a bench the driver kills
    mid-run."""
    pts = []
    for b in batches:
        if deadline is not None and time.perf_counter() > deadline:
            pts.append({"batch": b, "skipped": "bench time budget"})
            continue
        n_seg = 4 * b
        tape = make_tape(3 * n_seg + 4 * b, b, keys=keys, dt_ms=dt_ms)
        eps, _m, _runs = run_tape(dev_app, STREAM, tape, keys, ("Out",),
                                  warm=4, repeats=3)
        lat_tape = make_tape(b * 16, b, keys=keys, dt_ms=dt_ms)
        p99 = p99_latency(dev_app, STREAM, lat_tape, keys, warm=4)
        kp99 = kernel_p99_ms(dev_app, b, keys=keys, dt_ms=dt_ms)
        pt = {"batch": b, "eps": round(eps), "p99_ms": p99,
              "kernel_p99_ms": kp99}
        if host_app is not None:
            htape = make_tape(2 * b + 4 * b, b, keys=keys, dt_ms=dt_ms)
            heps, _hm, _hr = run_tape(host_app, STREAM, htape, keys,
                                      ("Out",), warm=1)
            hlat = make_tape(b * 8, b, keys=keys, dt_ms=dt_ms)
            pt["host_eps"] = round(heps)
            pt["host_p99_ms"] = p99_latency(host_app, STREAM, hlat, keys,
                                            warm=2)
        pts.append(pt)
    return pts


JOIN_APP = """
define stream L (symbol string, price double, volume int);
define stream R (symbol string, price double, volume int);
@info(name='q') from L#window.length(1024) as a join R#window.length(1024) as b
on a.symbol == b.symbol and a.price > b.price
select a.symbol as s, a.price as lp, b.price as rp insert into Out;
"""


def bench_join(n, batch, keys=1000, repeats=3):
    """Config 6 (extra, VERDICT r4 #2): stream-stream window join.
    Each side receives n/2 events; device = dense probe-grid kernel,
    host = the interp join (per-event probe of the retained window).
    Also measured: the same device engine UNPIPELINED (depth 0), so the
    eps delta attributable to the async dispatch pipeline is explicit
    and cross-checked against the overlap_ratio telemetry."""
    from siddhi_tpu import SiddhiManager

    def run(head, total, measure_repeats, pipe=True, stats_out=None):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(head + PIPE + JOIN_APP
                                    if "never" not in head and pipe
                                    else head + JOIN_APP)
        counted = [0]
        rt.add_batch_callback(
            "Out", lambda b: counted.__setitem__(0, counted[0] + b.n))
        rt.start()
        hl, hr = rt.input_handler("L"), rt.input_handler("R")
        codes = np.array([rt.strings.encode(f"K{i}") for i in range(keys)],
                         dtype=np.int32)
        rng = np.random.default_rng(0)
        half = batch // 2
        ts0 = 1_700_000_000_000
        eps_runs, seg1 = [], 0
        n_segs = measure_repeats
        per_seg = total // n_segs
        ev_done = 0
        # warm OUTSIDE the timed window: the first timed segment used to
        # pay the probe-grid compiles (BENCH_r05 config-6 run 1: 778 eps
        # vs ~66k warm) — identical warm tape for every engine, so the
        # match-count cross-check still compares identical streams
        for _ in range(2):
            for h in (hl, hr):
                h.send_batch(
                    {"symbol": codes[rng.integers(0, keys, half)],
                     "price": q4(rng.uniform(90, 130, half)),
                     "volume": rng.integers(1, 9, half).astype(np.int32)},
                    timestamps=ts0 + np.arange(ev_done, ev_done + half))
                ev_done += half
            rt.flush()
        warm_m = counted[0]
        for s in range(n_segs):
            t0 = time.perf_counter()
            for _ in range(per_seg // batch):
                for h in (hl, hr):
                    h.send_batch(
                        {"symbol": codes[rng.integers(0, keys, half)],
                         "price": q4(rng.uniform(90, 130, half)),
                         "volume": rng.integers(1, 9, half).astype(np.int32)},
                        timestamps=ts0 + np.arange(ev_done,
                                                   ev_done + half))
                    ev_done += half
            rt.flush()      # segment barrier (pipelined plans drain here)
            eps_runs.append(per_seg / (time.perf_counter() - t0))
            if s == 0:
                seg1 = counted[0] - warm_m
        if stats_out is not None:
            stats_out["device"] = rt.statistics().get("device", {})
            stats_out["placement"] = rt.statistics().get("placement", {})
        mgr.shutdown()
        return float(np.median(eps_runs)), seg1, [round(e) for e in eps_runs]

    stats = {}
    dev_eps, dev_m, dev_runs = run("", n * repeats, repeats,
                                   stats_out=stats)
    # same segments + median so compile amortization matches the
    # pipelined run — the delta is overlap, not warm-up accounting
    unp_eps, unp_m, _ = run("", n * repeats, repeats, pipe=False)
    host_eps, host_m, _ = run("@app:deviceJoins('never')\n", n, 1)
    assert dev_m == host_m == unp_m and dev_m > 0, \
        f"join match mismatch device={dev_m} host={host_m} unpiped={unp_m}"
    return {"device_eps": round(dev_eps), "device_eps_runs": dev_runs,
            "host_eps": round(host_eps),
            "speedup": round(dev_eps / host_eps, 2),
            "unpipelined_eps": round(unp_eps),
            "overlap_speedup": round(dev_eps / unp_eps, 2),
            **_overlap_summary(stats),
            "events": n, "batch": batch, "matches": dev_m,
            "note": "stream-stream length-window join, 1024x1024 windows, "
                    "1000 keys, equality + residual condition"}


# ---------------------------------------------------------------------------
# config 8: multi-plan overlap (the unified dispatch pipeline measured
# directly — N device plans share one input stream; runtime._drain
# dispatches all of them before materializing any)
# ---------------------------------------------------------------------------

MULTI_PLAN_APP = (STOCK +
    "@info(name='w1') from StockStream#window.length(512) "
    "select symbol, sum(price) as s group by symbol insert into Out;\n"
    "@info(name='w2') from StockStream#window.length(64) "
    "select max(price) as hi, min(price) as lo insert into Out2;\n"
    "@info(name='w3') from StockStream#window.lengthBatch(256) "
    "select avg(price) as m insert into Out3;\n"
    "@info(name='f1') from StockStream[price > 120] "
    "select symbol, price insert into Out4;\n")
MULTI_PLAN_OUTS = ("Out", "Out2", "Out3", "Out4")


def bench_overlap(n=1 << 16, batch=1 << 13, repeats=3, depth=3):
    """Pipelined (depth-D deferred pulls + cross-plan dispatch rounds)
    vs unpipelined, SAME tape and plans; asserts identical match counts
    and reports the eps delta next to the overlap_ratio telemetry that
    explains it."""
    head = DEV["windows"] + DEV["filters"]
    tape = make_tape(n * repeats + batch, batch)
    unp_eps, unp_m, _ = run_tape(head + MULTI_PLAN_APP, STREAM, tape, 8,
                                 MULTI_PLAN_OUTS, warm=1, repeats=repeats)
    stats = {}
    pip_eps, pip_m, pip_runs = run_tape(
        f"@app:devicePipeline({depth})\n" + head + MULTI_PLAN_APP, STREAM,
        tape, 8, MULTI_PLAN_OUTS, warm=1, repeats=repeats,
        stats_out=stats)
    assert pip_m == unp_m and pip_m > 0, \
        f"overlap config match mismatch piped={pip_m} unpiped={unp_m}"
    return {"device_eps": round(pip_eps), "device_eps_runs": pip_runs,
            "unpipelined_eps": round(unp_eps),
            "host_eps": round(unp_eps),
            "speedup": round(pip_eps / unp_eps, 2),
            "overlap_speedup": round(pip_eps / unp_eps, 2),
            **_overlap_summary(stats),
            "events": n, "batch": batch, "matches": pip_m,
            "note": f"3 device windows + 1 filter on one stream, "
                    f"devicePipeline({depth}) vs depth 0 — speedup here "
                    f"is overlap, not kernel changes"}


def kernel_eps(app, family, batch, keys=8, dt_ms=1, reps=6, info=None):
    """Device-COMPUTE-only events/sec (VERDICT r4 weak #2): feed one real
    batch through the engine to compile + capture the jitted kernel call
    and its device-resident arguments, then re-invoke the kernel `reps`
    times on those arguments and time with block_until_ready.  Host<->
    device transfers, output materialization, and the host engine layer
    are excluded; dispatch overhead is amortized by chaining the calls.
    This is the "locally-attached chips" roofline next to the end-to-end
    numbers, which ride the tunnel (~100 ms fixed pull, 10-25 MB/s)."""
    import jax
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    from siddhi_tpu.core.planner import FilterProjectPlan

    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.start()
    h = rt.input_handler(STREAM)
    store: dict = {}

    plans = rt._plans
    if family == "filter":
        plan = next(p for p in plans if isinstance(p, FilterProjectPlan))
        orig_step = plan._step

        def step(*a):
            store["fn"], store["args"] = orig_step, a
            return orig_step(*a)
        plan._step = step
        count = lambda args: int(next(iter(args[0].values())).shape[0])
    elif family == "window":
        plan = next(p for p in plans
                    if p.__class__.__name__ == "DeviceWindowAggPlan")
        _wrap_kernel_factory(plan, "_step_fn", store)
        count = lambda args: int(np.asarray(args[1]["__nvalid__"]))
    elif family == "pattern":
        plan = next(p for p in plans if isinstance(p, DevicePatternPlan))
        _capture_pattern_kernels(plan, store)
        store["plan_family"] = plan.family

        def count(args):
            ev = args[1]
            if "__nev__" in ev:
                # lane-vmapped blocks carry per-lane counts (L,)
                return int(np.asarray(ev["__nev__"]).sum())
            return int(np.asarray(ev["__valid__"]).sum())
    else:
        raise ValueError(family)

    tape = make_tape(2 * batch, batch, keys=keys, dt_ms=dt_ms)
    for cols, ts in _columnar(rt, STREAM, tape, keys):
        h.send_batch(cols, ts)
    rt.flush()
    if "fn" not in store:
        mgr.shutdown()
        if info is not None and "plan_family" in store:
            info["plan_family"] = store["plan_family"]
        return None
    fn, args = store["fn"], store["args"]
    n_call = count(args)
    threads_state = len(args) == 2 and family in ("window", "pattern")

    def chain(k):
        if family == "window":
            st = args[0]
            outs = []
            for _ in range(k):
                res = fn(st, args[1])
                st = res["nst"]
                outs.append(res)
            return outs
        if family == "pattern" and threads_state and "__nev__" not in args[1]:
            st, outs = args[0], []
            for _ in range(k):
                st, out = fn(st, args[1])
                outs.append(out)
            return outs
        return [fn(*args) for _ in range(k)]

    jax.block_until_ready(chain(2))          # warm (compile cache hit)
    t0 = time.perf_counter()
    jax.block_until_ready(chain(reps))
    dt = time.perf_counter() - t0
    mgr.shutdown()
    if info is not None and "plan_family" in store:
        info["plan_family"] = store["plan_family"]
    return round(n_call * reps / dt)


def latency_demo(dev_app, host_app, target_ms=10, seconds=6.0,
                 keys=8, rate=5_000, capacity=2048):
    """@app:maxBatchLatency demo (VERDICT r4 #5): a producer paced at
    `rate` events/sec; builders auto-flush when the OLDEST buffered
    event has waited target_ms (or at capacity), so micro-batch size
    adapts to the arrival rate.  At a rate ABOVE the host interpreter's
    capacity the host backlog (and its detect latency) grows without
    bound while the device engine holds a steady p99 — the
    latency-under-load story.  Reports achieved events/sec and p99
    detect latency (first-buffered-event -> match delivery) for both
    engines under the identical harness."""
    from siddhi_tpu import SiddhiManager

    def run(app):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(app)
        rt.batch_capacity = capacity    # both engines: same batch bound
        lat: list = []
        t0_batch = [0.0]
        rt.add_batch_callback(
            "Out", lambda b: lat.extend(
                [(time.perf_counter() - t0_batch[0]) * 1e3] * b.n))
        rt.start()
        h = rt.input_handler(STREAM)
        rng = np.random.default_rng(3)
        syms = rng.integers(0, keys, size=1 << 16)
        prices = q4(rng.uniform(90, 130, size=1 << 16))
        ts0 = 1_700_000_000_000
        i = 0
        t_origin = time.perf_counter()

        def send_one():
            nonlocal i
            while i > (time.perf_counter() - t_origin) * rate:
                pass                            # pace to `rate` events/sec
            j = i % (1 << 16)
            # 25 ms event spacing keeps the within-1s replay tail ~40
            # events, so latency-capped micro-flushes stay small
            h.send((f"K{syms[j]}", float(prices[j]), 1),
                   timestamp=ts0 + i * 25)
            # the runtime tracks first-append time per builder under its
            # lock — read it rather than re-deriving (review r5: a
            # pre-send check races the scheduler's auto-flush)
            t0_batch[0] = rt._builder_t0.get(STREAM, t0_batch[0])
            i += 1

        # prewarm ladder: exercise the flush-size regimes the timed
        # window can produce (shape buckets are sticky, but a ~10 s
        # tunnel compile landing mid-measurement voids the p99), then
        # settle until flushes run compile-free
        for _round in range(2):
            for size in (17, 60, 250, 1000, capacity):
                for _ in range(size):
                    send_one()
                rt.flush()
        settle_end = time.perf_counter() + 20.0
        while time.perf_counter() < settle_end:
            t0f = time.perf_counter()
            for _ in range(17):
                send_one()
            rt.flush()
            if time.perf_counter() - t0f < 0.5:
                break               # flush ran warm: shapes are compiled
        lat.clear()
        t_timed = time.perf_counter()
        sent_at_timed = i
        t_end = t_timed + seconds
        while time.perf_counter() < t_end:
            send_one()
        rt.flush()
        dt = time.perf_counter() - t_timed
        eps = (i - sent_at_timed) / max(dt, 1e-9)
        mgr.shutdown()
        p99 = round(float(np.percentile(lat, 99)), 1) if lat else None
        return round(eps), p99

    lat_head = f"@app:maxBatchLatency('{target_ms} ms')\n"
    dev_eps, dev_p99 = run(lat_head + dev_app)
    host_eps, host_p99 = run(lat_head + host_app)
    return {"target_ms": target_ms, "offered_rate_eps": rate,
            "device_eps": dev_eps, "device_p99_ms": dev_p99,
            "host_eps": host_eps, "host_p99_ms": host_p99,
            "note": "@app:maxBatchLatency adapts micro-batches to the "
                    "arrival rate: p99 detect ~= target + the engine's "
                    "per-flush floor.  The device floor HERE is the "
                    "~100 ms tunneled-TPU pull; the frontier's "
                    "kernel_p99_ms column shows the locally-attached "
                    "floor is single-digit ms"}


def _mark(label, t0):
    print(f"[bench {time.perf_counter() - t0:6.1f}s] {label}",
          file=sys.stderr, flush=True)


def _safe(label, fn, default=None):
    """Run one optional bench section; a failure degrades that section to
    `default` instead of killing the run — the final stdout line must
    ALWAYS be the machine-parseable summary (BENCH "parsed": null)."""
    try:
        return fn()
    except Exception as e:
        print(f"[bench] section {label!r} failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        return default


# ---------------------------------------------------------------------------
# --autotune: tuner-driven frontier sweep + online SLO-controller demo
# (core/autotune.py — see docs/AUTOTUNING.md)
# ---------------------------------------------------------------------------

def _autotune_tape(n, keys=8, dt_ms=1, seed=0):
    """(cols, ts) recorded-tape form the Autotuner consumes: symbol as a
    str array (the public send_batch path encodes it)."""
    rng = np.random.default_rng(seed)
    syms = np.asarray([f"K{i}" for i in rng.integers(0, keys, n)])
    ts0 = 1_700_000_000_000
    return ({"symbol": syms, "price": q4(rng.uniform(90.0, 130.0, n)),
             "volume": rng.integers(1, 1000, n).astype(np.int32)},
            ts0 + np.arange(n, dtype=np.int64) * dt_ms)


def autotune_bench(smoke=False):
    """Tuner-driven geometry sweep over configs 3/4/6 (the hand-tuned
    BENCH geometries ride in every grid, so a warm winner matches or
    beats them by construction) reporting before/after eps + p99 deltas,
    plus the @app:latencySLO('25ms') controller demo under paced load.
    The tuner asserts output-invariance across every candidate — a
    geometry that changed results would raise, not win."""
    from siddhi_tpu.core.autotune import Autotuner, Geometry

    t0 = time.perf_counter()
    tuner = Autotuner()
    out = {"configs": {}}
    if smoke:
        specs = {"3_sequence": {
            "app": DEV["patterns"] + C3, "keys": 8,
            "hand": Geometry(batch=1 << 11, pipeline_depth=3),
            "grid": [Geometry(batch=1 << 11, pipeline_depth=3),
                     Geometry(batch=1 << 12, pipeline_depth=0)]}}
    else:
        specs = {
            "3_sequence": {
                "app": DEV["patterns"] + C3, "keys": 8,
                "hand": Geometry(batch=1 << 17, pipeline_depth=3),
                "grid": [Geometry(batch=1 << 15, pipeline_depth=0),
                         Geometry(batch=1 << 15, pipeline_depth=3),
                         Geometry(batch=1 << 17, pipeline_depth=0),
                         Geometry(batch=1 << 17, pipeline_depth=3),
                         Geometry(batch=1 << 17, pipeline_depth=3,
                                  chunk_lanes=128),
                         # plan-family axis: the sweep's output-invariance
                         # check doubles as a cross-family differential
                         Geometry(batch=1 << 17, pipeline_depth=3,
                                  plan_family="chunk"),
                         Geometry(batch=1 << 17, pipeline_depth=3,
                                  plan_family="scan"),
                         Geometry(batch=1 << 17, pipeline_depth=3,
                                  plan_family="seq")]},
            "4_partitioned_1k": {
                "app": ("@app:partitionCapacity(1000)\n"
                        "@app:deviceSlots(32)\n") + C4,
                "keys": 1000,
                "hand": Geometry(batch=1 << 18, pipeline_depth=0),
                "grid": [Geometry(batch=1 << 16, pipeline_depth=0),
                         Geometry(batch=1 << 17, pipeline_depth=0),
                         Geometry(batch=1 << 18, pipeline_depth=0),
                         # plan-family axis over the PARTITIONED lanes
                         # (ISSUE 13): the lane-vmapped scan family vs
                         # the per-key sequential state kernel — the
                         # sweep's output-invariance check doubles as
                         # the partitioned cross-family differential
                         Geometry(batch=1 << 18, pipeline_depth=0,
                                  plan_family="scan"),
                         Geometry(batch=1 << 18, pipeline_depth=0,
                                  plan_family="seq")]},
            "6_join": {
                "app": JOIN_APP, "keys": 1000,
                "hand": Geometry(batch=2048, pipeline_depth=3),
                "grid": [Geometry(batch=2048, pipeline_depth=0),
                         Geometry(batch=2048, pipeline_depth=3),
                         Geometry(batch=4096, pipeline_depth=3)]},
        }
    all_ok = True
    for name, spec in specs.items():
        keys = spec["keys"]
        grid = list(spec["grid"])
        if spec["hand"].to_dict() not in [g.to_dict() for g in grid]:
            grid.append(spec["hand"])
        # tape = 2x the LARGEST candidate batch, warm = that batch:
        # every candidate (and the hand baseline) warms through at
        # least one full batch of its own geometry, so the timed
        # window is compile-free and the before/after comparison is
        # warm-for-warm (not a warmup artifact)
        maxb = max(g.batch for g in grid)
        n, warm = 2 * maxb, maxb
        if name == "6_join":
            tapes = {"L": _autotape_join(n, keys, 0),
                     "R": _autotape_join(n, keys, 1)}
        else:
            tapes = {STREAM: _autotune_tape(n, keys=keys)}
        res = tuner.tune(spec["app"], tapes=tapes, grid=grid,
                         warm_events=warm, force=False,
                         log=lambda m: print(f"[autotune] {name}: {m}",
                                             file=sys.stderr, flush=True))
        # before/after come from the SWEEP's own candidate scores (hand
        # rides in every grid): both sides measured under identical
        # conditions, so the delta is geometry, not run-to-run noise.
        # A warm cache skipped the sweep — re-measure both once, with a
        # noise guard (the winner then usually IS the hand geometry).
        by_geo = {json.dumps(c["geometry"], sort_keys=True): c
                  for c in res.get("candidates", [])}
        hand_key = json.dumps(spec["hand"].to_dict(), sort_keys=True)
        win_key = json.dumps(res["winner"], sort_keys=True)
        if hand_key in by_geo and win_key in by_geo:
            before, after = by_geo[hand_key], by_geo[win_key]
            ok = after["matches"] == before["matches"] and \
                after["eps"] >= before["eps"]      # winner maximized eps
        else:
            before = tuner._measure(spec["app"], spec["hand"], tapes, n,
                                    warm, None)
            after = tuner._measure(spec["app"],
                                   Geometry.from_dict(res["winner"]),
                                   tapes, n, warm, None)
            ok = after["matches"] == before["matches"] and \
                after["eps"] >= 0.8 * before["eps"]   # noise guard
        all_ok = all_ok and ok
        out["configs"][name] = {
            "winner": res["winner"], "from_cache": res["from_cache"],
            "candidates": res.get("candidates", []),
            "before": {"geometry": spec["hand"].to_dict(),
                       "eps": before["eps"], "p99_ms": before["p99_ms"]},
            "after": {"geometry": res["winner"], "eps": after["eps"],
                      "p99_ms": after["p99_ms"]},
            "eps_delta": round(after["eps"] / max(before["eps"], 1), 3),
            "matches_identical": after["matches"] == before["matches"],
            "pass": ok}
        _mark(f"autotune {name}: x{out['configs'][name]['eps_delta']} "
              f"({'cache' if res['from_cache'] else 'sweep'})", t0)
    out["slo"] = slo_demo(target_ms=25, seconds=2.0 if smoke else 6.0,
                          rate=2000 if smoke else 5000)
    out["pass"] = all_ok and out["slo"]["pass"]
    return out


def _autotape_join(n, keys, seed):
    rng = np.random.default_rng(seed)
    syms = np.asarray([f"K{i}" for i in rng.integers(0, keys, n)])
    ts0 = 1_700_000_000_000
    return ({"symbol": syms, "price": q4(rng.uniform(90, 130, n)),
             "volume": rng.integers(1, 9, n).astype(np.int32)},
            ts0 + np.arange(n, dtype=np.int64))


def slo_demo(target_ms=25, rate=5000, seconds=6.0, keys=8):
    """@app:latencySLO under paced load: the AIMD controller must hold
    the p99 detect-latency target within 2x while sustaining at least
    the offered rate (the latency_demo host throughput anchor).  Same
    producer harness as latency_demo; the controller adapts the
    micro-batch/flush cadence itself — no hand-set batch knobs."""
    from siddhi_tpu import SiddhiManager

    app = (f"@app:latencySLO('{target_ms} ms')\n" + DEV["patterns"] + C3)
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    lat: list = []
    t0_batch = [0.0]
    rt.add_batch_callback(
        "Out", lambda b: lat.extend(
            [(time.perf_counter() - t0_batch[0]) * 1e3] * b.n))
    rt.start()
    h = rt.input_handler(STREAM)
    rng = np.random.default_rng(3)
    syms = rng.integers(0, keys, size=1 << 16)
    prices = q4(rng.uniform(90, 130, size=1 << 16))
    ts0 = 1_700_000_000_000
    i = 0
    t_origin = time.perf_counter()

    def send_one():
        nonlocal i
        # SLEEP-paced (not a busy spin): a hot spin loop starves the
        # scheduler/flush threads of the GIL and the measured latency
        # reads as engine tail when it is producer contention
        while i > (time.perf_counter() - t_origin) * rate:
            time.sleep(0.0005)
        j = i % (1 << 16)
        h.send((f"K{syms[j]}", float(prices[j]), 1), timestamp=ts0 + i * 25)
        t0_batch[0] = rt._builder_t0.get(STREAM, t0_batch[0])
        i += 1

    # paced warmup in the SAME regime as the timed window: the
    # controller converges and every flush-size shape bucket the
    # steady state produces compiles here, not inside the measurement
    warm_end = time.perf_counter() + max(2 * seconds, 8.0)
    while time.perf_counter() < warm_end:
        send_one()
    rt.flush()
    lat.clear()
    rt.slo.total.reset()       # p99 over the timed window only
    t_timed = time.perf_counter()
    sent0 = i
    t_origin = t_timed - i / rate          # keep the pacing continuous
    while time.perf_counter() < t_timed + seconds:
        send_one()
    rt.flush()
    eps = (i - sent0) / max(time.perf_counter() - t_timed, 1e-9)
    # the headline p99 is the ENGINE-side per-batch end-to-end latency
    # (first buffered event -> batch fully processed) the controller
    # itself observes — measured inside the runtime, immune to the
    # stale-t0 approximation of the callback clock (kept as a
    # reference column)
    p99_s = rt.slo.total.percentile(99)
    slo_m = rt.slo.metrics()
    mgr.shutdown()
    p99 = round(p99_s * 1e3, 1) if p99_s is not None else None
    cb_p99 = round(float(np.percentile(lat, 99)), 1) if lat else None
    held = p99 is not None and p99 <= 2 * target_ms
    return {"target_ms": target_ms, "offered_rate_eps": rate,
            "eps": round(eps), "p99_ms": p99, "p99_callback_ms": cb_p99,
            "held_within_2x": held, "sustained": eps >= 0.9 * rate,
            "controller": slo_m,
            "pass": bool(held and eps >= 0.9 * rate)}


def trace_breakdown(app, n_batches=16, batch=2048, keys=8,
                    trace_out="bench_trace.json"):
    """Per-stage breakdown of end-to-end detect latency (config 3 shape):
    run the tape with statistics + the flight recorder on, reset after
    warm-up (so steady state is measured, not compiles), then read the
    stage histograms back.  The warm-up pass covers the ENTIRE tape —
    match-buffer growth (the (T, M) retry shape) only triggers on the
    batch whose match volume overflows the first-flush guess, so a
    prefix warm-up would leave a fresh ~1s compile inside the timed
    region and misattribute the breakdown to it; the timed pass replays
    the tape shifted forward past the `within` horizon (stale partials
    expire, time stays monotonic, every kernel shape is already cached).
    `coverage` is the fraction of the timed wall clock the named stage
    spans account for — the observability acceptance bar (>= 0.9 means
    regressions are attributable); the remainder is python dispatch glue
    between spans.  Valid because the traced app is synchronous (no
    @app:async): all spans run on the caller thread, so their seconds
    are disjoint slices of the wall clock — an async app would overlap
    ingest with dispatch and the sum would overstate.  Also exports the
    recorder as Chrome trace_event JSON (`trace_out`).

    Since ISSUE 17 the run carries `@app:profile('all')` and the
    kernel-vs-host split comes from the phase profiler's blocked-kernel
    attribution (core/profiler.py) instead of the stage-histogram
    approximation — same keys (`kernel_share`, `host_dispatch_share`),
    better numerator: the old `kernel` stage span measured dispatch-call
    wall, which under async dispatch is NOT device execution time.  The
    full per-phase report lands under `profile`."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_app_runtime("@app:profile('all')\n" + app)
    rt.enable_stats(True)
    rt.stats.tracer.enabled = True
    delivered = [0]
    rt.add_batch_callback(
        "Out", lambda b: delivered.__setitem__(0, delivered[0] + b.n))
    rt.start()
    h = rt.input_handler(STREAM)
    tape = make_tape(n_batches * batch, batch, keys=keys)
    batches = _columnar(rt, STREAM, tape, keys)
    for cols, ts in batches:
        h.send_batch(cols, ts)
    rt.flush()
    rt.stats.reset()                 # steady state only: compiles are done
    if rt.profiler is not None:
        rt.profiler.reset()
    delivered[0] = 0
    # replay shifted well past the within-window so the warm pass's
    # partials expire instead of matching across the seam
    shift = np.int64(int(batches[-1][1][-1]) - int(batches[0][1][0])
                     + 60_000)
    n_timed = sum(int(t[1].shape[0]) for t in batches)
    t0 = time.perf_counter()
    for cols, ts in batches:
        h.send_batch(cols, ts + shift)
    rt.flush()
    wall = time.perf_counter() - t0
    rep = rt.statistics()
    expl = rt.explain()
    prof_rep = rt.profile()
    n_trace = rt.stats.export_chrome_trace(trace_out)
    mgr.shutdown()

    stages = {st: td for st, td in rep["stages"].items()
              if td.get("seconds") and st not in ("parse", "plan")}
    covered = sum(td["seconds"] for td in stages.values())
    # kernel-vs-host-dispatch split (ROADMAP item 2 "push the
    # host-dispatch share down"): the phase profiler's blocked-kernel
    # attribution — device = h2d + kernel + d2h shares of the batch
    # wall; everything else (pack/unpack, python dispatch, sink) is
    # host.  The old stage approximation (`kernel` + `transfer` span
    # seconds) stays as the fallback for a profiler-less runtime.
    agg = prof_rep.get("aggregate") or {}
    if agg.get("shares"):
        kernel_share = agg["device_share"]
        host_share = agg["host_dispatch_share"]
    else:
        dev_s = sum(stages.get(st, {}).get("seconds", 0.0)
                    for st in ("kernel", "transfer"))
        kernel_share = round(dev_s / wall, 3)
        host_share = round((wall - dev_s) / wall, 3)
    # the chosen pattern plan family per query (the PR-6/13 families):
    # a trace that can't name the family can't attribute a regression
    families = {q: ent["family"] for q, ent in
                expl.get("queries", {}).items() if ent.get("family")}
    out = {
        "events": n_timed, "batch": batch, "matches": delivered[0],
        "end_to_end_s": round(wall, 4),
        "eps": round(n_timed / wall),
        "coverage": round(covered / wall, 3),
        "plan_family": (next(iter(families.values()))
                        if len(families) == 1 else families) or None,
        "kernel_share": kernel_share,
        "host_dispatch_share": host_share,
        # the phase profiler's own report: per-phase seconds/shares,
        # coverage of the dispatch wall, per-plan roofline fold — the
        # continuous surface bench numbers are now derived from
        "profile": {
            "coverage": agg.get("coverage"),
            "shares": agg.get("shares"),
            "phases_s": agg.get("phases_s"),
            "host_dispatch_share": agg.get("host_dispatch_share"),
            "plans": {name: {k: pv.get(k) for k in
                             ("host_dispatch_share", "kernel_eps",
                              "end_to_end_eps", "roofline")}
                      for name, pv in
                      (prof_rep.get("plans") or {}).items()},
        },
        "stages": {st: {
            "seconds": round(td["seconds"], 4),
            "share": round(td["seconds"] / wall, 3),
            **{k: td[k] for k in ("p50_ms", "p95_ms", "p99_ms") if k in td},
        } for st, td in sorted(stages.items(),
                               key=lambda kv: -kv[1]["seconds"])},
        "chrome_trace": {"path": trace_out, "events": n_trace},
    }
    if "device" in rep:
        out["device"] = rep["device"]
    return out


def tracing_overhead(smoke=True, reps=None) -> dict:
    """The tracing plane's overhead contract (docs/OBSERVABILITY.md):
    config-3 TCP-frame ingest eps with tracing OFF (`@app:trace('off')`
    — `rt.tracing is None`, the pre-tracing hot path), ON-BUT-UNSAMPLED
    (tracer live, the sampling modulo never fires — the always-on-ring
    steady state), and the default 1-in-16 sampling.  Off and unsampled
    must both cost <= 5% vs each other's envelope; variants run
    interleaved round-robin and score best-of so thermal/GC drift
    lands on every variant equally."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.net import TcpFrameClient

    n = 1 << 14 if smoke else 1 << 16
    batch = 1024 if smoke else 4096
    warm = 2
    tape = make_tape(n + warm * batch, batch)
    batches = _tape_str_batches(tape)
    n_timed = sum(t["n"] for t in tape[warm:])
    reps = reps if reps is not None else (2 if smoke else 3)

    def run(head):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(
            head + "@source(type='tcp', port='0')\n" + DEV["patterns"] + C3)
        rt.start()
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, STREAM,
                             TcpFrameClient.cols_of_schema(
                                 rt.schemas[STREAM]))
        for cols, ts in batches[:warm]:
            cli.send_batch(cols, ts)
        cli.barrier(timeout=120)
        t0 = time.perf_counter()
        for cols, ts in batches[warm:]:
            cli.send_batch(cols, ts)
        cli.barrier(timeout=120)
        dt = time.perf_counter() - t0
        cli.close()
        mgr.shutdown()
        return n_timed / dt

    variants = {"off": "@app:trace('off')\n",
                "unsampled": "@app:trace(sample='1000000000')\n",
                "sampled_16": ""}           # the default
    runs: dict = {k: [] for k in variants}
    for _ in range(reps):
        for name, head in variants.items():
            runs[name].append(run(head))
    eps = {k: max(v) for k, v in runs.items()}
    out = {"events": n_timed, "batch": batch,
           "eps": {k: round(v) for k, v in eps.items()}}
    for k in ("unsampled", "sampled_16"):
        out[f"{k}_overhead_pct"] = round(
            100.0 * (1.0 - eps[k] / eps["off"]), 2)
    # the acceptance bar: off and on-but-unsampled within 5%
    out["pass"] = out["unsampled_overhead_pct"] <= 5.0
    return out


def profile_overhead(smoke=True, reps=None) -> dict:
    """The phase profiler's overhead contract (docs/OBSERVABILITY.md):
    config-3 TCP-frame ingest eps with the profiler OFF
    (`@app:profile('off')` — `rt.profiler is None`, zero hooks) vs the
    DEFAULT 1-in-32 duty cycle.  Default sampling must cost <= 3% —
    the always-on bar; same interleaved best-of discipline as
    tracing_overhead so thermal/GC drift lands on both variants.  The
    smoke tape is 4x tracing_overhead's: a 3% band needs a timed
    region long enough that scheduler jitter sits well under it."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.net import TcpFrameClient

    n = 1 << 16
    batch = 2048 if smoke else 4096
    warm = 2
    tape = make_tape(n + warm * batch, batch)
    batches = _tape_str_batches(tape)
    n_timed = sum(t["n"] for t in tape[warm:])
    # a 3% band needs more best-of depth than tracing's 5%: at 2-3 reps
    # one slow 'off' outlier reads as a double-digit phantom overhead
    reps = reps if reps is not None else 4

    def run(head):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(
            head + "@source(type='tcp', port='0')\n" + DEV["patterns"] + C3)
        rt.start()
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, STREAM,
                             TcpFrameClient.cols_of_schema(
                                 rt.schemas[STREAM]))
        for cols, ts in batches[:warm]:
            cli.send_batch(cols, ts)
        cli.barrier(timeout=120)
        t0 = time.perf_counter()
        for cols, ts in batches[warm:]:
            cli.send_batch(cols, ts)
        cli.barrier(timeout=120)
        dt = time.perf_counter() - t0
        cli.close()
        mgr.shutdown()
        return n_timed / dt

    variants = {"off": "@app:profile('off')\n",
                "sampled_32": ""}           # the default duty cycle
    runs: dict = {k: [] for k in variants}
    for _ in range(reps):
        for name, head in variants.items():
            runs[name].append(run(head))
    eps = {k: max(v) for k, v in runs.items()}
    out = {"events": n_timed, "batch": batch,
           "eps": {k: round(v) for k, v in eps.items()},
           "sampled_32_overhead_pct": round(
               100.0 * (1.0 - eps["sampled_32"] / eps["off"]), 2)}
    out["pass"] = out["sampled_32_overhead_pct"] <= 3.0
    return out


def harness_info() -> dict:
    """Provenance block recorded with every bench result (BENCH_DETAIL
    + summary): two runs whose harness blocks differ are not comparable
    and scripts/perfcheck.py refuses tight-band comparisons across a
    config-hash change."""
    import hashlib
    import os
    import subprocess
    info: dict = {"git_rev": None}
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if r.returncode == 0:
            info["git_rev"] = r.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    # the workload identity: every app text a numbered config runs
    cfg = "\x1e".join([STREAM, PIPE, C1, C2, C2B, C3, C3S, C4, c5_app(8),
                       *(DEV[k] for k in sorted(DEV)),
                       *(HOST[k] for k in sorted(HOST))])
    info["config_hash"] = hashlib.sha256(cfg.encode()).hexdigest()[:12]
    from siddhi_tpu.core import autotune
    info["jax"] = autotune.jax_version()
    info["device"] = autotune.device_kind()
    return info


# ---------------------------------------------------------------------------
# native single-core calibration (no JVM in the image: an -O2 C++ run of
# the same matcher algorithms upper-bounds single-JVM single-thread
# throughput on this hardware — see native/bench_native.cpp)
# ---------------------------------------------------------------------------

def native_baseline():
    """Build + run the native harness on tapes matching each config's
    (n + warm, batch, keys) so the event streams are the ones the
    engines consumed; returns {config: {"eps": .., "matches": ..}} or
    {} when unavailable."""
    import os
    import shutil
    import subprocess
    import tempfile
    root = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(root, "native", "bench_native.cpp")
    exe = os.path.join(root, "native", "bench_native")
    runnable = os.path.exists(exe) and os.access(exe, os.X_OK)
    stale = (runnable and os.path.exists(src)
             and os.path.getmtime(exe) < os.path.getmtime(src))
    if (not runnable or stale) and os.path.exists(src) \
            and shutil.which("g++") is not None:
        r = subprocess.run(["g++", "-O2", "-std=c++17", "-o", exe, src],
                           capture_output=True)
        runnable = r.returncode == 0
    if not runnable:
        return {}

    def tape_bin(n, batch, keys, path):
        tape = make_tape(n, batch, keys=keys, dt_ms=1)
        rec = np.dtype([("ts", "<i8"), ("price", "<f4"), ("key", "<i4")])
        rows = np.empty(sum(t["n"] for t in tape), dtype=rec)
        o = 0
        for t in tape:
            sl = slice(o, o + t["n"])
            rows["ts"][sl] = t["ts"]
            rows["price"][sl] = t["price"]
            rows["key"][sl] = t["sym_idx"]
            o += t["n"]
        rows.tofile(path)

    def run_exe(args):
        try:
            r = subprocess.run([exe, *args], capture_output=True,
                               text=True, timeout=120)
            return r.stdout if r.returncode == 0 else ""
        except (OSError, subprocess.SubprocessError):
            return ""

    out = {}
    with tempfile.TemporaryDirectory() as td:
        # config 1's tape (n + 1 warm batch)
        p1 = os.path.join(td, "t1.bin")
        tape_bin((1 << 19) + (1 << 18), 1 << 18, 8, p1)
        text = run_exe([p1, "filter"])
        # configs 2+3 share (n, batch) = (1<<18, 1<<17)
        p2 = os.path.join(td, "t2.bin")
        tape_bin((1 << 18) + (1 << 17), 1 << 17, 8, p2)
        text += run_exe([p2, "window", "sequence"])
        p3 = os.path.join(td, "t3.bin")
        tape_bin((2 << 18) + (1 << 18), 1 << 18, 1000, p3)
        text += run_exe([p3, "partitioned:1000"])
    for line in text.splitlines():
        parts = line.split()
        if len(parts) >= 3:
            out[parts[0]] = {"eps": int(float(parts[1])),
                             "matches": int(parts[2])}
    return out


def _tape_str_batches(tape, keys=8):
    """Tape -> (cols, ts) with symbol as STR arrays — the form both the
    wire clients and the in-process differential feed, so the string
    dictionary builds in the same order on every path."""
    names = np.array([f"K{i}" for i in range(keys)])
    return [({"symbol": names[t["sym_idx"]], "price": t["price"],
              "volume": t["volume"]}, t["ts"]) for t in tape]


def net_bench(smoke=False) -> dict:
    """`--net [--smoke]`: serving-plane bench (docs/SERVING.md) on the
    config-3 pattern workload.

      * per-event REST POSTs (the old front door) vs columnar TCP
        frames vs the shm ring vs in-process `send_batch` — eps each,
        with the wire paths asserted BYTE-IDENTICAL to in-process
        ingest (same matches, same decoded rows, same order)
      * multi-producer TCP fan-in (full mode)
      * overload: 2x the admitted rate under shed.policy='shed' —
        engine p99 must stay within 2x its unloaded value, every shed
        event must be accounted in the ErrorStore, and replay() must
        restore them (zero unaccounted loss)

    --smoke shrinks the tape for CI (scripts/smoke.sh) but keeps every
    assertion."""
    import threading
    import urllib.request
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.net import RingProducer, TcpFrameClient
    from siddhi_tpu.service import SiddhiService

    n = 1 << 12 if smoke else 1 << 16
    batch = 512 if smoke else 4096
    warm = 2
    app_body = DEV["patterns"] + C3
    tape = make_tape(n + warm * batch, batch)
    batches = _tape_str_batches(tape)
    n_timed = sum(t["n"] for t in tape[warm:])

    def run_collect(app, connect_fn):
        """Fresh runtime; connect_fn(rt) -> (send, finish) callables.
        Warm batches (compiles) land outside the timed window; returns
        (eps over the timed region, ALL decoded Out rows)."""
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(app)
        rows = []
        rt.add_batch_callback("Out", lambda b: rows.extend(
            map(tuple, b.rows(rt.strings))))
        rt.start()
        send, finish = connect_fn(rt)
        for cols, ts in batches[:warm]:
            send(cols, ts)
        finish()
        t0 = time.perf_counter()
        for cols, ts in batches[warm:]:
            send(cols, ts)
        finish()
        dt = time.perf_counter() - t0
        mgr.shutdown()
        for key in ("_bench_cli", "_bench_prod"):
            c = rt.__dict__.get(key)
            if c is not None:
                c.close()
        return n_timed / dt, rows

    # 1) in-process columnar (the direct append_columnar path)
    def connect_inproc(rt):
        h = rt.input_handler(STREAM)
        return h.send_batch, rt.flush
    inproc_eps, inproc_rows = run_collect(app_body, connect_inproc)

    # 2) loopback TCP frames through @source(type='tcp')
    def connect_tcp(rt):
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, STREAM,
                             TcpFrameClient.cols_of_schema(
                                 rt.schemas[STREAM]))
        rt.__dict__["_bench_cli"] = cli       # keep alive till shutdown
        return cli.send_batch, lambda: cli.barrier(timeout=120)
    tcp_eps, tcp_rows = run_collect(
        "@source(type='tcp', port='0')\n" + app_body, connect_tcp)

    # 3) shm ring
    def connect_shm(rt):
        prod = RingProducer(rt.sources[0].ring_name, STREAM,
                            RingProducer.cols_of_schema(rt.schemas[STREAM]))
        rt.__dict__["_bench_prod"] = prod
        sent = [0]

        def send(cols, ts):
            prod.send_batch(cols, ts)
            sent[0] += len(ts)

        def finish():
            prod.barrier(timeout=120)           # every frame popped
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:  # feed drains async of
                if rt.admission[STREAM].metrics()["admitted_events"] \
                        >= sent[0]:
                    break                       # last pop fed: tight poll
                time.sleep(0.0002)
            rt.flush()
        return send, finish
    ring_slots = "16" if smoke else "64"
    shm_eps, shm_rows = run_collect(
        f"@source(type='shm', slots='{ring_slots}', "
        f"slot.size='1048576')\n" + app_body, connect_shm)

    # 4) per-event REST (the old debug front door) — measured on a
    # slice of the tape, one keep-alive connection, one event per POST
    n_rest = 256 if smoke else 1024
    svc = SiddhiService(port=0, net=False).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{svc.port}/siddhi/artifact/deploy",
            data=("@app:name('RestBench')\n"
                  + app_body).encode(), method="POST")
        urllib.request.urlopen(req).read()
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", svc.port)
        rest_events = []
        for cols, ts in batches:
            for i in range(len(ts)):
                rest_events.append((cols["symbol"][i], cols["price"][i],
                                    int(cols["volume"][i]), int(ts[i])))
                if len(rest_events) >= n_rest:
                    break
            if len(rest_events) >= n_rest:
                break
        t0 = time.perf_counter()
        for sym, p, v, ts_i in rest_events:
            body = json.dumps({"app": "RestBench", "stream": STREAM,
                               "data": [str(sym), float(p), v],
                               "timestamp": ts_i}).encode()
            conn.request("POST", "/siddhi/artifact/event", body=body,
                         headers={"Content-Type": "application/json"})
            conn.getresponse().read()
        rest_eps = n_rest / (time.perf_counter() - t0)
        conn.close()
    finally:
        svc.stop()

    # 5) multi-producer TCP fan-in (full mode): two connections, the
    # tape split between them.  A STATELESS filter app — interleaved
    # producers scramble cross-batch event time, which is a pattern-
    # engine workload question (pending windows stop expiring
    # monotonically), not a transport one; the filter isolates fan-in
    # capacity.  No cross-producer order, so count-only.
    mp_eps = None
    if not smoke:
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(
            "@source(type='tcp', port='0')\n" + DEV["filters"] + C1)
        rt.start()
        port = rt.sources[0].port
        cols_spec = TcpFrameClient.cols_of_schema(rt.schemas[STREAM])
        warm_cli = TcpFrameClient("127.0.0.1", port, STREAM, cols_spec)
        for cols, ts in batches[:warm]:
            warm_cli.send_batch(cols, ts)
        warm_cli.barrier(timeout=120)

        def one(share):
            cli = TcpFrameClient("127.0.0.1", port, STREAM, cols_spec)
            for cols, ts in share:
                cli.send_batch(cols, ts)
            cli.barrier(timeout=120)
            cli.close()
        ths = [threading.Thread(target=one, args=(s,))
               for s in (batches[warm::2], batches[warm + 1::2])]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        mp_eps = n_timed / (time.perf_counter() - t0)
        warm_cli.close()
        mgr.shutdown()

    identical = (tcp_rows == inproc_rows and shm_rows == inproc_rows
                 and len(inproc_rows) > 0)

    # 6) overload: 2x the admitted rate, shed.policy='shed'
    overload = _net_overload(smoke)

    res = {
        "events": n_timed, "batch": batch,
        "transport": {
            "inproc_eps": round(inproc_eps),
            "tcp_eps": round(tcp_eps),
            "shm_eps": round(shm_eps),
            "rest_eps": round(rest_eps, 1),
            **({"tcp_2producer_filter_eps": round(mp_eps)}
               if mp_eps else {}),
        },
        "tcp_vs_rest": round(tcp_eps / rest_eps, 1),
        "shm_vs_tcp": round(shm_eps / tcp_eps, 2),
        "matches": len(inproc_rows),
        "identical": identical,
        "overload": overload,
    }
    res["pass"] = bool(identical and res["tcp_vs_rest"] >= 5.0
                       and overload["pass"])
    return res


def _net_overload(smoke=False) -> dict:
    """Paced 2x-overload against a rate-limited tcp source with
    shed.policy='shed': p99 bound, zero unaccounted loss, replayable."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.net import TcpFrameClient

    rate = 4000.0                   # admitted eps
    burst = 400.0
    pace_batch = 64
    seconds = 1.5 if smoke else 4.0
    app = ("@app:statistics('true')\n"
           f"@source(type='tcp', port='0', rate.limit='{rate}', "
           f"burst='{burst}', shed.policy='shed')\n" + DEV["patterns"] + C3)

    def paced_run(offered_eps):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(app)
        delivered = [0]
        rt.add_batch_callback(STREAM, lambda b: delivered.__setitem__(
            0, delivered[0] + b.n))
        rt.start()
        cli = TcpFrameClient(
            "127.0.0.1", rt.sources[0].port, STREAM,
            TcpFrameClient.cols_of_schema(rt.schemas[STREAM]))
        rng = np.random.default_rng(11)
        ts0 = 1_700_000_000_000
        sent = 0

        def one_batch():
            nonlocal sent
            cols = {"symbol": np.array(
                        [f"K{i}" for i in rng.integers(0, 8, pace_batch)]),
                    "price": q4(rng.uniform(90, 130, pace_batch)),
                    "volume": rng.integers(1, 100, pace_batch)
                       .astype(np.int32)}
            cli.send_batch(cols, ts0 + np.arange(
                sent, sent + pace_batch, dtype=np.int64))
            sent += pace_batch

        # warm OUTSIDE the measured window: the first batches trigger
        # kernel compiles, which would otherwise backlog the socket and
        # burst-shed on drain (and pollute the p99 histogram)
        for _ in range(4):
            one_batch()
            cli.barrier(timeout=120)
        rt.stats.reset()                # p99 over the paced window only
        ctrl = rt.admission[STREAM]
        m0 = ctrl.metrics()
        sent0, delivered0 = sent, delivered[0]
        interval = pace_batch / offered_eps
        t_end = time.perf_counter() + seconds
        ts_next = time.perf_counter()
        while time.perf_counter() < t_end:
            one_batch()
            ts_next += interval
            lag = ts_next - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
        cli.barrier(timeout=60)
        m = ctrl.metrics()
        stats = rt.statistics()
        p99 = stats["streams"].get(STREAM, {}).get("p99_ms")
        out = {"sent": sent - sent0,
               "delivered": delivered[0] - delivered0,
               "shed": m["shed_events"] - m0["shed_events"],
               "p99_ms": p99,
               "stored_frames": m["shed_frames"] - m0["shed_frames"]}
        # replay restores every shed event (lift the limit first)
        ctrl.bucket.rate = None
        rep = rt.error_store.replay(rt)
        rt.flush()
        out["replayed_ok"] = (rep["remaining"] == 0
                              and delivered[0] == sent)
        cli.close()
        mgr.shutdown()
        return out

    base = paced_run(rate * 0.5)            # unloaded: half the limit
    over = paced_run(rate * 2.0)            # 2x the admitted rate
    p99_ok = (base["p99_ms"] is None or over["p99_ms"] is None
              or over["p99_ms"] <= 2.0 * max(base["p99_ms"], 1.0))
    res = {"rate_limit_eps": rate, "unloaded": base, "overloaded": over,
           "p99_within_2x": p99_ok,
           "zero_loss": bool(over["replayed_ok"] and over["shed"] > 0)}
    res["pass"] = bool(res["p99_within_2x"] and res["zero_loss"]
                       and base["replayed_ok"])
    return res


def chaos_net(seed: int = 7) -> dict:
    """Serving-plane chaos (`--chaos` rides this after the core
    sections): mid-frame disconnects must not poison the server or
    lose admitted frames; a slow consumer on a tiny shm ring must
    backpressure the producer, never drop; injected ingest faults
    capture whole frames for replay."""
    import socket as _socket
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.faults import FaultInjector
    from siddhi_tpu.net import RingProducer, TcpFrameClient
    from siddhi_tpu.net import frame as fp

    APP = ("@source(type='tcp', port='0')\n"
           "define stream S (sym string, p double);\n"
           "@info(name='q') from S select sym, p insert into Out;\n")
    out: dict = {}
    rng = np.random.default_rng(seed)

    # 1) mid-frame disconnects between healthy producers
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(APP)
    delivered = [0]
    rt.add_batch_callback("S", lambda b: delivered.__setitem__(
        0, delivered[0] + b.n))
    rt.start()
    port = rt.sources[0].port
    cols_spec = TcpFrameClient.cols_of_schema(rt.schemas["S"])
    n_sent = 0
    for round_ in range(3):
        cli = TcpFrameClient("127.0.0.1", port, "S", cols_spec)
        for k in range(4):
            cli.send_batch(
                {"sym": np.array([f"K{i}" for i in
                                  rng.integers(0, 4, 32)]),
                 "p": q4(rng.uniform(0, 10, 32))},
                np.arange(n_sent, n_sent + 32, dtype=np.int64))
            n_sent += 32
        cli.barrier()
        cli.close()
        # now a rude client: half a frame, then vanish
        raw = _socket.create_connection(("127.0.0.1", port))
        blob = fp.encode_hello("", "S", cols_spec)
        raw.sendall(blob[:len(blob) // 2 + round_])
        raw.close()
        # and one that sends garbage
        raw = _socket.create_connection(("127.0.0.1", port))
        raw.sendall(bytes(rng.integers(0, 256, 64, dtype=np.uint8)))
        raw.close()
    time.sleep(0.1)
    errors = rt.statistics()["net"]["S"].get("protocol_errors", 0)
    disc_ok = delivered[0] == n_sent
    out["mid_frame_disconnect"] = {
        "sent": n_sent, "delivered": delivered[0],
        "protocol_errors": errors, "pass": disc_ok}
    mgr.shutdown()

    # 2) slow consumer: a 2-slot ring backpressures, loses nothing
    APP_SHM = ("@source(type='shm', slots='2', slot.size='8192')\n"
               "define stream S (sym string, p double);\n"
               "@info(name='q') from S select sym, p insert into Out;\n")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(APP_SHM)
    delivered2 = [0]
    rt.add_batch_callback("S", lambda b: delivered2.__setitem__(
        0, delivered2[0] + b.n))
    rt.start()
    prod = RingProducer(rt.sources[0].ring_name, "S",
                        RingProducer.cols_of_schema(rt.schemas["S"]),
                        push_timeout=30)
    n2 = 0
    for k in range(64):                     # 64 frames through 2 slots
        prod.send_batch({"sym": np.array(["A", "B"]),
                         "p": np.array([1.0, 2.0])},
                        np.arange(n2, n2 + 2, dtype=np.int64))
        n2 += 2
    prod.barrier(timeout=30)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and delivered2[0] < n2:
        rt.flush()
        time.sleep(0.01)
    slow_ok = delivered2[0] == n2
    out["slow_consumer_ring"] = {"sent": n2, "delivered": delivered2[0],
                                 "pass": slow_ok}
    prod.close()
    mgr.shutdown()

    # 3) injected ingest faults: admitted frames capture whole + replay
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(APP)
    delivered3 = [0]
    rt.add_batch_callback("S", lambda b: delivered3.__setitem__(
        0, delivered3[0] + b.n))
    rt.start()
    rt.fault_injector = FaultInjector(seed=seed, counts={"net.feed": 3})
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]))
    n3 = 0
    for k in range(8):
        cli.send_batch({"sym": np.array(["X"] * 16),
                        "p": q4(rng.uniform(0, 10, 16))},
                       np.arange(n3, n3 + 16, dtype=np.int64))
        n3 += 16
    cli.barrier()
    stored = len(rt.error_store)
    rt.fault_injector = None
    rep = rt.error_store.replay(rt)
    rt.flush()
    feed_ok = (stored == 3 and rep["remaining"] == 0
               and delivered3[0] == n3)
    out["injected_feed_faults"] = {
        "sent": n3, "stored_then_replayed": stored,
        "delivered_after_replay": delivered3[0], "pass": feed_ok}
    cli.close()
    mgr.shutdown()

    out["pass"] = disc_ok and slow_ok and feed_ok
    return out


# ---------------------------------------------------------------------------
# kill-9 durability chaos (`--chaos`): SIGKILL at a fault-injected point,
# recover, prove exactly-once (docs/RELIABILITY.md)
# ---------------------------------------------------------------------------

_K9_HEAD = ("@app:name('K9')\n"
            "@app:durability('batch')\n")

K9_PATTERN = _K9_HEAD + """
@app:devicePatterns('prefer')
@source(type='tcp', port='0')
define stream S (sym string, p double);
define table OutT (s1 string, s2 string);
@info(name='q') from every a=S[p > 120] -> b=S[p < 80] within 1 sec
select a.sym as s1, b.sym as s2 insert into OutT;
"""

K9_WINDOW = _K9_HEAD + """
@source(type='tcp', port='0')
define stream S (sym string, p double);
define table OutT (sym string, s double, c long);
@info(name='q') from S#window.length(64)
select sym, sum(p) as s, count() as c group by sym insert into OutT;
"""

K9_JOIN = _K9_HEAD + """
@source(type='tcp', port='0')
define stream S (sym string, p double);
@source(type='tcp', port='0')
define stream T (sym string, p double);
define table OutT (sym string, pa double, pb double);
@info(name='q') from S#window.length(32) as a join T#window.length(32) as b
    on a.sym == b.sym
select a.sym as sym, a.p as pa, b.p as pb insert into OutT;
"""

K9_CONFIGS = {"pattern": (K9_PATTERN, ["S"]),
              "window": (K9_WINDOW, ["S"]),
              "join": (K9_JOIN, ["S", "T"])}


def _k9_tape(seed, streams, rounds=10, batch=128, keys=6,
             with_ts=False):
    """Deterministic per-round frame tape, regenerated identically by
    the parent (clean run + resume) and the to-be-killed child.
    `with_ts` adds the event-time column aggregations fold by."""
    rng = np.random.default_rng(seed)
    ts0 = 1_700_000_000_000
    out = []
    for k in range(rounds):
        rd = {}
        for sid in streams:
            ts = ts0 + np.arange(k * batch, (k + 1) * batch,
                                 dtype=np.int64) * 2
            cols = {"sym": np.array([f"K{i}" for i in
                                     rng.integers(0, keys, batch)]),
                    "p": q4(rng.uniform(60.0, 140.0, batch))}
            if with_ts:
                cols["ts"] = ts
            rd[sid] = (cols, ts)
        out.append(rd)
    return out


def chaos_kill9_child(spec_path: str) -> None:
    """Hidden `--chaos-child <spec.json>` mode: build the durable app,
    feed the deterministic tape over loopback TCP (per-frame ACK
    barriers, so every acked frame is a durability promise), persist at
    the scripted round, and SIGKILL OURSELVES at the armed injection
    point — mid-`wal.append` leaves a torn record on disk, exactly the
    crash shape recovery must absorb.  Exits 3 if the kill never fired
    (the parent treats any exit other than SIGKILL as a failure)."""
    import json as _json
    import os
    import signal
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore
    from siddhi_tpu.net import TcpFrameClient

    with open(spec_path) as f:
        spec = _json.load(f)

    class _Kill9:
        """FaultInjector-shaped: SIGKILL (not an exception) at the Nth
        check of one point — the process vanishes mid-operation."""

        def __init__(self, point, at):
            self.point, self.at, self.n = point, at, 0

        def check(self, point, detail=""):
            if point == self.point:
                self.n += 1
                if self.n >= self.at:
                    os.kill(os.getpid(), signal.SIGKILL)

    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(spec["snap_dir"]))
    rt = mgr.create_app_runtime(spec["app"])
    rt.start()
    rt.fault_injector = _Kill9(spec["kill_point"], spec["kill_at"])
    ports = {s.stream_id: s.port for s in rt.sources}
    clis = {sid: TcpFrameClient("127.0.0.1", ports[sid], sid,
                                TcpFrameClient.cols_of_schema(
                                    rt.schemas[sid]))
            for sid in spec["streams"]}
    tape = _k9_tape(spec["seed"], spec["streams"], spec["rounds"],
                    spec["batch"], spec["keys"],
                    with_ts=spec.get("with_ts", False))
    for k, rd in enumerate(tape):
        if k == spec["snapshot_at"]:
            rt.persist()
        for sid in spec["streams"]:
            cols, ts = rd[sid]
            clis[sid].send_batch(cols, ts)
            # serialize streams per round: append order (and thus the
            # clean-run differential) stays deterministic
            clis[sid].barrier(timeout=60)
    os._exit(3)


def chaos_kill9(seed: int = 7) -> dict:
    """`--chaos` kill-9-and-recover section: for each of the pattern /
    window / join configs, a subprocess feeds N TCP frames into a
    `@app:durability('batch')` app and is SIGKILLED at a fault-injected
    point (mid-`wal.append` with a snapshot behind it; mid-snapshot
    with only the log).  The parent then recovers — restore newest
    loadable snapshot + replay the WAL suffix past the watermark — and
    resumes the unacked tape tail exactly as a real producer would.

    Asserted per config and kill point:
      * byte-identical outputs to an uninterrupted run (zero duplicate,
        zero lost admitted events — the exactly-once invariant)
      * events_in == applied + shed over the recovered pipeline
      * zero ErrorStore captures (nothing was quietly parked)"""
    import json as _json
    import os
    import shutil
    import subprocess
    import tempfile
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore

    rounds, batch, keys = 10, 128, 6
    out = {"seed": seed, "configs": {}, "pass": True}
    for name, (app, streams) in K9_CONFIGS.items():
        tape = _k9_tape(seed, streams, rounds, batch, keys)
        events_in = rounds * batch * len(streams)

        # uninterrupted reference run (in-process feed; wire-vs-inproc
        # byte-identity is net_bench's standing assertion)
        clean_dir = tempfile.mkdtemp(prefix="siddhi_k9_clean_")
        mgr = SiddhiManager()
        mgr.set_persistence_store(FileSystemPersistenceStore(clean_dir))
        rt = mgr.create_app_runtime(app)
        hs = {sid: rt.input_handler(sid) for sid in streams}
        for rd in tape:
            for sid in streams:
                cols, ts = rd[sid]
                hs[sid].send_batch(cols, ts)
        rt.flush()
        want = sorted(map(tuple, rt.tables["OutT"].all_rows()))
        mgr.shutdown()
        shutil.rmtree(clean_dir, ignore_errors=True)

        cfg = {"events_in": events_in, "clean_rows": len(want)}
        snapshot_at = 4
        pre_appends = snapshot_at * len(streams)
        for kname, point, at in (
                ("mid_wal_append", "wal.append",
                 pre_appends + 2 * len(streams) + 1),
                ("mid_snapshot", "persist.save", 1)):
            work = tempfile.mkdtemp(prefix=f"siddhi_k9_{name}_")
            snap_dir = os.path.join(work, "snap")
            spec = {"app": app.replace(
                        "@app:durability('batch')",
                        f"@app:durability('batch', dir='{work}/wal')"),
                    "streams": streams, "snap_dir": snap_dir,
                    "seed": seed, "rounds": rounds, "batch": batch,
                    "keys": keys, "snapshot_at": snapshot_at,
                    "kill_point": point, "kill_at": at}
            spec_path = os.path.join(work, "spec.json")
            with open(spec_path, "w") as f:
                _json.dump(spec, f)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--chaos-child", spec_path],
                capture_output=True, timeout=600)
            killed = proc.returncode == -9
            rep = {}
            got = None
            shed = applied = resumed_events = 0
            if killed:
                m2 = SiddhiManager()
                m2.set_persistence_store(
                    FileSystemPersistenceStore(snap_dir))
                rt2 = m2.create_app_runtime(spec["app"])
                rep = rt2.recover()
                durable = dict(rt2.wal.seqs)
                h2 = {sid: rt2.input_handler(sid) for sid in streams}
                for k, rd in enumerate(tape):
                    for sid in streams:
                        if k + 1 > durable.get(sid, 0):
                            cols, ts = rd[sid]   # the unacked tail: a
                            h2[sid].send_batch(cols, ts)  # producer
                            resumed_events += batch       # retransmits
                rt2.flush()
                got = sorted(map(tuple, rt2.tables["OutT"].all_rows()))
                shed = sum(len(e.events or ())
                           for e in rt2.error_store.entries())
                wm_events = sum(rep["watermark"].values()) * batch
                applied = (wm_events + rep["replayed_events"]
                           + resumed_events)
                m2.shutdown()
            ok = (killed and got == want and shed == 0
                  and applied + shed == events_in)
            cfg[kname] = {
                "killed": killed,
                "restored_revision": rep.get("restored_revision"),
                "watermark": rep.get("watermark"),
                "replayed_frames": rep.get("replayed_frames"),
                "corrupt_skipped": rep.get("corrupt_skipped"),
                "recovery_s": rep.get("recovery_s"),
                "resumed_events": resumed_events,
                "applied": applied, "shed": shed,
                "identical": got == want,
                "pass": ok,
            }
            if not killed:
                cfg[kname]["child_rc"] = proc.returncode
                cfg[kname]["child_tail"] = \
                    proc.stderr.decode(errors="replace")[-500:]
            out["pass"] = out["pass"] and ok
            shutil.rmtree(work, ignore_errors=True)
        cfg["pass"] = all(cfg[k]["pass"] for k in
                          ("mid_wal_append", "mid_snapshot"))
        out["configs"][name] = cfg
    return out


K9_AGG = _K9_HEAD + """
@source(type='tcp', port='0')
define stream S (sym string, p double, ts long);
define aggregation Roll
from S
select sym, sum(p) as total, avg(p) as mean, count() as n
group by sym
aggregate by ts every sec, min;
"""

K9_AGG_QUERY = ("from Roll within 1699999000000L, 1700001000000L "
                "per 'sec' select sym, total, mean, n")


def chaos_agg_kill9(seed: int = 7) -> dict:
    """`--chaos` queryable-state section: the kill-9 harness pointed at
    a `define aggregation` app.  A subprocess feeds TCP frames into the
    durable rollup and is SIGKILLED mid-`wal.append` (snapshot behind
    it) and mid-snapshot; the parent recovers and resumes the unacked
    tail.  Asserted per kill point, against an uninterrupted run:

      * store-query rows byte-identical (the exactly-once invariant on
        the aggregation plane — no bucket double-merge, none lost)
      * the device-resident bucket store itself byte-identical
        (`state_dict()` compares raw f64 bases, not rendered rows)
      * zero ErrorStore captures"""
    import json as _json
    import os
    import shutil
    import subprocess
    import tempfile
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore

    rounds, batch, keys = 10, 128, 6
    streams = ["S"]
    tape = _k9_tape(seed, streams, rounds, batch, keys, with_ts=True)

    # uninterrupted reference (in-proc feed, same tape; durability off
    # -- the reference run needs no WAL and must not warn about one)
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        K9_AGG.replace("@app:durability('batch')\n", ""))
    rt.start()
    h = rt.input_handler("S")
    for rd in tape:
        cols, ts = rd["S"]
        h.send_batch(cols, ts)
    rt.flush()
    want_rows = rt.query(K9_AGG_QUERY)
    want_state = rt.aggregations["Roll"].state_dict()
    dev_path = rt.explain()["aggregations"]["Roll"]["path"]
    mgr.shutdown()

    out = {"seed": seed, "clean_rows": len(want_rows),
           "path": dev_path, "kills": {}, "pass": dev_path != "host"}
    snapshot_at = 4
    for kname, point, at in (
            ("mid_wal_append", "wal.append", snapshot_at + 3),
            ("mid_snapshot", "persist.save", 1)):
        work = tempfile.mkdtemp(prefix="siddhi_k9agg_")
        snap_dir = os.path.join(work, "snap")
        spec = {"app": K9_AGG.replace(
                    "@app:durability('batch')",
                    f"@app:durability('batch', dir='{work}/wal')"),
                "streams": streams, "snap_dir": snap_dir,
                "seed": seed, "rounds": rounds, "batch": batch,
                "keys": keys, "snapshot_at": snapshot_at,
                "with_ts": True, "kill_point": point, "kill_at": at}
        spec_path = os.path.join(work, "spec.json")
        with open(spec_path, "w") as f:
            _json.dump(spec, f)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--chaos-child", spec_path],
            capture_output=True, timeout=600)
        killed = proc.returncode == -9
        rep = {}
        rows_ok = state_ok = False
        shed = resumed = 0
        if killed:
            m2 = SiddhiManager()
            m2.set_persistence_store(FileSystemPersistenceStore(snap_dir))
            rt2 = m2.create_app_runtime(spec["app"])
            rep = rt2.recover()
            durable = dict(rt2.wal.seqs)
            h2 = rt2.input_handler("S")
            for k, rd in enumerate(tape):
                if k + 1 > durable.get("S", 0):
                    cols, ts = rd["S"]
                    h2.send_batch(cols, ts)
                    resumed += batch
            rt2.flush()
            rows_ok = rt2.query(K9_AGG_QUERY) == want_rows
            state_ok = (rt2.aggregations["Roll"].state_dict()
                        == want_state)
            shed = sum(len(e.events or ())
                       for e in rt2.error_store.entries())
            m2.shutdown()
        ok = killed and rows_ok and state_ok and shed == 0
        out["kills"][kname] = {
            "killed": killed,
            "restored_revision": rep.get("restored_revision"),
            "replayed_frames": rep.get("replayed_frames"),
            "resumed_events": resumed, "shed": shed,
            "rows_identical": rows_ok,
            "bucket_state_identical": state_ok, "pass": ok}
        if not killed:
            out["kills"][kname]["child_rc"] = proc.returncode
            out["kills"][kname]["child_tail"] = \
                proc.stderr.decode(errors="replace")[-500:]
        out["pass"] = out["pass"] and ok
        shutil.rmtree(work, ignore_errors=True)
    return out


# ---------------------------------------------------------------------------
# machine-loss chaos (`--chaos`): SIGKILL the PRIMARY PROCESS, promote the
# hot standby, resume the producer — the whole machine is gone, so only
# what replication shipped survives (docs/RELIABILITY.md "High
# availability & failover")
# ---------------------------------------------------------------------------

REPL_APP = """@app:name('HARepl')
@source(type='tcp', port='0')
define stream S (sym string, p double);
define table OutT (sym string, s double, c long);
@info(name='q') from S#window.length(64)
select sym, sum(p) as s, count() as c group by sym insert into OutT;
"""


def chaos_repl_child(spec_path: str) -> None:
    """Hidden `--chaos-repl-child <spec.json>` mode: run the PRIMARY of
    the machine-loss cell — a durable app plus a replication front door
    (NetServer with repl_resolve) — and SIGKILL OURSELVES at the armed
    injection point.  Two feed modes: 'parent' (the parent process is
    the producer over loopback TCP; we die mid-`wal.append`, a frame
    the producer was never acked for) and 'self' (we feed our own tape,
    persist full+incremental snapshots that TRUNCATE the log, then
    idle; we die mid-`repl.ship snapshot:` — the standby's catch-up
    chain cut off halfway).  Exits 3 if the kill never fired."""
    import json as _json
    import os
    import signal
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import (
        IncrementalFileSystemPersistenceStore)
    from siddhi_tpu.net import TcpFrameClient
    from siddhi_tpu.net.server import NetServer

    with open(spec_path) as f:
        spec = _json.load(f)

    class _Kill9:
        """SIGKILL at the Nth check of one point (optionally only when
        the detail starts with a prefix — 'snapshot:' selects the
        catch-up frames of repl.ship)."""

        def __init__(self, point, at, prefix=""):
            self.point, self.at, self.prefix = point, at, prefix
            self.n = 0

        def check(self, point, detail=""):
            if point == self.point and \
                    str(detail).startswith(self.prefix):
                self.n += 1
                if self.n >= self.at:
                    os.kill(os.getpid(), signal.SIGKILL)

    mgr = SiddhiManager()
    mgr.set_persistence_store(
        IncrementalFileSystemPersistenceStore(spec["snap_dir"]))
    rt = mgr.create_app_runtime(spec["app"])
    rt.start()
    rt.fault_injector = _Kill9(spec["kill_point"], spec["kill_at"],
                               spec.get("kill_prefix", ""))
    srv = NetServer(lambda a, s: (_ for _ in ()).throw(KeyError(s)),
                    port=0, repl_resolve=lambda app: rt).start()
    ports = {"repl": srv.port, "source": rt.sources[0].port}
    tmp_ports = spec["ports_path"] + ".tmp"
    with open(tmp_ports, "w") as f:
        _json.dump(ports, f)
    os.replace(tmp_ports, spec["ports_path"])
    if spec["feed"] == "self":
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                             TcpFrameClient.cols_of_schema(
                                 rt.schemas["S"]))
        tape = _k9_tape(spec["seed"], ["S"], spec["rounds"],
                        spec["batch"], spec["keys"])
        for k, rd in enumerate(tape):
            cols, ts = rd["S"]
            cli.send_batch(cols, ts)
            cli.barrier(timeout=60)
            if k == spec["full_at"]:
                # first incremental persist = F- full (oplog activation);
                # its snapshot barrier truncates sealed segments
                rt.persist(incremental=True)
            elif k == spec["incr_at"]:
                rt.persist(incremental=True)    # I- delta -> 2-rev chain
        with open(spec["fed_path"], "w") as f:
            f.write("done")
    # serve (and, armed, die) until the parent's cell is over
    import time as _time
    deadline = _time.monotonic() + 600
    while _time.monotonic() < deadline:
        _time.sleep(0.05)
    os._exit(3)


def _repl_standby(peer_port: int, wal_dir: str, store_dir: str):
    """The parent-held hot standby of the machine-loss cell."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import (
        IncrementalFileSystemPersistenceStore)
    mgr = SiddhiManager()
    # shipped F-/I- revisions land verbatim: the standby's store must
    # reassemble the chain at promote time
    mgr.set_persistence_store(
        IncrementalFileSystemPersistenceStore(store_dir))
    rt = mgr.create_app_runtime(
        "@app:durability('batch', dir='" + wal_dir + "', "
        "segment.bytes='2048')\n"
        "@app:replication('async', role='standby', "
        f"peer='127.0.0.1:{peer_port}')\n" + REPL_APP)
    rt.start()
    return mgr, rt


def chaos_machine_loss(seed: int = 7) -> dict:
    """`--chaos` machine-loss cell: the primary RUNS IN A CHILD PROCESS
    and is SIGKILLED — its disk is treated as gone; the parent holds
    the hot standby, promotes it, and resumes the producer from the
    standby's durable watermark (exactly a real producer's retransmit
    contract).  Two kill shapes:

      * mid_frame: killed inside `wal.append` of a frame the producer
        was never acked for — the standby replays its replicated log
        and the producer retransmits the tail
      * mid_snapshot_ship: killed halfway through shipping the
        snapshot catch-up chain (the standby subscribed AFTER
        truncation) — the standby promotes from the partial chain's
        newest full revision and the producer retransmits the rest

    Asserted per shape: outputs byte-identical to an uninterrupted run,
    `events_in == applied + shed` (shed == 0 — nothing quietly parked),
    and the pre-kill happy path left ZERO ErrorStore captures."""
    import json as _json
    import os
    import shutil
    import signal
    import subprocess
    import tempfile
    import time as _time
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore
    from siddhi_tpu.net import TcpFrameClient

    rounds, batch, keys = 10, 128, 6
    events_in = rounds * batch
    tape = _k9_tape(seed, ["S"], rounds, batch, keys)

    # uninterrupted reference
    clean_dir = tempfile.mkdtemp(prefix="siddhi_ml_clean_")
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(clean_dir))
    rt = mgr.create_app_runtime(REPL_APP)
    h = rt.input_handler("S")
    for rd in tape:
        cols, ts = rd["S"]
        h.send_batch(cols, ts)
    rt.flush()
    want = sorted(map(tuple, rt.tables["OutT"].all_rows()))
    mgr.shutdown()
    shutil.rmtree(clean_dir, ignore_errors=True)

    def wait_file(path, timeout_s=60.0):
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if os.path.exists(path):
                return True
            _time.sleep(0.02)
        return False

    out = {"seed": seed, "clean_rows": len(want),
           "events_in": events_in, "pass": True}
    shapes = (
        ("mid_frame", {"feed": "parent", "kill_point": "wal.append",
                       "kill_at": 7}),
        ("mid_snapshot_ship", {"feed": "self", "kill_point": "repl.ship",
                               "kill_prefix": "snapshot:", "kill_at": 2,
                               "full_at": 3, "incr_at": 6}),
    )
    for name, kill in shapes:
        work = tempfile.mkdtemp(prefix=f"siddhi_ml_{name}_")
        spec = {"app": ("@app:durability('batch', dir='" + work
                        + "/pwal', segment.bytes='2048')\n" + REPL_APP),
                "snap_dir": os.path.join(work, "psnap"),
                "ports_path": os.path.join(work, "ports.json"),
                "fed_path": os.path.join(work, "fed"),
                "seed": seed, "rounds": rounds, "batch": batch,
                "keys": keys, **kill}
        spec_path = os.path.join(work, "spec.json")
        with open(spec_path, "w") as f:
            _json.dump(spec, f)
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--chaos-repl-child", spec_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        cell = {"pass": False}
        mgr_s = None
        try:
            if not wait_file(spec["ports_path"]):
                raise RuntimeError("child never published its ports")
            with open(spec["ports_path"]) as f:
                ports = _json.load(f)
            if spec["feed"] == "self":
                # the child feeds + snapshots ITSELF (truncating its
                # log); the standby subscribes only after, so its very
                # first poll is the catch-up gap
                if not wait_file(spec["fed_path"]):
                    raise RuntimeError("child never finished feeding")
            mgr_s, rt_s = _repl_standby(ports["repl"],
                                        os.path.join(work, "swal"),
                                        os.path.join(work, "ssnap"))
            sent = 0
            if spec["feed"] == "parent":
                cli = TcpFrameClient(
                    "127.0.0.1", ports["source"], "S",
                    TcpFrameClient.cols_of_schema(rt_s.schemas["S"]))
                try:
                    for rd in tape:
                        cols, ts = rd["S"]
                        cli.send_batch(cols, ts)
                        cli.barrier(timeout=60)
                        sent += 1
                        if sent == 3:
                            # pre-kill happy path: NOTHING was parked
                            cell["pre_kill_captures"] = \
                                len(rt_s.error_store)
                except Exception:
                    pass                # the machine just died mid-frame
                finally:
                    try:
                        cli.close()
                    except Exception:
                        pass
            # the kill fired (anything else is a failed cell)
            rc = proc.wait(timeout=120)
            killed = rc == -signal.SIGKILL
            cell["killed"] = killed
            if spec["feed"] == "self":
                # let the receiver land whatever the chain shipped
                deadline = _time.monotonic() + 10
                while _time.monotonic() < deadline and \
                        rt_s.statistics()["replication"] \
                        .get("applied_snapshots", 0) < 1:
                    _time.sleep(0.05)
            # post-kill `repl.receive` link errors are the EXPECTED loud
            # capture of a dead machine; any OTHER point captured means
            # the happy path quietly parked something
            cell["happy_path_captures"] = len(
                [e for e in rt_s.error_store.entries()
                 if e.point != "repl.receive"])
            report = rt_s.promote()
            durable = dict(rt_s.wal.seqs)
            h2 = rt_s.input_handler("S")
            resumed_events = 0
            for k, rd in enumerate(tape):
                if k + 1 > durable.get("S", 0):
                    cols, ts = rd["S"]
                    h2.send_batch(cols, ts)     # producer retransmit
                    resumed_events += batch
            rt_s.flush()
            got = sorted(map(tuple, rt_s.tables["OutT"].all_rows()))
            shed = sum(len(e.events or ())
                       for e in rt_s.error_store.entries())
            wm_events = sum(report["recovery"]["watermark"]
                            .values()) * batch
            applied = (wm_events + report["recovery"]["replayed_events"]
                       + resumed_events)
            ok = (killed and got == want and shed == 0
                  and applied + shed == events_in
                  and cell.get("happy_path_captures", 1) == 0
                  and cell.get("pre_kill_captures", 0) == 0)
            cell.update({
                "promote_s": report["promote_s"],
                "generation": report["generation"],
                "restored_revision":
                    report["recovery"]["restored_revision"],
                "replayed_frames": report["recovery"]["replayed_frames"],
                "resumed_events": resumed_events,
                "applied": applied, "shed": shed,
                "identical": got == want, "pass": ok})
        except Exception as e:
            cell["error"] = f"{type(e).__name__}: {e}"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            if not cell.get("killed"):
                cell["child_tail"] = (proc.stderr.read() or b"") \
                    .decode(errors="replace")[-500:]
            if mgr_s is not None:
                mgr_s.shutdown()
            shutil.rmtree(work, ignore_errors=True)
        out[name] = cell
        out["pass"] = out["pass"] and bool(cell.get("pass"))
    return out


def chaos_split_brain(seed: int = 7) -> dict:
    """`--chaos` split-brain cell: after the standby promotes (fencing
    ABOVE every generation it saw), the deposed primary is still alive
    and still believes it serves.  Point the promoted node's receiver
    back at it — the operator misconfiguration that makes split-brain
    dangerous — and prove the fence rejects the stale timeline LOUDLY
    on both sides: the deposed primary refuses the from-the-future
    subscriber (`rejected_generation`, ERROR frame), and the promoted
    node captures the refusal in its ErrorStore instead of silently
    rewinding onto the dead branch."""
    import shutil
    import tempfile
    import time as _time
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore
    from siddhi_tpu.net.repl import WalReceiver
    from siddhi_tpu.net.server import NetServer

    work = tempfile.mkdtemp(prefix="siddhi_sb_")
    out = {"seed": seed, "pass": False}
    mgr_a = mgr_b = srv = None
    try:
        mgr_a = SiddhiManager()
        mgr_a.set_persistence_store(
            FileSystemPersistenceStore(work + "/asnap"))
        rt_a = mgr_a.create_app_runtime(
            "@app:durability('batch', dir='" + work + "/awal')\n"
            + REPL_APP)
        rt_a.start()
        srv = NetServer(lambda a, s: (_ for _ in ()).throw(KeyError(s)),
                        port=0, repl_resolve=lambda app: rt_a).start()
        mgr_b, rt_b = _repl_standby(srv.port, work + "/bwal",
                                    work + "/bsnap")
        tape = _k9_tape(seed, ["S"], 4, 64, 6)
        h = rt_a.input_handler("S")
        for rd in tape:
            cols, ts = rd["S"]
            h.send_batch(cols, ts)
        rt_a.flush()
        wm = rt_a.wal.watermark()
        deadline = _time.monotonic() + 20
        while _time.monotonic() < deadline and \
                rt_b.replication.applied_watermark() != wm:
            _time.sleep(0.02)
        report = rt_b.promote()         # A is now DEPOSED — but alive
        out["generation"] = report["generation"]
        # the misconfigured resubscribe: promoted B tails deposed A
        recv = WalReceiver(rt_b, rt_b.replication,
                           f"127.0.0.1:{srv.port}").start()
        try:
            deadline = _time.monotonic() + 20
            while _time.monotonic() < deadline and (
                    rt_a.replication is None
                    or rt_a.replication.rejected_generation < 1):
                _time.sleep(0.02)
        finally:
            recv.stop()
        a_rejected = (rt_a.replication is not None
                      and rt_a.replication.rejected_generation >= 1)
        b_captures = [e for e in rt_b.error_store.entries("_replication")
                      if "rejected" in e.message or "deposed" in e.message]
        # and B's own timeline was never rewound: its log still serves
        h2 = rt_b.input_handler("S")
        cols, ts = tape[0]["S"]
        h2.send_batch(cols, ts)
        rt_b.flush()
        out.update({
            "deposed_rejected_subscriber": a_rejected,
            "promoted_captured_refusal": len(b_captures),
            "promoted_still_serving":
                rt_b.wal.watermark()["S"] > wm["S"],
            "pass": bool(a_rejected and b_captures
                         and rt_b.wal.watermark()["S"] > wm["S"])})
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        if srv is not None:
            srv.stop()
        for m in (mgr_a, mgr_b):
            if m is not None:
                m.shutdown()
        shutil.rmtree(work, ignore_errors=True)
    return out


def durability_bench(smoke=True) -> dict:
    """The measured durability-overhead column: config-3 TCP-ingest eps
    per sync policy.  `'batch'` must cost <= 15% vs `'off'` (the bench
    `durability` field the acceptance criteria pin); `'fsync'` is
    reported for the honesty of the trade.  `'semi-sync'` is batch PLUS
    a live in-process hot standby whose append-ack the durable barrier
    waits on (@app:replication('semi-sync')) — it must cost <= 25% vs
    `'batch'` alone, measured at the same barrier cadence."""
    import shutil
    import tempfile
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.net import TcpFrameClient
    from siddhi_tpu.net.server import NetServer

    n = 1 << 12 if smoke else 1 << 15
    batch = 512 if smoke else 2048
    warm = 2
    tape = make_tape(n + warm * batch, batch)
    batches = _tape_str_batches(tape)
    n_timed = sum(t["n"] for t in tape[warm:])
    eps, matches = {}, {}
    tmp = tempfile.mkdtemp(prefix="siddhi_dur_bench_")
    try:
        for policy in ("off", "batch", "fsync", "semi-sync"):
            head = "@source(type='tcp', port='0')\n"
            if policy == "semi-sync":
                head = (f"@app:durability('batch', "
                        f"dir='{tmp}/wal_semi')\n"
                        f"@app:replication('semi-sync', "
                        f"ack.timeout='30 sec', heartbeat='25 ms')\n"
                        ) + head
            elif policy != "off":
                head = (f"@app:durability('{policy}', "
                        f"dir='{tmp}/wal_{policy}')\n") + head
            mgr = SiddhiManager()
            rt = mgr.create_app_runtime(head + DEV["patterns"] + C3)
            rows = []
            rt.add_batch_callback("Out", lambda b, rows=rows: rows.extend(
                map(tuple, b.rows(rt.strings))))
            rt.start()
            srv = mgr_s = None
            if policy == "semi-sync":
                # the hot standby the barrier waits on, in-process: a
                # replication front door on the primary + a standby
                # runtime tailing it (net/repl.py)
                srv = NetServer(
                    lambda a, s: (_ for _ in ()).throw(KeyError(s)),
                    port=0, repl_resolve=lambda app: rt).start()
                mgr_s = SiddhiManager()
                rt_s = mgr_s.create_app_runtime(
                    f"@app:name('DurStandby')\n"
                    f"@app:durability('batch', dir='{tmp}/wal_sb')\n"
                    f"@app:replication('async', role='standby', "
                    f"peer='127.0.0.1:{srv.port}')\n"
                    "define stream StockStream "
                    "(symbol string, price double, volume int);\n")
                rt_s.start()
            cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, STREAM,
                                 TcpFrameClient.cols_of_schema(
                                     rt.schemas[STREAM]))
            for cols, ts in batches[:warm]:
                cli.send_batch(cols, ts)
            cli.barrier(timeout=120)
            t0 = time.perf_counter()
            for cols, ts in batches[warm:]:
                cli.send_batch(cols, ts)
            cli.barrier(timeout=120)
            eps[policy] = round(n_timed / (time.perf_counter() - t0))
            matches[policy] = len(rows)
            cli.close()
            if srv is not None:
                srv.stop()
            if mgr_s is not None:
                mgr_s.shutdown()
            mgr.shutdown()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    overhead = {p: round(100.0 * (1.0 - eps[p] / eps["off"]), 1)
                for p in ("batch", "fsync")}
    # the semi-sync premium is measured against 'batch' ALONE — the
    # replication cost on top of the same local sync policy
    overhead["semi-sync_vs_batch"] = round(
        100.0 * (1.0 - eps["semi-sync"] / eps["batch"]), 1)
    identical = len(set(matches.values())) == 1
    return {"policy": "batch", "tcp_eps": eps,
            "overhead_pct": overhead, "events": n_timed,
            "batch": batch, "identical_matches": identical,
            "pass": bool(overhead["batch"] <= 15.0 and identical
                         and overhead["semi-sync_vs_batch"] <= 25.0)}


def chaos_bench(seed: int = 7) -> dict:
    """Seeded chaos harness (`--chaos [--seed N]`): runs the pattern,
    window, and join configs clean and then under injected faults
    (core/faults.py FaultInjector), asserting ZERO event loss and full
    recovery:

      * transient dispatch resource faults  -> ladder halves the work and
        retries; outputs byte-identical to the clean run
      * persistent dispatch resource faults -> plan quarantined onto the
        interpreter path; outputs byte-identical to the clean run
      * sink publish faults -> retried with backoff; payloads that
        exhaust retries are captured in the ErrorStore and REPLAYED once
        the transport recovers — every payload delivered exactly once

    Deterministic under a fixed seed: the injector's schedule and the
    backoff jitter both derive from it."""
    import warnings
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.faults import FaultInjector
    from siddhi_tpu.core.io import InMemoryBroker

    PATTERN = """
        @app:devicePatterns('prefer')
        @OnError(action='store')
        define stream S (sym string, p double);
        from every a=S[p > 120] -> b=S[p < 80] within 1 sec
        select a.sym as s1, b.sym as s2 insert into Out;
    """
    WINDOW = """
        @OnError(action='store')
        define stream S (sym string, p double);
        from S#window.length(64) select sym, sum(p) as s, count() as c
            group by sym insert into Out;
    """
    JOIN = """
        @OnError(action='store')
        define stream S (sym string, p double);
        define stream T (sym string, p double);
        from S#window.length(32) as a join T#window.length(32) as b
            on a.sym == b.sym
        select a.sym as sym, a.p as pa, b.p as pb insert into Out;
    """

    def feed(rt, streams, n_batches=8, batch=256, keys=8):
        rng = np.random.default_rng(seed)
        ts0 = 1_700_000_000_000
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
        handlers = [rt.input_handler(s) for s in streams]
        for k in range(n_batches):
            for h in handlers:
                h.send_batch(
                    {"sym": [f"K{i % keys}" for i in range(batch)],
                     "p": q4(rng.uniform(60.0, 140.0, batch))},
                    ts0 + np.arange(k * batch, (k + 1) * batch,
                                    dtype=np.int64) * 2)
            rt.flush()
        return sorted(map(tuple, rows))

    def run(app, streams, injector=None):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(app)
        rt.fault_injector = injector
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                rows = feed(rt, streams)
            lad = next(iter(rt._ladders.values()), None)
            return rows, {
                "halvings": lad.halvings if lad else 0,
                "quarantined": bool(rt.statistics().get("degraded_plans")),
                "injected": (rt.fault_injector.stats()["fired"]
                             if rt.fault_injector else {})}
        finally:
            mgr.shutdown()

    out = {"seed": seed, "configs": {}, "pass": True}
    for name, app, streams in (("pattern", PATTERN, ["S"]),
                               ("window", WINDOW, ["S"]),
                               ("join", JOIN, ["S", "T"])):
        clean, _ = run(app, streams)
        halved, info_h = run(app, streams,
                             FaultInjector(seed=seed,
                                           counts={"dispatch": 2}))
        quar, info_q = run(app, streams,
                           FaultInjector(seed=seed,
                                         counts={"dispatch": 10 ** 6}))
        cfg = {"matches": len(clean),
               "halving": {"identical": halved == clean, **info_h},
               "quarantine": {"identical": quar == clean, **info_q}}
        ok = (halved == clean and quar == clean and len(clean) > 0
              and info_h["halvings"] >= 1 and not info_h["quarantined"]
              and info_q["quarantined"])
        cfg["pass"] = ok
        out["configs"][name] = cfg
        out["pass"] = out["pass"] and ok

    # sink delivery under publish faults: retry, capture, replay
    SINK = """
        define stream S (x int);
        @sink(type='inMemory', topic='chaos_out', on.error='store',
              max.retries='2', retry.interval='1 ms',
              breaker.threshold='4', breaker.reset='50 ms')
        define stream Out (x int);
        from S select x insert into Out;
    """
    got = []
    InMemoryBroker.reset()
    InMemoryBroker.subscribe("chaos_out", lambda m: got.append(m[0]))
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(SINK)
    rt.fault_injector = FaultInjector(seed=seed,
                                      rates={"sink.publish": 0.4})
    rt.start()
    h = rt.input_handler("S")
    n_sink = 64
    for i in range(n_sink):
        h.send((i,))
        rt.flush()
    stored = len(rt.error_store)
    rt.fault_injector = None            # transport recovers
    replay = rt.error_store.replay(rt)
    sink = rt.sinks[0]
    sink_ok = (sorted(got) == list(range(n_sink))
               and replay["remaining"] == 0)
    out["sink"] = {"delivered": len(got), "expected": n_sink,
                   "retries": sink.retries, "stored_then_replayed": stored,
                   "breaker_opens": sink.metrics().get("circuit_opens", 0),
                   "pass": sink_ok}
    out["pass"] = out["pass"] and sink_ok
    mgr.shutdown()

    # serving-plane chaos: mid-frame disconnects, slow shm consumer,
    # injected ingest faults (zero admitted-frame loss throughout)
    net = _safe("chaos net", lambda: chaos_net(seed), {"pass": False})
    out["net"] = net
    out["pass"] = out["pass"] and bool(net.get("pass"))

    # durability chaos: SIGKILL at fault-injected points (mid-wal.append,
    # mid-snapshot), recover, prove exactly-once per config
    k9 = _safe("chaos kill9", lambda: chaos_kill9(seed), {"pass": False})
    out["kill9"] = k9
    out["pass"] = out["pass"] and bool(k9.get("pass"))

    # queryable-state chaos: SIGKILL mid-flush on a durable aggregation,
    # recover, prove the bucket store itself is byte-identical
    a9 = _safe("chaos agg kill9", lambda: chaos_agg_kill9(seed),
               {"pass": False})
    out["agg_kill9"] = a9
    out["pass"] = out["pass"] and bool(a9.get("pass"))

    # machine-loss chaos: SIGKILL the primary PROCESS (its disk is
    # gone), promote the hot standby, resume the producer — lossless
    ml = _safe("chaos machine loss", lambda: chaos_machine_loss(seed),
               {"pass": False})
    out["machine_loss"] = ml
    out["pass"] = out["pass"] and bool(ml.get("pass"))

    # split-brain: the deposed primary is alive; fencing rejects its
    # timeline loudly on both sides
    sb = _safe("chaos split brain", lambda: chaos_split_brain(seed),
               {"pass": False})
    out["split_brain"] = sb
    out["pass"] = out["pass"] and bool(sb.get("pass"))

    # measured durability overhead per sync policy ('batch' <= 15%)
    dur = _safe("durability overhead", lambda: durability_bench(smoke=True),
                {"pass": False})
    out["durability"] = dur
    out["pass"] = out["pass"] and bool(dur.get("pass"))
    return out


def _print_summary(summary: dict, cap: int = 2048) -> None:
    """Emit the machine-parseable summary as the FINAL stdout line,
    bounded to `cap` bytes: drivers keep only a stdout tail and parse
    its last line, so an oversized line truncates into garbage (the
    BENCH "parsed": null failure shape).  Oversize degrades by dropping
    detail keys — never by emitting an unparseable line.  The bound is
    HARD: if dropping detail keys still leaves the line over cap (or a
    value fails to serialize), a minimal headline line prints instead,
    so the last stdout line ALWAYS round-trips through json.loads
    (pinned by scripts/smoke.sh and tests/test_bench_summary.py)."""
    drop_order = ("stage_shares_config3", "configs", "roofline",
                  "transport", "trace_coverage_config3", "tracing",
                  "profile", "harness", "durability", "placement")
    try:
        line = json.dumps(summary)
        for key in drop_order:
            if len(line) <= cap:
                break
            summary.pop(key, None)
            line = json.dumps(summary)
    except (TypeError, ValueError):        # non-serializable value crept in
        line = None
    if line is None or len(line) > cap:
        line = json.dumps({k: summary.get(k) for k in
                           ("metric", "value", "unit", "vs_baseline",
                            "detail")
                           if isinstance(summary.get(k),
                                         (str, int, float, type(None)))})
    sys.stderr.flush()
    print(line, flush=True)


def pattern_families_smoke() -> dict:
    """`bench.py --family-smoke` (scripts/smoke.sh): one eligible pattern
    per plan family, run differentially against the host interpreter —
    a lowering regression in any family fails fast, in CI time budget.
    Includes the ISSUE-13 lowerings: a count-quantifier cell (rank/
    select chase) and a partitioned-lanes parity cell (the lane-vmapped
    flat block vs per-key host clones)."""
    from siddhi_tpu import SiddhiManager

    C_COUNT = STOCK + (
        "@info(name='q') from every e1=StockStream[price > 110]<1:3> -> "
        "e2=StockStream[price < 95] within 1 sec "
        "select e1[0].price as a, e1[last].price as b, e2.price as c "
        "insert into Out;\n")

    CASES = {
        # family -> (annotation head, query): each query is eligible for
        # the family it exercises (asserted below via plan.family)
        "seq": ("@app:patternFamily('seq')\n", C3),
        "chunk": ("@app:patternFamily('chunk')\n", C3),
        "scan": ("@app:patternFamily('scan')\n", C3),
        "dfa": ("@app:patternFamily('dfa')\n", C3S),
        "scan_count": ("@app:patternFamily('scan')\n", C_COUNT),
        "dfa_count": ("@app:patternFamily('dfa')\n", C_COUNT),
    }

    def run(app, n=1024, batch=256, keys=8, sort=False):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(app)
        rows = []
        rt.add_batch_callback("Out", lambda b: rows.extend(
            map(tuple, b.rows(rt.strings))))
        rt.start()
        h = rt.input_handler(STREAM)
        from siddhi_tpu.core.pattern_plan import DevicePatternPlan
        fam = next((p.family for p in rt._plans
                    if isinstance(p, DevicePatternPlan)), None)
        tape = make_tape(n, batch, keys=keys)
        for cols, ts in _columnar(rt, STREAM, tape, keys):
            h.send_batch(cols, ts)
        rt.flush()
        mgr.shutdown()
        return fam, sorted(rows) if sort else rows

    out = {"families": {}, "pass": True}
    for cell, (ann, q) in CASES.items():
        fam = cell.split("_")[0]
        used, dev = run(ann + DEV["patterns"] + q)
        _u, host = run(HOST["patterns"] + q)
        ok = used == fam and dev == host and len(dev) > 0
        out["families"][cell] = {"engaged": used, "matches": len(dev),
                                 "host_matches": len(host),
                                 "identical": dev == host, "pass": ok}
        out["pass"] = out["pass"] and ok

    # partitioned-lanes parity: config 4's shape at smoke scale, default
    # family selection (must be a parallel one), per-key host clones as
    # the oracle; cross-key delivery order is not defined -> sorted
    used, dev = run("@app:partitionCapacity(64)\n" + C4,
                    keys=48, sort=True)
    _u, host = run(HOST["patterns"] + C4, keys=48, sort=True)
    ok = used in ("scan", "dfa") and dev == host and len(dev) > 0
    out["families"]["partitioned_lanes"] = {
        "engaged": used, "matches": len(dev), "host_matches": len(host),
        "identical": dev == host, "pass": ok}
    out["pass"] = out["pass"] and ok
    return out


# ---------------------------------------------------------------------------
# queryable-state workload matrix (`--matrix`): DEBS-style rollup shapes
# over the aggregation plane, every cell asserting device-vs-host parity
# (docs/AGGREGATION.md)
# ---------------------------------------------------------------------------

MATRIX_TS0 = 1_700_000_000_000


def _matrix_app(head=""):
    return (head +
            "define stream Trades "
            "(sym string, p double, v double, ts long);\n"
            "define aggregation Roll\n"
            "from Trades\n"
            "select sym, sum(p * v) as turnover, avg(p) as mean, "
            "min(p) as lo, max(p) as hi, count() as n\n"
            "group by sym\n"
            "aggregate by ts every sec, min, hour;\n")


def _matrix_tape(n_batches, batch, keys, seed=13):
    rng = np.random.default_rng(seed)
    out = []
    for k in range(n_batches):
        ts = (MATRIX_TS0 + k * 1500
              + np.sort(rng.integers(0, 1500, batch)))
        out.append((
            {"sym": np.array([f"G{i}" for i in
                              rng.integers(0, keys, batch)]),
             "p": rng.uniform(10, 500, batch),
             "v": rng.uniform(1, 50, batch),
             "ts": ts.astype(np.int64)},
            ts.astype(np.int64)))
    return out


def _matrix_query(per="min"):
    return (f"from Roll within {MATRIX_TS0 - 3_600_000}L, "
            f"{MATRIX_TS0 + 86_400_000}L per {per!r} "
            f"select sym, turnover, mean, lo, hi, n")


def matrix_bench(smoke=False) -> dict:
    """Queryable-state workload matrix (`--matrix`): DEBS-grand-challenge
    shaped cells over `define aggregation`:

      * rollup_kN — ingest-only rollup sweep across group-by
        cardinalities; per-cell differential against the forced-host
        path (`@app:deviceAggregations('off')`) across EVERY duration
      * mixed     — interleaved ingest + store queries on one thread
        (the dashboard-refresh shape); in-process store-query p99
      * wire      — paced TCP producer thread + a second connection
        issuing concurrent wire store queries; client-observed p99 and
        final wire-vs-inproc row parity

    Per-cell summary (eps + store_query_p99_ms + parity) lands in
    BENCH_DETAIL.json; the final stdout line is machine-parseable."""
    import threading
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.net import TcpFrameClient

    n_batches = 8 if smoke else 24
    batch = 512 if smoke else 4096
    key_sweep = (8, 64) if smoke else (8, 128, 1024)
    pers = ("sec", "min", "hour")

    def run_inproc(head, keys, query_every=0):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(_matrix_app(head))
        rt.start()
        h = rt.input_handler("Trades")
        tape = _matrix_tape(n_batches, batch, keys)
        qlat = []
        t0 = time.perf_counter()
        for i, (cols, ts) in enumerate(tape):
            h.send_batch(cols, ts)
            if query_every and (i + 1) % query_every == 0:
                tq = time.perf_counter()
                rt.query(_matrix_query())
                qlat.append((time.perf_counter() - tq) * 1e3)
        rt.flush()
        elapsed = time.perf_counter() - t0
        rows = {per: sorted(rt.query(_matrix_query(per)))
                for per in pers}
        path = rt.explain()["aggregations"]["Roll"]["path"]
        sq = (rt.statistics().get("aggregation", {})
              .get("store_query", {}))
        mgr.shutdown()
        return rows, elapsed, path, qlat, sq

    out = {"smoke": smoke, "events_per_cell": n_batches * batch,
           "cells": {}, "pass": True}

    # rollup cardinality sweep: device vs forced-host differential
    host_rows = {}
    for keys in key_sweep:
        dev_rows, el, path, _, _ = run_inproc("", keys)
        hrows, _, hpath, _, _ = run_inproc(
            "@app:deviceAggregations('off')\n", keys)
        host_rows[keys] = hrows
        parity = dev_rows == hrows
        ok = (parity and path == "device-resident" and hpath == "host"
              and all(len(v) > 0 for v in dev_rows.values()))
        out["cells"][f"rollup_k{keys}"] = {
            "keys": keys, "eps": round(n_batches * batch / el),
            "path": path, "parity": parity,
            "rows": {per: len(v) for per, v in dev_rows.items()},
            "pass": ok}
        out["pass"] = out["pass"] and ok

    # mixed ingest + store-query load on one thread
    mkeys = key_sweep[-1]
    mrows, mel, mpath, qlat, msq = run_inproc("", mkeys, query_every=1)
    mok = (mrows == host_rows[mkeys] and mpath == "device-resident"
           and len(qlat) == n_batches)
    out["cells"]["mixed"] = {
        "keys": mkeys, "eps": round(n_batches * batch / mel),
        "store_queries": len(qlat),
        "store_query_p99_ms": round(float(np.percentile(qlat, 99)), 3),
        "tracker_p99_ms": msq.get("p99_ms"),
        "parity": mrows == host_rows[mkeys], "pass": mok}
    out["pass"] = out["pass"] and mok

    # concurrent wire store queries under paced TCP ingest
    wkeys = key_sweep[0]
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        _matrix_app("@source(type='tcp', port='0')\n"))
    rt.start()
    port = rt.sources[0].port
    cols_spec = TcpFrameClient.cols_of_schema(rt.schemas["Trades"])
    tape = _matrix_tape(n_batches, batch, wkeys)
    stop = threading.Event()
    feed_err = []

    def feed():
        cli = TcpFrameClient("127.0.0.1", port, "Trades", cols_spec)
        try:
            for cols, ts in tape:
                cli.send_batch(cols, ts)
                time.sleep(0.001)      # paced: leave room for queries
            cli.barrier(timeout=300)
        except Exception as e:          # surfaced in the cell result
            feed_err.append(repr(e))
        finally:
            stop.set()
            cli.close()

    qcli = TcpFrameClient("127.0.0.1", port, "Trades", cols_spec)
    th = threading.Thread(target=feed)
    t0 = time.perf_counter()
    th.start()
    wlat = []
    while not stop.is_set() or not wlat:
        tq = time.perf_counter()
        qcli.query(_matrix_query(), timeout=120)
        wlat.append((time.perf_counter() - tq) * 1e3)
    th.join()
    elapsed = time.perf_counter() - t0
    wire_rows = sorted(qcli.query(_matrix_query(), timeout=120))
    inproc_rows = sorted(rt.query(_matrix_query()))
    qcli.close()
    wsq = rt.statistics().get("aggregation", {}).get("store_query", {})
    mgr.shutdown()
    wok = (not feed_err and wire_rows == inproc_rows
           and len(wire_rows) > 0)
    out["cells"]["wire"] = {
        "keys": wkeys, "eps": round(n_batches * batch / elapsed),
        "store_queries": len(wlat),
        "store_query_p99_ms": round(float(np.percentile(wlat, 99)), 3),
        "tracker_p99_ms": wsq.get("p99_ms"),
        "parity": wire_rows == inproc_rows,
        "feed_errors": feed_err, "pass": wok}
    out["pass"] = out["pass"] and wok
    return out


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if "--chaos-child" in argv:
        # hidden subprocess mode for the kill-9 durability chaos: feeds
        # the scripted tape and SIGKILLs itself at the armed point
        chaos_kill9_child(argv[argv.index("--chaos-child") + 1])
        return
    if "--chaos-repl-child" in argv:
        # hidden subprocess mode for the machine-loss chaos: runs the
        # PRIMARY (durable app + replication front door) and SIGKILLs
        # itself at the armed point
        chaos_repl_child(argv[argv.index("--chaos-repl-child") + 1])
        return
    if "--family-smoke" in argv:
        res = pattern_families_smoke()
        print(json.dumps({"metric": "plan_family_parity",
                          "value": 1 if res["pass"] else 0,
                          "unit": "all_families_match_interpreter", **res}))
        if not res["pass"]:
            sys.exit(1)
        return
    if "--net" in argv:
        # serving-plane bench (docs/SERVING.md): REST vs TCP vs shm vs
        # in-process on config 3, byte-identical differential, paced 2x
        # overload with shed accounting + replay; --smoke shrinks for CI
        res = net_bench(smoke="--smoke" in argv)
        print(json.dumps({"metric": "net_serving_plane",
                          "value": res["tcp_vs_rest"],
                          "unit": "tcp_frame_eps_over_per_event_rest",
                          **res}))
        if not res["pass"]:
            sys.exit(1)
        return
    if "--matrix" in argv:
        # queryable-state workload matrix (docs/AGGREGATION.md): rollup
        # cardinality sweep + mixed query/ingest + concurrent wire
        # store queries, each cell device-vs-host parity-checked;
        # --smoke shrinks it for scripts/smoke.sh
        res = matrix_bench(smoke="--smoke" in argv)
        detail = {"harness": harness_info(), "matrix": res}
        with open("BENCH_DETAIL.json", "w") as f:
            json.dump(detail, f, indent=1, default=str)
        print(json.dumps({
            "metric": "queryable_state_matrix",
            "value": 1 if res["pass"] else 0,
            "unit": "all_cells_device_host_parity",
            "cells": {k: {"eps": c.get("eps"),
                          "store_query_p99_ms":
                              c.get("store_query_p99_ms"),
                          "parity": c.get("parity", c.get("pass"))}
                      for k, c in res["cells"].items()},
            "detail": "BENCH_DETAIL.json"}))
        if not res["pass"]:
            sys.exit(1)
        return
    if "--chaos" in argv:
        seed = 7
        if "--seed" in argv:
            seed = int(argv[argv.index("--seed") + 1])
        res = chaos_bench(seed)
        print(json.dumps({"metric": "chaos_recovery",
                          "value": 1 if res["pass"] else 0,
                          "unit": "all_recovery_paths_lossless", **res}))
        if not res["pass"]:
            sys.exit(1)
        return
    if "--autotune" in argv:
        # tuner-driven frontier sweep (before/after eps + p99 per config)
        # + the @app:latencySLO AIMD controller demo; --smoke shrinks it
        # to one config for the CI budget (scripts/smoke.sh)
        res = autotune_bench(smoke="--smoke" in argv)
        print(json.dumps({"metric": "autotune_sweep",
                          "value": 1 if res["pass"] else 0,
                          "unit": "tuned_geometry_matches_or_beats_hand",
                          **res}))
        if not res["pass"]:
            sys.exit(1)
        return
    if "--trace" in argv:
        # fast mode: per-stage breakdown (the diagnosability check —
        # where does a detect-latency millisecond go?) of config 3 AND
        # the partitioned config 4, each naming its chosen plan family
        # and the profiler-attributed kernel-vs-host-dispatch split
        # (ROADMAP item 2's measurement), plus the frame-tracing and
        # phase-profiler overhead contracts.  --trace MUST be checked
        # before --smoke: `--trace --smoke` is the perfcheck sentinel's
        # input (scripts/perfcheck.py) and used to silently run the
        # bench_overlap smoke instead.  --smoke shrinks the tapes.
        smoke = "--smoke" in argv
        tr = trace_breakdown(DEV["patterns"] + C3,
                             n_batches=8 if smoke else 16,
                             batch=1024 if smoke else 2048)
        head4 = "@app:partitionCapacity(1000)\n@app:deviceSlots(32)\n"
        tr4 = _safe("trace config4", lambda: trace_breakdown(
            head4 + C4, n_batches=4 if smoke else 8,
            batch=1024 if smoke else 2048, keys=1000,
            trace_out="bench_trace_c4.json"), {})
        ov = _safe("tracing overhead",
                   lambda: tracing_overhead(smoke=True), {})
        pov = _safe("profile overhead",
                    lambda: profile_overhead(smoke=True), {})
        print(json.dumps({"metric": "stage_breakdown_config3",
                          "value": tr["coverage"],
                          "unit": "fraction_of_e2e_latency_attributed",
                          **tr,
                          "config4": {k: tr4.get(k) for k in
                                      ("eps", "coverage", "plan_family",
                                       "kernel_share",
                                       "host_dispatch_share",
                                       "profile")},
                          "tracing_overhead": ov,
                          "profile_overhead": pov,
                          "harness": _safe("harness", harness_info, {})}))
        return
    if "--smoke" in argv:
        # CI sanity (scripts/smoke.sh): a short pipelined-vs-unpipelined
        # run over the multi-plan config — asserts identical match
        # counts (inside bench_overlap) and prints the eps delta, so
        # overlap regressions surface in tier-1 time budget
        res = bench_overlap(n=1 << 12, batch=1 << 10, repeats=1, depth=2)
        print(json.dumps({
            "metric": "pipelined_vs_unpipelined_smoke",
            "value": res["overlap_speedup"],
            "unit": "eps_ratio",
            "eps_pipelined": res["device_eps"],
            "eps_unpipelined": res["unpipelined_eps"],
            "overlap_ratio": res["overlap_ratio"],
            "matches": res["matches"],
        }))
        return
    t0 = time.perf_counter()
    configs = {}

    configs["1_filter"] = bench_config(
        "filter", PIPE + DEV["filters"] + C1, HOST["filters"] + C1,
        n=1 << 19, batch=1 << 18, repeats=5)
    configs["1_filter"]["kernel_eps"] = kernel_eps(
        DEV["filters"] + C1, "filter", batch=1 << 18)
    _mark("config 1 done", t0)

    configs["2_window_agg"] = bench_config(
        "window", PIPE + DEV["windows"] + C2, HOST["windows"] + C2,
        n=1 << 18, batch=1 << 17, repeats=5)
    configs["2_window_agg"]["kernel_eps"] = kernel_eps(
        DEV["windows"] + C2, "window", batch=1 << 17)
    _mark("config 2 done", t0)

    configs["3_sequence"] = bench_config(
        "sequence", PIPE + DEV["patterns"] + C3, HOST["patterns"] + C3,
        n=1 << 18, batch=1 << 17, latency=True,
        lat_dev_app=DEV["patterns"] + C3)
    info3: dict = {}
    configs["3_sequence"]["kernel_eps"] = kernel_eps(
        DEV["patterns"] + C3, "pattern", batch=1 << 17, info=info3)
    configs["3_sequence"]["plan_family"] = info3.get("plan_family")
    # per-family kernel roofline sweep (the plan-family axis): same tape,
    # same batch, each family forced via @app:patternFamily; the "dfa"
    # family needs a static transition, so it sweeps the C3S variant
    # next to "scan" on the same tape for a like-for-like column

    def _fam_eps(fam, app):
        # a forced-but-ineligible family falls back with a warning; the
        # roofline must never mislabel the fallback's throughput, so the
        # ENGAGED family is checked and mismatches are reported as such
        inf: dict = {}
        eps = kernel_eps(app, "pattern", batch=1 << 17, info=inf)
        used = inf.get("plan_family")
        if used != fam:
            return {"eps": eps, "engaged": used, "requested": fam}
        return eps

    configs["3_sequence"]["kernel_eps_by_family"] = {
        fam: _safe(f"kernel_eps family {fam}", lambda fam=fam: _fam_eps(
            fam, f"@app:patternFamily('{fam}')\n" + DEV["patterns"] + C3))
        for fam in ("seq", "chunk", "scan")}
    configs["3_sequence"]["kernel_eps_static_by_family"] = {
        fam: _safe(f"kernel_eps static family {fam}",
                   lambda fam=fam: _fam_eps(
                       fam, f"@app:patternFamily('{fam}')\n"
                       + DEV["patterns"] + C3S))
        for fam in ("scan", "dfa")}
    _mark("config 3 done", t0)

    # latency/throughput frontier for the CEP sequence config (the
    # micro-batch size is the knob, VERDICT r3 #3) — measured HERE, before
    # the expensive configs 4/5, so a slow run degrades those first
    c3 = configs["3_sequence"]
    # the largest frontier point reuses config 3's measured eps but gets
    # a REAL p99 (it used to report null): measured unpipelined, like
    # every other frontier point
    big = c3["batch"]
    # the largest frontier point gets a REAL measured p99 like every
    # other point: warmed (and flushed) before timing — the same
    # treatment config 6 got in PR 5 (BENCH_r05 still recorded null)
    c3["frontier"] = _safe("frontier", lambda: frontier(
        DEV["patterns"] + C3, HOST["patterns"] + C3,
        deadline=t0 + 420), []) + [
        {"batch": big, "eps": c3["device_eps"],
         "p99_ms": _safe("big-point p99", lambda: p99_latency(
             DEV["patterns"] + C3, STREAM,
             make_tape(big * 8, big), 8, warm=4))}]
    c3["latency_demo"] = _safe("latency_demo", lambda: latency_demo(
        DEV["patterns"] + C3, HOST["patterns"] + C3))
    c3["trace"] = _safe("trace", lambda: trace_breakdown(
        DEV["patterns"] + C3), {})
    _mark("frontier + latency demo + trace done", t0)

    head = ("@app:partitionCapacity(1000)\n@app:deviceSlots(32)\n")
    configs["4_partitioned_1k"] = bench_config(
        "partitioned", head + C4, HOST["patterns"] + C4,
        n=2 << 18, batch=1 << 18, keys=1000, latency=True, repeats=5)
    info4: dict = {}
    configs["4_partitioned_1k"]["kernel_eps"] = kernel_eps(
        head + C4, "pattern", batch=1 << 18, keys=1000, info=info4)
    configs["4_partitioned_1k"]["plan_family"] = info4.get("plan_family")
    # per-config stage breakdown (BENCH_DETAIL.json): the partitioned
    # config's plan family + kernel-vs-host-dispatch split, small scale
    configs["4_partitioned_1k"]["trace"] = _safe(
        "trace config4", lambda: trace_breakdown(
            head + C4, n_batches=8, batch=2048, keys=1000,
            trace_out="bench_trace_c4.json"), {})

    c5 = c5_app(1000)
    c5_outs = tuple(f"Out{i}" for i in range(16))
    configs["5_1k_mixed_queries"] = bench_config(
        "1k-queries", c5, HOST["patterns"] + c5,
        n=1 << 11, batch=1 << 10, dt_ms=50, warm=2,
        out_streams=c5_outs, check_matches=True)
    configs["5_1k_mixed_queries"]["note"] = \
        ("device = 4 fused multi-query kernels (250 lanes each), median of "
         "3 x 2048-event segments; host = 1000 sequential matchers")

    configs["6_join"] = bench_join(n=1 << 15, batch=4096)

    configs["8_multi_plan_overlap"] = bench_overlap()

    # externalTimeBatch window row (device kind added r5): same tape but
    # with an event-time column driving the tumbling buckets
    def et_tape_cols(rt, tape):
        codes = np.array([rt.strings.encode(f"K{i}") for i in range(8)],
                         dtype=np.int32)
        return [({"symbol": codes[t["sym_idx"]], "price": t["price"],
                  "volume": t["volume"], "et": t["ts"]}, t["ts"])
                for t in tape]

    def run_etb(app, tape, repeats):
        from siddhi_tpu import SiddhiManager
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(app)
        counted = [0]
        rt.add_batch_callback("Out", lambda b: counted.__setitem__(
            0, counted[0] + b.n))
        rt.start()
        h = rt.input_handler(STREAM)
        batches = et_tape_cols(rt, tape)
        for cols, ts in batches[:1]:
            h.send_batch(cols, ts)
        rt.flush()
        warm_m = counted[0]
        timed = batches[1:]
        seg = max(1, len(timed) // repeats)
        eps_runs, m1 = [], 0
        for r in range(repeats):
            part = timed[r * seg:(r + 1) * seg]
            if not part:
                break
            n_seg = sum(int(t[1].shape[0]) for t in part)
            tt = time.perf_counter()
            for cols, ts in part:
                h.send_batch(cols, ts)
            rt.flush()
            eps_runs.append(n_seg / (time.perf_counter() - tt))
            if r == 0:
                m1 = counted[0] - warm_m
        mgr.shutdown()
        return float(np.median(eps_runs)), m1, [round(e) for e in eps_runs]

    etb_tape = make_tape((1 << 17) * 3 + (1 << 16), 1 << 16)
    d_eps, d_m, d_runs = run_etb(
        PIPE + DEV["windows"] + C2B, etb_tape, 3)
    h_eps, h_m, _ = run_etb(HOST["windows"] + C2B,
                            etb_tape[:1 + (1 << 17) // (1 << 16)], 1)
    assert d_m == h_m and d_m > 0, (d_m, h_m)
    configs["7_external_time_batch"] = {
        "device_eps": round(d_eps), "device_eps_runs": d_runs,
        "host_eps": round(h_eps), "speedup": round(d_eps / h_eps, 2),
        "events": 1 << 17, "batch": 1 << 16, "matches": d_m,
        "note": "grouped externalTimeBatch(et, 64ms) tumbling buckets"}
    _mark("configs 4+5+6+7 done", t0)

    # non-Python calibration column (VERDICT r3 #9): no JVM exists in
    # this image, so an -O2 C++ run of the same matcher algorithms on
    # the same tape distribution stands in as a conservative UPPER bound
    # for single-JVM single-thread throughput on this hardware
    nat = _safe("native baseline", native_baseline, {})
    nat_of = {"1_filter": "filter", "2_window_agg": "window",
              "3_sequence": "sequence", "4_partitioned_1k": "partitioned"}
    for cfg, key in nat_of.items():
        if key in nat:
            configs[cfg]["native_cpp_eps"] = nat[key]["eps"]
            configs[cfg]["vs_native_cpp"] = round(
                configs[cfg]["device_eps"] / nat[key]["eps"], 2)
    _mark("native baseline done", t0)

    # roofline block (ROADMAP item 2 trajectory): per-config device
    # KERNEL eps vs the single-thread native C++ roofline, for the
    # WINNING plan family — the gap this PR's parallel-in-time families
    # exist to close, tracked per run
    roofline = {}
    for cfg in ("3_sequence", "4_partitioned_1k"):
        c = configs.get(cfg, {})
        ke, ne = c.get("kernel_eps"), c.get("native_cpp_eps")
        roofline[cfg] = {
            "plan_family": c.get("plan_family"),
            "kernel_eps": ke,
            "native_cpp_eps": ne,
            "vs_native_cpp": round(ke / ne, 4) if ke and ne else None,
        }
    roofline["3_sequence"]["kernel_eps_by_family"] = \
        configs["3_sequence"].get("kernel_eps_by_family")
    roofline["3_sequence"]["kernel_eps_static_by_family"] = \
        configs["3_sequence"].get("kernel_eps_static_by_family")

    # serving-plane transport column (ROADMAP item 3): a smoke-scale
    # net bench so every full run reports wire vs in-process ingest
    net_res = _safe("net transport smoke",
                    lambda: net_bench(smoke=True), {})
    _mark("net transport smoke done", t0)

    # durability-overhead column (ROADMAP item 5): TCP ingest eps per
    # sync policy on the config-3 schema — 'batch' must stay within 15%
    # of 'off' for durable serving to be the production default
    dur_res = _safe("durability overhead",
                    lambda: durability_bench(smoke=True), {})
    _mark("durability overhead done", t0)

    # tracing-overhead column (ISSUE 15): the frame-tracing plane must
    # cost <= 5% of config-3 TCP-ingest eps when off or on-but-unsampled
    trace_ov = _safe("tracing overhead",
                     lambda: tracing_overhead(smoke=True), {})
    _mark("tracing overhead done", t0)

    # profiler-overhead column (ISSUE 17): the phase profiler at the
    # default 1-in-32 duty cycle must cost <= 3% of config-3 TCP-ingest
    # eps vs @app:profile('off') — the always-on acceptance bar
    prof_ov = _safe("profile overhead",
                    lambda: profile_overhead(smoke=True), {})
    _mark("profile overhead done", t0)

    # transport-vs-host-vs-kernel breakdown per config: the
    # "transport-bound" calibration note as a MEASURED column.  For each
    # config: the kernel-only ceiling, the end-to-end in-process engine
    # rate (kernel + host dispatch), and the wire ceiling (loopback TCP
    # frames, measured on the config-3 schema at smoke scale — the
    # schema every numbered config shares).  `bound` names the limiter:
    # the wire when it is slower than the engine, else host dispatch
    # when >half the end-to-end time is outside the kernel, else the
    # kernel itself.
    wire_eps = (net_res.get("transport") or {}).get("tcp_eps")
    breakdown = {}
    for cfg, c in sorted(configs.items()):
        de, ke = c.get("device_eps"), c.get("kernel_eps")
        if not de:
            continue
        row = {"engine_eps": de}
        if ke:
            row["kernel_eps"] = ke
            row["host_share"] = round(max(0.0, 1.0 - de / ke), 3)
        if wire_eps:
            row["wire_tcp_eps"] = wire_eps
            row["wire_vs_engine"] = round(wire_eps / de, 2)
        if wire_eps and wire_eps < de:
            row["bound"] = "transport"
        elif ke and de / ke < 0.5:
            row["bound"] = "host"
        elif ke:
            row["bound"] = "kernel"
        breakdown[cfg] = row

    h = configs["4_partitioned_1k"]
    detail = {
        "harness": _safe("harness", harness_info, {}),
        "metric": "partitioned_pattern_throughput_1k_keys",
        "value": h["device_eps"],
        "unit": "events/sec",
        "vs_baseline": h["speedup"],
        "vs_production_claim": round(h["device_eps"] / PROD_CLAIM_EPS, 2),
        "p99_detect_ms": h.get("p99_detect_ms"),
        "calibration": {
            "host_eps": "single-threaded python interpreter (measured, "
                        "same tapes) — the matched-conditions baseline",
            "vs_production_claim": "device headline over the reference "
                                   "README's ~300k eps production anchor "
                                   "(engine-level comparison)",
            "native_cpp_eps": "-O2 C++ of the same matcher algorithm, no "
                              "engine around it (no event model, dispatch, "
                              "or output materialization) — an upper bound "
                              "for any single-thread CPU engine incl. a "
                              "JVM; the reference engine's own production "
                              "anchor sits ~1000x below this roofline",
            "transport": "device numbers ride a tunneled TPU (~100 ms "
                         "fixed pull latency, ~10-25 MB/s): transfers, "
                         "not compute, bound most configs here",
        },
        "roofline": roofline,
        "transport": net_res,
        "durability": dur_res,
        "tracing": trace_ov,
        "profile": prof_ov,
        "transport_breakdown": breakdown,
        "configs": configs,
    }
    def _write_detail():
        with open("BENCH_DETAIL.json", "w") as f:
            json.dump(detail, f, indent=1)
    _safe("detail file", _write_detail)
    # ONE short stdout line: drivers keep only the stdout TAIL, so the
    # full per-config detail (which blew past their capture window —
    # BENCH "parsed": null) goes to BENCH_DETAIL.json and the parseable
    # summary stays bounded; _print_summary degrades the payload rather
    # than ever emitting an oversized/unparseable final line
    tr = c3.get("trace") or {}
    summary = {
        "metric": detail["metric"], "value": detail["value"],
        "unit": detail["unit"], "vs_baseline": detail["vs_baseline"],
        "vs_production_claim": detail["vs_production_claim"],
        "p99_detect_ms": detail["p99_detect_ms"],
        "trace_coverage_config3": tr.get("coverage"),
        "stage_shares_config3": {st: d.get("share") for st, d in
                                 tr.get("stages", {}).items()},
        # the tracing plane's overhead contract: off vs on-but-unsampled
        # TCP-ingest eps (<= 5% — docs/OBSERVABILITY.md overhead table)
        "tracing": ({"eps": trace_ov.get("eps"),
                     "unsampled_overhead_pct":
                         trace_ov.get("unsampled_overhead_pct"),
                     "sampled_16_overhead_pct":
                         trace_ov.get("sampled_16_overhead_pct"),
                     "pass": trace_ov.get("pass")}
                    if trace_ov else None),
        # the phase profiler's overhead contract: default 1-in-32 duty
        # cycle vs @app:profile('off') TCP-ingest eps (<= 3% — ISSUE 17)
        "profile": ({"sampled_32_overhead_pct":
                         prof_ov.get("sampled_32_overhead_pct"),
                     "pass": prof_ov.get("pass")}
                    if prof_ov else None),
        "harness": detail["harness"] or None,
        "roofline": {k: {kk: v.get(kk) for kk in
                         ("plan_family", "kernel_eps", "vs_native_cpp")}
                     for k, v in roofline.items()},
        # the serving-plane transport column: wire ingest eps by
        # transport (net_bench smoke scale) + the REST multiple
        "transport": ({**net_res.get("transport", {}),
                       "tcp_vs_rest": net_res.get("tcp_vs_rest"),
                       "identical": net_res.get("identical")}
                      if net_res else None),
        "configs": {k: {"eps": v["device_eps"], "speedup": v["speedup"],
                        **({"p99_ms": v["p99_detect_ms"]}
                           if v.get("p99_detect_ms") is not None else {}),
                        **({"bound": breakdown[k]["bound"]}
                           if breakdown.get(k, {}).get("bound") else {})}
                    for k, v in configs.items()},
        # durability column (sync policy + measured overhead vs 'off'):
        # LAST in the oversize drop_order, like placement, so the
        # exactly-once serving trade survives into the final line unless
        # nothing else is left to drop (a parseable line always wins)
        "durability": ({"policy": dur_res.get("policy"),
                        "overhead_pct": dur_res.get("overhead_pct"),
                        "tcp_eps": (dur_res.get("tcp_eps") or {}).get(
                            "batch")}
                       if dur_res else None),
        # device/interpreter query counts per config (placement plane,
        # docs/ANALYSIS.md): a future silent demotion shifts these
        # numbers in the bench trajectory — dropped only as the final
        # resort before the minimal-headline fallback
        "placement": {k: "{}d/{}i/{}dem".format(
                          v["placement"].get("device", 0),
                          v["placement"].get("interpreter", 0),
                          v["placement"].get("interp_demotions", 0))
                      for k, v in configs.items() if v.get("placement")},
        "detail": "BENCH_DETAIL.json",
    }
    _print_summary(summary)


if __name__ == "__main__":
    main()
