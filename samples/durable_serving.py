"""Durable serving quickstart: admitted-frame WAL + snapshot-coordinated
crash recovery (docs/RELIABILITY.md "Durability & exactly-once recovery").

A pattern app runs with `@app:durability('fsync')`: every admitted frame
appends to a CRC-per-record write-ahead log before it is processed, and
`persist()` records the per-stream durable watermark in the snapshot
revision.  The demo feeds frames, snapshots mid-stream, feeds more,
"crashes" (abandons the runtime without shutdown), then recovers a fresh
runtime: restore newest snapshot -> replay the WAL suffix past the
watermark -> the match table is byte-identical to an uninterrupted run.

(The app string deliberately keeps the analyzer's SA13 warning visible:
'fsync' behind an unbounded block-policy source means a disk stall
surfaces only as producer backpressure — the smoke corpus pins it.)

    python samples/durable_serving.py
"""
import os, sys, shutil, tempfile
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import FileSystemPersistenceStore

APP = """
@app:name('Durable')
@app:durability('fsync')
@source(type='tcp', port='0')
define stream Ticks (symbol string, price double);
define table Surges (symbol string, p1 double, p2 double);

@info(name='surge')
from every e1=Ticks[price > 100] -> e2=Ticks[price > e1.price] within 1 sec
select e1.symbol as symbol, e1.price as p1, e2.price as p2
insert into Surges;
"""

work = tempfile.mkdtemp(prefix="siddhi_durable_")
rng = np.random.default_rng(7)
ts0 = 1_700_000_000_000
frames = [({"symbol": np.array([f"K{i}" for i in
                                rng.integers(0, 4, 256)]),
            "price": np.round(rng.uniform(90, 130, 256), 2)},
           ts0 + np.arange(k * 256, (k + 1) * 256, dtype=np.int64))
          for k in range(8)]


def feed(rt, fr):
    h = rt.input_handler("Ticks")
    for cols, ts in fr:
        h.send_batch(cols, ts)
    rt.flush()


mgr = SiddhiManager()
mgr.set_persistence_store(FileSystemPersistenceStore(work))
rt = mgr.create_app_runtime(APP)
rt.start()
feed(rt, frames[:4])
rev = rt.persist()                       # snapshot barrier: watermark + truncation
print(f"snapshot {rev!r} watermark={rev.watermark}")
feed(rt, frames[4:])
print("wal:", {k: rt.wal.metrics()[k] for k in
               ("appended_frames", "fsyncs", "segments")})
n_live = len(rt.tables["Surges"].all_rows())
rt.wal.close()                           # simulate SIGKILL: no shutdown,
del rt, mgr                              # just the process vanishing

m2 = SiddhiManager()
m2.set_persistence_store(FileSystemPersistenceStore(work))
rt2 = m2.create_app_runtime(APP)
report = rt2.recover()                   # restore + replay the WAL suffix
print("recovery:", report)
n_rec = len(rt2.tables["Surges"].all_rows())
print(f"matches: live={n_live} recovered={n_rec} "
      f"({'EXACTLY-ONCE OK' if n_live == n_rec else 'MISMATCH'})")

m2.shutdown()
shutil.rmtree(work, ignore_errors=True)
