"""Quickstart: filter + projection (reference:
siddhi-samples/quick-start-samples/.../SimpleFilterSample.java).

    python samples/simple_filter.py
"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from siddhi_tpu import SiddhiManager

APP = """
define stream StockStream (symbol string, price double, volume int);
@info(name='filterQuery')
from StockStream[price > 100] select symbol, price insert into OutStream;
"""

mgr = SiddhiManager()
rt = mgr.create_app_runtime(APP)
rt.add_callback("OutStream",
                lambda evs: [print("match:", e.data) for e in evs])
rt.start()
h = rt.input_handler("StockStream")
h.send(("WSO2", 151.25, 100))
h.send(("ACME", 32.5, 20))
h.send(("IBM", 120.0, 5))
rt.flush()
mgr.shutdown()
