"""Machine-loss-tolerant HA quickstart: hot-standby WAL replication +
failover (docs/RELIABILITY.md "High availability & failover").

A durable pattern app runs as the PRIMARY behind a frame server; a
second runtime deploys the same app as a STANDBY replica that dials the
primary's frame port and tails its write-ahead log (REPL frames,
docs/SERVING.md).  The demo feeds frames, waits for the standby's
applied watermark to converge, "loses the machine" (abandons the
primary without shutdown), promotes the standby — fence, heal, replay
to head — and shows the promoted node serving the identical match
table.

(The app string deliberately keeps the analyzer's SA14 warning visible:
'semi-sync' behind an unbounded block-policy source means a standby
stall surfaces only as producer backpressure — the smoke corpus pins
it.)

    python samples/replicated_failover.py
"""
import os, sys, shutil, tempfile, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import IncrementalFileSystemPersistenceStore
from siddhi_tpu.net.server import NetServer

APP = """
@app:name('HADemo')
@app:durability('batch')
@app:replication('semi-sync', degrade='async')
@source(type='tcp', port='0')
define stream Ticks (symbol string, price double);
define table Surges (symbol string, p1 double, p2 double);

@info(name='surge')
from every e1=Ticks[price > 100] -> e2=Ticks[price > e1.price] within 1 sec
select e1.symbol as symbol, e1.price as p1, e2.price as p2
insert into Surges;
"""

work = tempfile.mkdtemp(prefix="siddhi_ha_")
rng = np.random.default_rng(7)
ts0 = 1_700_000_000_000
frames = [({"symbol": np.array([f"K{i}" for i in
                                rng.integers(0, 4, 256)]),
            "price": np.round(rng.uniform(90, 130, 256), 2)},
           ts0 + np.arange(k * 256, (k + 1) * 256, dtype=np.int64))
          for k in range(8)]

# primary: durable + replicable, fronted by a frame server
mgr_p = SiddhiManager()
mgr_p.set_persistence_store(
    IncrementalFileSystemPersistenceStore(work + "/pstore"))
rt_p = mgr_p.create_app_runtime(APP)
rt_p.start()
srv = NetServer(lambda a, s: (_ for _ in ()).throw(KeyError(s)),
                port=0, repl_resolve=lambda app: rt_p).start()

# standby: same app text + the standby role, tailing the primary
mgr_s = SiddhiManager()
mgr_s.set_persistence_store(
    IncrementalFileSystemPersistenceStore(work + "/sstore"))
rt_s = mgr_s.create_app_runtime(APP.replace(
    "@app:replication('semi-sync', degrade='async')",
    "@app:replication('async', role='standby', "
    f"peer='127.0.0.1:{srv.port}')"))
rt_s.start()                             # passive: tails, serves nothing

h = rt_p.input_handler("Ticks")
for cols, ts in frames:
    h.send_batch(cols, ts)
rt_p.flush()
n_live = len(rt_p.tables["Surges"].all_rows())

deadline = time.time() + 20              # async tail: wait for convergence
while time.time() < deadline:
    if rt_s.replication.applied_watermark().get("Ticks", 0) >= len(frames):
        break
    time.sleep(0.05)
print("standby:", {k: rt_s.replication.metrics()[k] for k in
                   ("role", "applied_records", "applied_watermark")})

rt_p.wal.close()                         # machine loss: no shutdown, the
srv.stop()                               # process (and its box) vanish
del rt_p, mgr_p

report = rt_s.promote()                  # fence -> heal -> replay -> serve
print("promotion:", {k: report[k] for k in
                     ("promoted", "generation", "promote_s")})
n_rec = len(rt_s.tables["Surges"].all_rows())
print(f"matches: primary={n_live} promoted={n_rec} "
      f"({'FAILOVER EXACT' if n_live == n_rec else 'MISMATCH'})")

mgr_s.shutdown()
shutil.rmtree(work, ignore_errors=True)
