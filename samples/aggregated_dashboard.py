"""Queryable state plane quickstart: device-resident incremental
aggregation served by SiddhiQL store queries (docs/AGGREGATION.md).

`define aggregation` rolls every trade into per-duration buckets —
seconds through hours here — and the runtime keeps the bucket state
ITSELF on device (one float64 base matrix per duration, merged in
place by a jitted segment-reduce; `rt.explain()` shows the plan as
`device-resident`).  Dashboards never see any of that machinery: they
ask with a store query (`from TradeAgg within ... per 'min' select
...`), in process via `rt.query()` or over the wire via
`FrameClient.query()` / `POST /siddhi/artifact/query`.

(The app string deliberately keeps the analyzer's SA15 warning
visible: `group by sym` with no `@purge` retention means one rolling
bucket row per (bucket, symbol) pair per duration, forever — the
smoke corpus pins the finding.  Production apps declare
`@purge(retention='1 hour')` or similar.)

    python samples/aggregated_dashboard.py
"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
from siddhi_tpu import SiddhiManager

APP = """
@app:name('Dashboard')
define stream Trades (sym string, price double, vol long, ts long);

define aggregation TradeAgg
from Trades
select sym, sum(price * vol) as turnover, avg(price) as avgPrice,
       min(price) as lo, max(price) as hi, count() as trades
group by sym
aggregate by ts every sec, min, hour;
"""

rng = np.random.default_rng(21)
ts0 = 1_700_000_000_000
syms = np.array(["AAPL", "NVDA", "TSLA", "AMZN"])

mgr = SiddhiManager()
rt = mgr.create_app_runtime(APP)
rt.start()
h = rt.input_handler("Trades")
for k in range(16):
    n = 512
    ts = ts0 + k * 15_000 + rng.integers(0, 15_000, n)
    ts.sort()
    h.send_batch({"sym": syms[rng.integers(0, 4, n)],
                  "price": np.round(rng.uniform(90, 410, n), 2),
                  "vol": rng.integers(1, 50, n).astype(np.int64),
                  "ts": ts.astype(np.int64)}, ts.astype(np.int64))
rt.flush()

agg = rt.aggregations["TradeAgg"]
print("placement:", rt.explain()["aggregations"]["TradeAgg"]["path"])
print("state:", agg.metrics())

rows = rt.query(
    f"from TradeAgg within {ts0}L, {ts0 + 300_000}L per 'min' "
    f"select sym, turnover, avgPrice, trades")
print(f"\nper-minute rollup ({len(rows)} rows):")
for bucket, row in sorted(rows)[:8]:
    sym, turnover, avg_price, trades = row
    print(f"  {bucket}  {sym:<5} turnover={turnover:>12.2f} "
          f"avg={avg_price:7.2f} trades={trades}")

rows = rt.query(
    f"from TradeAgg within {ts0 - 3_600_000}L, {ts0 + 3_600_000}L "
    f"per 'hour' select sym, lo, hi, trades")
print(f"\nhourly extremes ({len(rows)} rows):")
for bucket, (sym, lo, hi, trades) in sorted(rows):
    print(f"  {bucket}  {sym:<5} lo={lo:7.2f} hi={hi:7.2f} "
          f"trades={trades}")

sq = rt.statistics()["aggregation"]["store_query"]
print(f"\nstore queries: {sq['batches']} "
      f"(p99 {sq.get('p99_ms', 0.0)} ms)")
mgr.shutdown()
