"""Quickstart: sliding time window aggregation (reference:
quick-start-samples/.../TimeWindowSample.java) under the virtual clock.

    python samples/time_window.py
"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from siddhi_tpu import SiddhiManager

APP = """
@app:playback
define stream Temps (room string, temp double);
@info(name='avgQuery')
from Temps#window.time(10 sec) select room, avg(temp) as avgTemp
group by room insert into Out;
"""

mgr = SiddhiManager()
rt = mgr.create_app_runtime(APP)
rt.add_callback("Out", lambda evs: [print("avg:", e.data) for e in evs])
rt.start()
h = rt.input_handler("Temps")
h.send(("r1", 20.0), timestamp=1_000)
h.send(("r1", 24.0), timestamp=5_000)
h.send(("r1", 28.0), timestamp=12_000)   # the 20.0 reading has expired
rt.flush()
mgr.shutdown()
