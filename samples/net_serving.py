"""Serving-plane quickstart: columnar wire ingest with admission
control (docs/SERVING.md).

A pattern app exposes a TCP frame endpoint with a 50k eps rate limit
shedding into the replayable ErrorStore; a producer ships columnar
batches with `TcpFrameClient` (zero per-event Python on either side),
then the shed events are replayed once load clears.

    python samples/net_serving.py
"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
from siddhi_tpu import SiddhiManager
from siddhi_tpu.net import TcpFrameClient

APP = """
@app:name('Serving')
@source(type='tcp', port='0', rate.limit='50000', shed.policy='shed')
define stream Ticks (symbol string, price double, volume int);

@info(name='surge')
from every e1=Ticks[price > 100] -> e2=Ticks[price > e1.price] within 1 sec
select e1.symbol as symbol, e1.price as p1, e2.price as p2
insert into Surges;
"""

mgr = SiddhiManager()
rt = mgr.create_app_runtime(APP)
matches = []
rt.add_batch_callback("Surges", lambda b: matches.extend(b.rows(rt.strings)))
rt.start()

port = rt.sources[0].port
print(f"frame server on 127.0.0.1:{port} (ws-capable, same port)")

cli = TcpFrameClient("127.0.0.1", port, "Ticks",
                     TcpFrameClient.cols_of_schema(rt.schemas["Ticks"]))
rng = np.random.default_rng(7)
ts0 = 1_700_000_000_000
for k in range(8):
    n = 2048
    cli.send_batch(
        {"symbol": np.array([f"K{i}" for i in rng.integers(0, 8, n)]),
         "price": np.round(rng.uniform(90, 130, n), 2),
         "volume": rng.integers(1, 1000, n).astype(np.int32)},
        ts0 + np.arange(k * n, (k + 1) * n, dtype=np.int64))
cli.barrier()          # PING/ACK: everything admitted, fed, flushed

net = rt.statistics()["net"]["Ticks"]
print(f"frames={net['frames_in']} events={net['events_in']} "
      f"admitted={net['admitted_events']} shed={net['shed_events']} "
      f"matches={len(matches)}")

if net["shed_events"]:
    rt.admission["Ticks"].bucket.rate = None      # load cleared
    print("replaying shed events:", rt.error_store.replay(rt))

cli.close()
mgr.shutdown()
