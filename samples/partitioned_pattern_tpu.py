"""The flagship TPU workload: a partitioned CEP pattern where every
partition key is one lane of ONE batched device NFA kernel (the
reference clones the whole query graph per key instead —
core:partition/PartitionRuntime.java:257-306).

    python samples/partitioned_pattern_tpu.py
"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
from siddhi_tpu import SiddhiManager

APP = """
@app:partitionCapacity(128)
define stream Txn (card string, amt double);
partition with (card of Txn)
begin
  @info(name='fraud')
  from every e1=Txn[amt > 100] -> e2=Txn[amt > e1.amt * 2] within 1 min
  select e1.amt as first, e2.amt as spike insert into Alerts;
end;
"""

mgr = SiddhiManager()
rt = mgr.create_app_runtime(APP)
n = [0]
rt.add_batch_callback("Alerts", lambda b: n.__setitem__(0, n[0] + b.n))
rt.start()
h = rt.input_handler("Txn")
rng = np.random.default_rng(0)
for i in range(5000):
    h.send((f"card{int(rng.integers(128))}",
            float(np.round(rng.uniform(50, 400) * 4) / 4)),
           timestamp=1_000 + i * 10)
rt.flush()
print(f"alerts: {n[0]} (all 128 card partitions matched on one device kernel)")
mgr.shutdown()
