#!/usr/bin/env python
"""Perf-regression sentinel (ISSUE 17): compare a fresh
`bench.py --trace --smoke` report against the checked-in
scripts/perf_baseline.json and exit 1 when the attribution moved
outside the tolerance bands.

What it guards is the *shape* of device-time attribution, not raw eps:
absolute throughput varies machine to machine, but the phase shares —
where a processed second goes — are a property of the code.  A change
that doubles host-dispatch seconds doubles the host-share *odds*
(odds = s / (1 - s)); comparing in odds space makes the band
symmetric across the share range (0.3 -> 0.46 and 0.7 -> 0.82 are the
same 2x regression), so the band is a max odds ratio, default 1.6 —
tight enough that a 2x host-seconds regression (odds ratio 2.0) always
trips it, loose enough for run-to-run jitter.

Checks (fail -> exit 1):
  * host_dispatch_share odds ratio vs baseline, config 3 and config 4
  * per-phase aggregate shares (config 3) within +-`share_abs`
  * phase-attribution coverage >= `coverage_min` of the dispatch wall

Warn-only (never fail CI on wall-clock luck):
  * end-to-end / kernel eps ratio bands
  * the profiler/tracing overhead contract flags

A harness config-hash mismatch means the workload itself changed —
every band would be comparing different programs, so the sentinel
reports "stale baseline" and passes; refresh with `--write-baseline`.

Usage:
    python scripts/perfcheck.py                  # run bench, compare
    python scripts/perfcheck.py --input FILE     # compare a saved report
    python scripts/perfcheck.py --write-baseline # run bench, refresh
    python scripts/perfcheck.py --input FILE --inject-host-share-x2
                                # seeded 2x host-seconds regression
                                # (self-test: MUST exit 1)
"""
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "scripts", "perf_baseline.json")

TOLERANCES = {
    "host_share_odds_x": 1.6,   # max odds ratio fresh/baseline (2x trips)
    "share_abs": 0.2,           # per-phase share drift band
    "coverage_min": 0.9,        # attribution floor (ISSUE 17 acceptance)
    "eps_ratio": [0.4, 2.5],    # warn-only wall-clock band
}


def _last_json_line(text: str) -> dict:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise ValueError("no JSON object line in input")


def load_report(path=None) -> dict:
    if path:
        with open(path) as f:
            return _last_json_line(f.read())
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"),
         "--trace", "--smoke"],
        capture_output=True, text=True, timeout=1800, cwd=ROOT)
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-4000:])
        raise RuntimeError(f"bench.py --trace --smoke exited {r.returncode}")
    return _last_json_line(r.stdout)


def _metrics_of(rep: dict) -> dict:
    """The comparable slice of a --trace report: config3 top-level +
    profile aggregate, config4 sub-block."""
    prof = rep.get("profile") or {}
    plans = prof.get("plans") or {}
    kernel_eps = max((p.get("kernel_eps") or 0.0 for p in plans.values()),
                     default=0.0) or None
    c4 = rep.get("config4") or {}
    return {
        "config3": {
            "eps": rep.get("eps"),
            "coverage": prof.get("coverage"),
            "kernel_share": rep.get("kernel_share"),
            "host_dispatch_share": rep.get("host_dispatch_share"),
            "shares": prof.get("shares"),
            "kernel_eps": kernel_eps,
        },
        "config4": {
            "eps": c4.get("eps"),
            "coverage": ((c4.get("profile") or {}).get("coverage")),
            "host_dispatch_share": c4.get("host_dispatch_share"),
        },
    }


def write_baseline(rep: dict, path: str) -> dict:
    base = {
        "schema": 1,
        "written_unix": round(time.time(), 1),
        "harness": rep.get("harness") or {},
        "metrics": _metrics_of(rep),
        "overhead": {
            "profile_sampled_32_pct": (rep.get("profile_overhead") or {})
            .get("sampled_32_overhead_pct"),
            "tracing_unsampled_pct": (rep.get("tracing_overhead") or {})
            .get("unsampled_overhead_pct"),
        },
        "tolerances": TOLERANCES,
    }
    # the native single-thread roofline column the live profiler's
    # fold_roofline() reads back (keys match _native_roofline's parse)
    try:
        sys.path.insert(0, ROOT)
        import bench
        nat = bench.native_baseline()
        base["native_cpp_eps"] = {
            "3_sequence": (nat.get("sequence") or {}).get("eps"),
            "4_partitioned": (nat.get("partitioned") or {}).get("eps"),
        }
    except Exception as e:      # no g++ in a stripped image: no column
        sys.stderr.write(f"[perfcheck] native roofline skipped: {e}\n")
        base["native_cpp_eps"] = {}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(base, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return base


def _odds(s):
    s = min(max(float(s), 1e-6), 1.0 - 1e-6)
    return s / (1.0 - s)


def inject_host_share_x2(rep: dict) -> dict:
    """Seeded regression for the self-test: double the host-dispatch
    *seconds* of both configs — in share terms, double the odds."""
    def bump(s):
        o = 2.0 * _odds(s)
        return round(o / (1.0 + o), 4)
    if rep.get("host_dispatch_share") is not None:
        rep["host_dispatch_share"] = bump(rep["host_dispatch_share"])
    prof = rep.get("profile") or {}
    if prof.get("host_dispatch_share") is not None:
        prof["host_dispatch_share"] = bump(prof["host_dispatch_share"])
    c4 = rep.get("config4") or {}
    if c4.get("host_dispatch_share") is not None:
        c4["host_dispatch_share"] = bump(c4["host_dispatch_share"])
    return rep


def compare(rep: dict, base: dict) -> dict:
    tol = {**TOLERANCES, **(base.get("tolerances") or {})}
    fresh = _metrics_of(rep)
    bm = base.get("metrics") or {}
    failures, warnings = [], []

    bh = (base.get("harness") or {}).get("config_hash")
    fh = (rep.get("harness") or {}).get("config_hash")
    if bh and fh and bh != fh:
        return {"metric": "perfcheck", "pass": True, "stale_baseline": True,
                "note": f"config hash {fh} != baseline {bh}: workload "
                        "changed, bands not comparable — refresh with "
                        "--write-baseline", "failures": [], "warnings": []}

    for cfg in ("config3", "config4"):
        fs = (fresh.get(cfg) or {}).get("host_dispatch_share")
        bs = (bm.get(cfg) or {}).get("host_dispatch_share")
        if fs is None or bs is None:
            warnings.append(f"{cfg}: host_dispatch_share missing "
                            f"(fresh={fs}, baseline={bs})")
            continue
        ratio = _odds(fs) / _odds(bs)
        if ratio > tol["host_share_odds_x"]:
            failures.append(
                f"{cfg}: host_dispatch_share {fs:.3f} vs baseline "
                f"{bs:.3f} — odds ratio {ratio:.2f} > "
                f"{tol['host_share_odds_x']} (host dispatch regressed)")

    f_sh = (fresh["config3"].get("shares") or {})
    b_sh = ((bm.get("config3") or {}).get("shares") or {})
    for ph in sorted(set(f_sh) | set(b_sh)):
        d = abs((f_sh.get(ph) or 0.0) - (b_sh.get(ph) or 0.0))
        if d > tol["share_abs"]:
            failures.append(
                f"config3 phase {ph}: share moved {d:.3f} > "
                f"{tol['share_abs']} ({b_sh.get(ph)} -> {f_sh.get(ph)})")

    for cfg in ("config3", "config4"):
        cov = (fresh.get(cfg) or {}).get("coverage")
        if cov is not None and cov < tol["coverage_min"]:
            failures.append(f"{cfg}: phase coverage {cov:.3f} < "
                            f"{tol['coverage_min']}")

    lo, hi = tol["eps_ratio"]
    for cfg in ("config3", "config4"):
        fe = (fresh.get(cfg) or {}).get("eps")
        be = (bm.get(cfg) or {}).get("eps")
        if fe and be and not (lo <= fe / be <= hi):
            warnings.append(f"{cfg}: eps ratio {fe / be:.2f} outside "
                            f"[{lo}, {hi}] (fresh {fe}, baseline {be})")
    pov = rep.get("profile_overhead") or {}
    if pov and pov.get("pass") is False:
        warnings.append("profiler overhead contract failed: "
                        f"{pov.get('sampled_32_overhead_pct')}% > 3%")

    return {"metric": "perfcheck", "pass": not failures,
            "failures": failures, "warnings": warnings,
            "host_dispatch_share": {
                cfg: {"fresh": (fresh.get(cfg) or {})
                      .get("host_dispatch_share"),
                      "baseline": (bm.get(cfg) or {})
                      .get("host_dispatch_share")}
                for cfg in ("config3", "config4")}}


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    path = None
    if "--input" in argv:
        path = argv[argv.index("--input") + 1]
    base_path = BASELINE
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]

    rep = load_report(path)

    if "--write-baseline" in argv:
        i = argv.index("--write-baseline")
        out = (argv[i + 1] if i + 1 < len(argv)
               and not argv[i + 1].startswith("--") else base_path)
        base = write_baseline(rep, out)
        print(json.dumps({"metric": "perfcheck", "pass": True,
                          "wrote_baseline": out,
                          "metrics": base["metrics"]}))
        return 0

    if "--inject-host-share-x2" in argv:
        rep = inject_host_share_x2(rep)

    if not os.path.exists(base_path):
        print(json.dumps({"metric": "perfcheck", "pass": True,
                          "note": f"no baseline at {base_path} — run "
                                  "--write-baseline first"}))
        return 0
    with open(base_path) as f:
        base = json.load(f)
    res = compare(rep, base)
    print(json.dumps(res))
    return 0 if res["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
