#!/usr/bin/env bash
# CI smoke: the tier-1 suite plus a ~5-second end-to-end service check
# (deploy an app over REST, push events, assert /metrics exposes
# nonzero counters).  Exits nonzero on any failure.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== compileall =="
# every module must at least parse/compile — a syntax error in a rarely
# imported module must not wait for a request to surface
python -m compileall -q siddhi_tpu

echo "== tuning-cache schema lint =="
# a malformed persisted tuning cache must never brick a deploy: the
# loader quarantines corrupt files (core/autotune.py TuningCache), and
# this lint step catches schema drift before it ships
python -m siddhi_tpu.core.autotune --lint

echo "== static analysis: self-lint =="
# the no-silent-demotion CI gate (docs/ANALYSIS.md): an except handler
# on a plan-lowering path that swallows without recording a Demotion
# (SL01), or an unguarded shared-counter mutation in a lock-owning
# class (SL02), fails the build here — exactly the two bug classes
# review rounds keep finding
python -m siddhi_tpu.analysis --self

echo "== static analysis: concurrency (--threads) =="
# the concurrency self-analysis gate (docs/ANALYSIS.md "Concurrency
# self-analysis"): SL03 lockset / inconsistent guard, SL04 lock-order
# inversion, SL05 blocking-call-under-lock, SL06 thread lifecycle over
# the engine's own source.  The baseline pins the justified-suppression
# inventory — a new `# lint: allow (...)` anywhere fails CI until the
# baseline is regenerated in the same commit (--write-baseline)
python -m siddhi_tpu.analysis --threads \
    --baseline scripts/threads_baseline.json

echo "== static analysis: samples corpus =="
# the analyzer over every samples/*.py app string: expected findings are
# PINNED (all info-severity conveniences in the samples); any new rule
# firing — or an expected one disappearing — fails CI
python -m siddhi_tpu.analysis \
    --expect SA07,SA07,SA07,SA07,SA12,SA13,SA13,SA13,SA14,SA15 \
    samples/simple_filter.py samples/time_window.py \
    samples/partitioned_pattern_tpu.py samples/net_serving.py \
    samples/durable_serving.py samples/replicated_failover.py \
    samples/aggregated_dashboard.py

echo "== tier-1 tests =="
python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider

echo "== lock-witness vs static graph =="
# run a fast serving-plane tier-1 subset with every engine lock
# witness-instrumented (utils/locks.py, SIDDHI_LOCK_CHECK=1): the
# ACTUAL acquisition orders the tests exhibit are recorded, then
# cross-checked against the static lock graph.  Any witnessed order
# the model contradicts or does not know fails CI — the SL04 deadlock
# verdicts are only as good as this agreement.  (A dynamic inversion
# additionally raises LockOrderError inside the test run itself.)
WITNESS_OUT="$(mktemp -u /tmp/siddhi_lock_witness.XXXXXX.json)"
SIDDHI_LOCK_CHECK=1 SIDDHI_LOCK_WITNESS_OUT="$WITNESS_OUT" \
    python -m pytest tests/test_net_admission.py tests/test_net_server.py \
    tests/test_wal.py tests/test_service.py tests/test_tracing.py \
    tests/test_replication.py \
    -q -m 'not slow' -p no:cacheprovider
python -m siddhi_tpu.analysis --threads --witness "$WITNESS_OUT"
rm -f "$WITNESS_OUT"

echo "== service /metrics smoke =="
python - <<'EOF'
import json
import sys
import time
import urllib.request

from siddhi_tpu.service import SiddhiService

svc = SiddhiService(port=0).start()
base = f"http://127.0.0.1:{svc.port}"
deadline = time.time() + 5.0
try:
    app = ("@app:name('Smoke')\n"
           "@app:trace('all')\n"       # every frame traced -> exemplars
           "define stream S (sym string, p double);\n"
           "@info(name='q') from S[p > 10] select sym, p insert into Out;\n")
    req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                 data=app.encode(), method="POST")
    assert json.loads(urllib.request.urlopen(req).read())["app"] == "Smoke"
    for i in range(20):
        req = urllib.request.Request(
            f"{base}/siddhi/artifact/event",
            data=json.dumps({"app": "Smoke", "stream": "S",
                             "data": [f"K{i % 4}", 9.0 + i]}).encode(),
            method="POST")
        urllib.request.urlopen(req).read()
    text = ""
    while time.time() < deadline:
        with urllib.request.urlopen(f"{base}/metrics") as r:
            ctype = r.headers["Content-Type"]
            text = r.read().decode()
        if 'siddhi_tpu_events_total{app="Smoke",stream="S"} 20' in text:
            break
        time.sleep(0.2)
    assert "version=0.0.4" in ctype, f"bad content type {ctype!r}"
    assert 'siddhi_tpu_events_total{app="Smoke",stream="S"} 20' in text, \
        "events_total never reached 20:\n" + text[:1500]
    assert "siddhi_tpu_query_latency_seconds" in text
    # classic 0.0.4 response: exemplar syntax is ILLEGAL here — a real
    # Prometheus text parser would reject the whole exposition
    assert " # {trace_id=" not in text
    for ln in text.splitlines():             # exposition parses
        if ln and not ln.startswith("#"):
            float("nan") if ln.rsplit(" ", 1)[1] == "NaN" \
                else float(ln.rsplit(" ", 1)[1])
    # the tracing plane's exemplars ride the Accept-negotiated
    # OpenMetrics form (docs/OBSERVABILITY.md): the dispatch-latency
    # histogram buckets must carry a trace id there
    req = urllib.request.Request(
        f"{base}/metrics",
        headers={"Accept": "application/openmetrics-text; version=1.0.0"})
    with urllib.request.urlopen(req) as r:
        assert "openmetrics-text" in r.headers["Content-Type"]
        om = r.read().decode()
    assert "siddhi_tpu_stream_dispatch_latency_seconds_bucket" in om
    assert any(" # {trace_id=" in ln for ln in om.splitlines()), \
        "no exemplar on the dispatch-latency histogram"
    assert om.rstrip().endswith("# EOF")
    print(f"OK: /metrics valid, nonzero counters; exemplars on the "
          f"OpenMetrics form ({len(text.splitlines())} lines)")
finally:
    svc.stop()
EOF

echo "== service frame-ingest smoke =="
# the front door end-to-end: start the service, deploy a pattern app,
# push ONE columnar frame over localhost TCP to the shared frame port,
# and assert the match arrived and /metrics shows the ingest gauges
python - <<'EOF'
import urllib.request

import numpy as np

from siddhi_tpu.net import TcpFrameClient
from siddhi_tpu.service import SiddhiService

svc = SiddhiService(port=0).start()
base = f"http://127.0.0.1:{svc.port}"
try:
    app = ("@app:name('NetSmoke')\n"
           "define stream S (sym string, p double);\n"
           "@info(name='q') from every e1=S -> e2=S[p > e1.p] "
           "select e1.sym as s1, e2.p as p2 insert into Out;\n")
    req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                 data=app.encode(), method="POST")
    urllib.request.urlopen(req).read()
    rt = svc.runtimes["NetSmoke"]
    matches = []
    rt.add_batch_callback("Out", lambda b: matches.extend(
        map(tuple, b.rows(rt.strings))))
    cli = TcpFrameClient("127.0.0.1", svc.net_port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]),
                         app="NetSmoke")
    cli.send_batch({"sym": np.array(["A", "B", "C", "D"]),
                    "p": np.array([10.0, 12.0, 9.0, 11.0])},
                   np.arange(4, dtype=np.int64))
    cli.barrier(timeout=30)
    cli.close()
    assert matches, "no pattern match arrived over the frame plane"
    with urllib.request.urlopen(f"{base}/metrics") as r:
        text = r.read().decode()
    for series in ("siddhi_tpu_net_events_total",
                   "siddhi_tpu_net_admitted_events_total"):
        line = next((ln for ln in text.splitlines()
                     if ln.startswith(series + "{")), None)
        assert line is not None and line.rstrip().endswith(" 4"), \
            f"{series} missing or != 4: {line!r}"
    print(f"OK: {len(matches)} matches via frame plane, ingest gauges live")
finally:
    svc.stop()
EOF

echo "== frame tracing smoke =="
# the causal tracing plane end-to-end (docs/OBSERVABILITY.md "Frame
# tracing"): deploy over REST, send one TCP columnar frame with a
# PRODUCER-stamped trace id, then assert GET /siddhi/artifact/trace
# serves a Chrome trace_event object containing that trace.  The JSON
# is linted on disk with `python -m json.tool` + a required-key check.
TRACE_JSON="$(mktemp -u /tmp/siddhi_trace_smoke.XXXXXX.json)"
python - "$TRACE_JSON" <<'EOF'
import json
import sys
import urllib.request

import numpy as np

from siddhi_tpu.net import TcpFrameClient
from siddhi_tpu.service import SiddhiService

out_path = sys.argv[1]
svc = SiddhiService(port=0).start()
base = f"http://127.0.0.1:{svc.port}"
try:
    app = ("@app:name('TraceSmoke')\n"
           "@app:trace('all')\n"
           "define stream S (sym string, p double);\n"
           "@info(name='q') from S[p > 10] select sym, p insert into Out;\n")
    req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                 data=app.encode(), method="POST")
    urllib.request.urlopen(req).read()
    rt = svc.runtimes["TraceSmoke"]
    cli = TcpFrameClient("127.0.0.1", svc.net_port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]),
                         app="TraceSmoke")
    cli.send_batch({"sym": np.array(["A", "B", "C", "D"]),
                    "p": np.array([11.0, 12.0, 13.0, 14.0])},
                   np.arange(4, dtype=np.int64),
                   trace_id="smoke-trace-1")
    cli.barrier(timeout=30)
    cli.close()
    with urllib.request.urlopen(
            f"{base}/siddhi/artifact/trace?siddhiApp=TraceSmoke") as r:
        blob = r.read()
    with open(out_path, "wb") as f:
        f.write(blob)
    obj = json.loads(blob)
    spans = [ev for ev in obj["traceEvents"] if ev.get("ph") == "X"
             and ev.get("args", {}).get("trace") == "smoke-trace-1"]
    names = {ev["name"] for ev in spans}
    for want in ("frame", "admit", "freeze", "dispatch"):
        assert want in names, (want, sorted(names))
    print(f"OK: producer trace id served with {len(spans)} spans "
          f"({sorted(names)})")
finally:
    svc.stop()
EOF
# Chrome trace_event schema lint: valid JSON + the required keys
python -m json.tool "$TRACE_JSON" > /dev/null
python - "$TRACE_JSON" <<'EOF'
import json
import sys
obj = json.load(open(sys.argv[1]))
assert isinstance(obj.get("traceEvents"), list) and obj["traceEvents"]
md = obj.get("metadata")
assert isinstance(md, dict) and md.get("hostname"), md
for ev in obj["traceEvents"]:
    assert ev.get("ph") in ("X", "M") and "name" in ev and "pid" in ev, ev
print("OK: Chrome trace JSON schema valid "
      f"({len(obj['traceEvents'])} events, host {md['hostname']})")
EOF
rm -f "$TRACE_JSON"

echo "== phase-profiler smoke =="
# the device-time attribution plane end-to-end (docs/OBSERVABILITY.md
# "Device-time profiling"): deploy over REST with the profiler in
# 'all' mode, push TCP frames, then assert GET /siddhi/artifact/profile
# serves per-plan phase shares that sum to 1.0 and that /metrics
# exposes the siddhi_tpu_phase_seconds_total series.
python - <<'EOF'
import json
import urllib.request

import numpy as np

from siddhi_tpu.net import TcpFrameClient
from siddhi_tpu.service import SiddhiService

svc = SiddhiService(port=0).start()
base = f"http://127.0.0.1:{svc.port}"
try:
    app = ("@app:name('ProfSmoke')\n"
           "@app:profile('all')\n"
           "define stream S (sym string, p double);\n"
           "@info(name='q') from every e1=S[p > 10] -> e2=S[p > e1.p] "
           "select e1.sym as s1, e2.p as p2 insert into Out;\n")
    req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                 data=app.encode(), method="POST")
    urllib.request.urlopen(req).read()
    rt = svc.runtimes["ProfSmoke"]
    cli = TcpFrameClient("127.0.0.1", svc.net_port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]),
                         app="ProfSmoke")
    for k in range(4):
        cli.send_batch({"sym": np.array(["A", "B", "C", "D"]),
                        "p": np.array([11.0, 12.0, 13.0, 14.0])},
                       np.arange(4 * k, 4 * k + 4, dtype=np.int64))
    cli.barrier(timeout=30)
    cli.close()
    with urllib.request.urlopen(
            f"{base}/siddhi/artifact/profile?siddhiApp=ProfSmoke") as r:
        prof = json.loads(r.read())["apps"]["ProfSmoke"]
    assert prof["mode"] == "all", prof.get("mode")
    assert prof["plans"], "no plan accumulated any attribution"
    for name, pv in prof["plans"].items():
        s = sum(pv["shares"].values())
        assert abs(s - 1.0) < 5e-4, (name, pv["shares"])
    agg = prof["aggregate"]
    assert agg["rounds"] > 0 and agg["coverage"] >= 0.9, agg
    with urllib.request.urlopen(f"{base}/metrics") as r:
        text = r.read().decode()
    assert "siddhi_tpu_phase_seconds_total{" in text
    assert "siddhi_tpu_host_dispatch_share{" in text
    print(f"OK: profile plane live ({len(prof['plans'])} plans, "
          f"coverage {agg['coverage']}, "
          f"host share {agg['host_dispatch_share']})")
finally:
    svc.stop()
EOF

echo "== kill -9 recovery smoke =="
# exactly-once durable serving end-to-end (docs/RELIABILITY.md): start a
# service subprocess with @app:durability('batch'), feed N TCP frames
# (ACK'd = durable), SIGKILL the whole service, restart it, redeploy —
# recover-on-redeploy must yield match counts identical to an
# uninterrupted in-process run.  Exits nonzero on any drift.
python - <<'EOF'
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import FileSystemPersistenceStore
from siddhi_tpu.net import TcpFrameClient

APP = """@app:name('KillSmoke')
@app:durability('batch')
define stream S (sym string, p double);
define table M (s1 string, p2 double);
@info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec
select e1.sym as s1, e2.p as p2 insert into M;
"""

CHILD = """
import sys, threading
from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import FileSystemPersistenceStore
from siddhi_tpu.service import SiddhiService
mgr = SiddhiManager()
mgr.set_persistence_store(FileSystemPersistenceStore(sys.argv[1]))
svc = SiddhiService(port=0, manager=mgr).start()
print(f"READY {svc.port} {svc.net_port}", flush=True)
threading.Event().wait()
"""

rng = np.random.default_rng(11)
ts0 = 1_700_000_000_000
frames = [({"sym": np.array([f"K{i}" for i in rng.integers(0, 4, 256)]),
            "p": np.round(rng.uniform(90, 130, 256), 2)},
           ts0 + np.arange(k * 256, (k + 1) * 256, dtype=np.int64))
          for k in range(6)]

# uninterrupted reference
work = tempfile.mkdtemp(prefix="siddhi_kill9_smoke_")
mgr = SiddhiManager()
mgr.set_persistence_store(FileSystemPersistenceStore(work + "/ref"))
rt = mgr.create_app_runtime(APP)
rt.start()
h = rt.input_handler("S")
for cols, ts in frames:
    h.send_batch(cols, ts)
rt.flush()
want = len(rt.tables["M"].all_rows())
mgr.shutdown()
assert want > 0


def start_service():
    p = subprocess.Popen([sys.executable, "-c", CHILD, work + "/svc"],
                         stdout=subprocess.PIPE, text=True)
    line = p.stdout.readline().split()
    assert line and line[0] == "READY", line
    return p, int(line[1]), int(line[2])


def deploy(port):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/siddhi/artifact/deploy",
        data=APP.encode(), method="POST")
    return json.loads(urllib.request.urlopen(req).read())


def matches(port):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/siddhi/artifact/query",
        data=json.dumps({"app": "KillSmoke",
                         "query": "from M select s1"}).encode(),
        method="POST")
    return len(json.loads(urllib.request.urlopen(req).read())["rows"])

try:
    child, port, net_port = start_service()
    deploy(port)
    cli = TcpFrameClient("127.0.0.1", net_port, "S",
                         [("sym", "string"), ("p", "double")],
                         app="KillSmoke")
    for cols, ts in frames:
        cli.send_batch(cols, ts)
    cli.barrier(timeout=60)        # durable ACK: frames are in the WAL
    os.kill(child.pid, signal.SIGKILL)
    child.wait(timeout=10)
    try:
        cli.close()
    except OSError:
        pass

    child2, port2, _ = start_service()
    deploy(port2)                  # recover-on-redeploy replays the WAL
    got = matches(port2)
    info = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port2}/siddhi/artifact/snapshot"
        f"?siddhiApp=KillSmoke").read())
    rec = info["recovery"]
    assert got == want, f"match drift after kill -9: {got} != {want}"
    assert rec["replayed_frames"] == len(frames), rec
    os.kill(child2.pid, signal.SIGKILL)
    print(f"OK: kill -9 recovery exact ({got} matches, "
          f"{rec['replayed_frames']} frames replayed in "
          f"{rec['recovery_s']}s)")
finally:
    shutil.rmtree(work, ignore_errors=True)
EOF

echo "== HA failover smoke =="
# machine-loss failover end-to-end (docs/RELIABILITY.md "High
# availability"): two service subprocesses — a durable primary and a
# hot standby tailing its WAL over the frame protocol — feed the
# primary N ACK'd frames, wait for the standby's applied watermark to
# converge, SIGKILL the primary, POST /siddhi/artifact/promote to the
# standby, and assert the promoted node serves match counts identical
# to an uninterrupted in-process run.  Exits nonzero on any drift.
python - <<'EOF'
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import IncrementalFileSystemPersistenceStore
from siddhi_tpu.net import TcpFrameClient

APP = """@app:name('HASmoke')
@app:durability('batch', dir='{wal}', segment.bytes='4096')
{extra}define stream S (sym string, p double);
define table M (s1 string, p2 double);
@info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec
select e1.sym as s1, e2.p as p2 insert into M;
"""

CHILD = """
import sys, threading
from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import IncrementalFileSystemPersistenceStore
from siddhi_tpu.service import SiddhiService
mgr = SiddhiManager()
mgr.set_persistence_store(IncrementalFileSystemPersistenceStore(sys.argv[1]))
svc = SiddhiService(port=0, manager=mgr).start()
print(f"READY {svc.port} {svc.net_port}", flush=True)
threading.Event().wait()
"""

rng = np.random.default_rng(13)
ts0 = 1_700_000_000_000
frames = [({"sym": np.array([f"K{i}" for i in rng.integers(0, 4, 256)]),
            "p": np.round(rng.uniform(90, 130, 256), 2)},
           ts0 + np.arange(k * 256, (k + 1) * 256, dtype=np.int64))
          for k in range(6)]

work = tempfile.mkdtemp(prefix="siddhi_ha_smoke_")

# uninterrupted in-process reference
mgr = SiddhiManager()
mgr.set_persistence_store(
    IncrementalFileSystemPersistenceStore(work + "/ref_store"))
rt = mgr.create_app_runtime(APP.format(wal=work + "/ref_wal", extra=""))
rt.start()
h = rt.input_handler("S")
for cols, ts in frames:
    h.send_batch(cols, ts)
rt.flush()
want = len(rt.tables["M"].all_rows())
mgr.shutdown()
assert want > 0


def start_service(store):
    p = subprocess.Popen([sys.executable, "-c", CHILD, store],
                         stdout=subprocess.PIPE, text=True)
    line = p.stdout.readline().split()
    assert line and line[0] == "READY", line
    return p, int(line[1]), int(line[2])


def post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body if isinstance(body, bytes) else json.dumps(body).encode(),
        method="POST")
    return json.loads(urllib.request.urlopen(req).read())


def repl_info(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/siddhi/artifact/snapshot"
            f"?siddhiApp=HASmoke") as r:
        return json.loads(r.read())


try:
    primary, p_port, p_net = start_service(work + "/p_store")
    post(p_port, "/siddhi/artifact/deploy",
         APP.format(wal=work + "/p_wal", extra="").encode())
    standby, s_port, s_net = start_service(work + "/s_store")
    post(s_port, "/siddhi/artifact/deploy",
         APP.format(wal=work + "/s_wal",
                    extra="@app:replication('async', role='standby', "
                          f"peer='127.0.0.1:{p_net}')\n").encode())

    cli = TcpFrameClient("127.0.0.1", p_net, "S",
                         [("sym", "string"), ("p", "double")],
                         app="HASmoke")
    for cols, ts in frames:
        cli.send_batch(cols, ts)
    cli.barrier(timeout=60)        # durable ACK: frames are in the WAL

    # hot standby converges (async: poll its applied watermark)
    deadline = time.time() + 20
    while time.time() < deadline:
        repl = repl_info(s_port).get("replication", {})
        if repl.get("applied_watermark", {}).get("S", 0) >= len(frames):
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"standby never converged: {repl}")

    os.kill(primary.pid, signal.SIGKILL)   # machine loss
    primary.wait(timeout=10)
    try:
        cli.close()
    except OSError:
        pass

    rep = post(s_port, "/siddhi/artifact/promote", {"app": "HASmoke"})
    assert rep["promoted"] and rep["generation"] >= 1, rep
    assert rep["recovery"]["replayed_frames"] == len(frames), rep
    got = len(post(s_port, "/siddhi/artifact/query",
                   {"app": "HASmoke",
                    "query": "from M select s1"})["rows"])
    assert got == want, f"match drift after failover: {got} != {want}"
    info = repl_info(s_port)
    assert info["replication"]["role"] == "primary", info["replication"]
    assert info["replication"]["promoted"] is True
    os.kill(standby.pid, signal.SIGKILL)
    print(f"OK: failover exact ({got} matches on the promoted standby, "
          f"{rep['recovery']['replayed_frames']} frames replayed, "
          f"promote {rep['promote_s']}s)")
finally:
    for p in ("primary", "standby"):
        proc = locals().get(p)
        if proc is not None and proc.poll() is None:
            proc.kill()
    shutil.rmtree(work, ignore_errors=True)
EOF

echo "== net serving-plane smoke =="
# bench.py --net --smoke: loopback columnar wire ingest (TCP + shm
# ring) on the config-3 pattern workload, asserted byte-identical to
# in-process send_batch; per-event REST measured as the baseline the
# frame protocol must beat >=5x; paced 2x-overload with
# shed.policy='shed' asserting p99 <= 2x unloaded, zero unaccounted
# loss (every shed event in the ErrorStore) and full replay
python bench.py --net --smoke

echo "== queryable-state smoke =="
# the state plane end-to-end: deploy a `define aggregation` app, ingest
# over the frame plane, then read the SAME rollup three ways — wire
# QUERY frame, REST store query, in-process runtime.query() — and
# assert all three agree byte-for-byte and /metrics carries the
# siddhi_tpu_agg_* series
python - <<'EOF'
import json
import urllib.request

import numpy as np

from siddhi_tpu.net import TcpFrameClient
from siddhi_tpu.service import SiddhiService

svc = SiddhiService(port=0).start()
base = f"http://127.0.0.1:{svc.port}"
try:
    app = ("@app:name('AggSmoke')\n"
           "define stream T (sym string, p double, ts long);\n"
           "define aggregation Roll\n"
           "from T select sym, sum(p) as total, count() as n\n"
           "group by sym aggregate by ts every sec, min;\n")
    req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                 data=app.encode(), method="POST")
    urllib.request.urlopen(req).read()
    rt = svc.runtimes["AggSmoke"]
    ts0 = 1_700_000_000_000
    cli = TcpFrameClient("127.0.0.1", svc.net_port, "T",
                         TcpFrameClient.cols_of_schema(rt.schemas["T"]),
                         app="AggSmoke")
    ts = ts0 + np.arange(256, dtype=np.int64) * 20
    cli.send_batch({"sym": np.array([f"S{i % 5}" for i in range(256)]),
                    "p": np.linspace(1.0, 64.0, 256),
                    "ts": ts}, ts)
    cli.barrier(timeout=30)
    q = (f"from Roll within {ts0}L, {ts0 + 60_000}L per 'sec' "
         f"select sym, total, n")
    assert rt.explain()["aggregations"]["Roll"]["path"] \
        == "device-resident"
    inproc = sorted(rt.query(q))
    wire = sorted(cli.query(q))
    cli.close()
    req = urllib.request.Request(
        f"{base}/siddhi/artifact/query",
        data=json.dumps({"app": "AggSmoke", "query": q}).encode(),
        method="POST")
    with urllib.request.urlopen(req) as r:
        rest = sorted((t, tuple(row)) for t, row in
                      json.loads(r.read())["rows"])
    assert len(inproc) > 0 and wire == inproc and rest == inproc, \
        (len(inproc), len(wire), len(rest))
    with urllib.request.urlopen(f"{base}/metrics") as r:
        text = r.read().decode()
    for series in ("siddhi_tpu_agg_groups", "siddhi_tpu_agg_buckets",
                   "siddhi_tpu_agg_store_queries_total"):
        assert any(ln.startswith(series) for ln in text.splitlines()), \
            f"{series} missing from /metrics"
    print(f"OK: {len(inproc)} rollup rows identical over wire QUERY, "
          f"REST, and in-process; agg series live")
finally:
    svc.stop()
EOF

echo "== queryable-state workload matrix smoke =="
# bench.py --matrix --smoke: shrunk DEBS-style cells (rollup cardinality
# sweep, mixed query/ingest, concurrent wire store queries), each cell
# device-vs-host parity-checked; last line must parse as JSON with
# per-cell eps + store-query p99
python bench.py --matrix --smoke | tee /tmp/_matrix_smoke.out
python - <<'EOF'
import json
d = json.loads(open("/tmp/_matrix_smoke.out")
               .read().strip().splitlines()[-1])
assert d["metric"] == "queryable_state_matrix" and d["value"] == 1, d
assert all(c.get("parity") for c in d["cells"].values()), d["cells"]
print("OK: matrix cells", ", ".join(
    f"{k}={c['eps']} eps" for k, c in d["cells"].items()))
EOF

echo "== seeded chaos smoke =="
# bench.py --chaos: injected dispatch + sink faults under a fixed seed;
# asserts zero event loss and full recovery (ladder halving, interpreter
# quarantine with byte-identical matches, sink retry/ErrorStore replay).
# Exits nonzero if any recovery path loses or duplicates an event.
python bench.py --chaos --seed 7

echo "== autotune smoke =="
# bench.py --autotune --smoke: one-config tuner sweep (output-invariance
# asserted per candidate) + the @app:latencySLO AIMD controller under
# paced load; the tuning cache is scoped to a throwaway path so CI never
# pollutes (or trusts) the developer's persisted winners
SIDDHI_TUNE_CACHE="$(mktemp -u /tmp/siddhi_tune_smoke.XXXXXX.json)" \
    python bench.py --autotune --smoke

echo "== plan-family parity smoke =="
# bench.py --family-smoke: one eligible pattern per NFA plan family
# (seq / chunk / scan / dfa), plus the ISSUE-13 count-quantifier and
# partitioned-lanes cells, each run differentially against the host
# interpreter — a lowering regression in any family fails fast here
# instead of surfacing as wrong matches in production
python bench.py --family-smoke

echo "== pipelined-vs-unpipelined bench smoke =="
# bench.py --smoke: short pipelined-vs-unpipelined run over the
# multi-plan overlap config; asserts identical match counts and prints
# the eps delta + overlap_ratio.  The LAST stdout line must round-trip
# through json.loads — the bench driver parses exactly that line, and
# an unparseable tail is the BENCH "parsed": null failure shape
python bench.py --smoke | tee /tmp/_bench_smoke.out
python - <<'EOF'
import json
line = open("/tmp/_bench_smoke.out").read().strip().splitlines()[-1]
parsed = json.loads(line)          # raises -> smoke fails
assert isinstance(parsed, dict) and "metric" in parsed, parsed
print("OK: bench --smoke last line parses:", parsed["metric"])
EOF

echo "== perf-regression sentinel =="
# scripts/perfcheck.py: fresh bench.py --trace --smoke vs the checked-in
# scripts/perf_baseline.json.  Exits 1 when the host-dispatch-share odds
# move past the band (the "someone made dispatch 2x more host-bound"
# regression), when any phase share drifts beyond its absolute band, or
# when attribution coverage drops below 0.9.  Raw eps is warn-only (CI
# machines jitter); a baseline written on a different workload config
# (config_hash mismatch) downgrades to a stale-baseline note so config
# refactors don't hard-fail until the baseline is regenerated
# (perfcheck.py --write-baseline, committed alongside)
python scripts/perfcheck.py

echo "smoke: PASS"
