// Native single-core baseline harness for the BASELINE.json configs.
//
// The environment has no JVM, so the reference engine cannot be run
// directly (BASELINE.md); this C++ harness is the calibration anchor
// instead: it executes the SAME matcher algorithms as the sequential
// host interpreter — branchy filter loop, pending-instance CEP
// matcher (reference StreamPreStateProcessor pending lists), per-key
// partitioned matchers — at optimized native single-core speed.  A
// single-threaded JVM engine on this hardware is bounded above by
// these numbers (JITted Java runs at or below -O2 C++ on this kind of
// pointer-light numeric code), so `device_eps / native_cpp_eps` is a
// conservative stand-in for "vs single-JVM CPU".
//
// Input: a binary tape [n x {int64 ts_ms, float price, int32 key}]
// written by bench.py (same random tape the python engines consume).
// Output: one line per config: "<name> <events_per_sec> <matches>".
//
// Build: g++ -O2 -std=c++17 -o bench_native bench_native.cpp
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

struct Ev { int64_t ts; float price; int32_t key; };

static std::vector<Ev> load(const char* path) {
    FILE* f = fopen(path, "rb");
    if (!f) { perror("tape"); exit(1); }
    fseek(f, 0, SEEK_END);
    long bytes = ftell(f);
    fseek(f, 0, SEEK_SET);
    size_t n = bytes / sizeof(Ev);
    std::vector<Ev> evs(n);
    if (fread(evs.data(), sizeof(Ev), n, f) != n) { perror("read"); exit(1); }
    fclose(f);
    return evs;
}

using clk = std::chrono::steady_clock;

static double secs(clk::time_point a, clk::time_point b) {
    return std::chrono::duration<double>(b - a).count();
}

// config 1: stateless filter `price > 100`, payload passthrough
static void run_filter(const std::vector<Ev>& evs) {
    auto t0 = clk::now();
    int64_t matches = 0;
    double sink = 0.0;                    // defeat dead-code elimination
    for (const Ev& e : evs) {
        if (e.price > 100.0f) { matches++; sink += e.price; }
    }
    auto t1 = clk::now();
    printf("filter %.0f %lld %.1f\n", evs.size() / secs(t0, t1),
           (long long)matches, sink);
}

// config 2: sliding length(1000) avg(price) per event
static void run_window(const std::vector<Ev>& evs) {
    auto t0 = clk::now();
    const size_t L = 1000;
    std::vector<float> ring(L, 0.0f);
    double sum = 0.0, sink = 0.0;
    size_t filled = 0, pos = 0;
    for (const Ev& e : evs) {
        if (filled == L) sum -= ring[pos];
        ring[pos] = e.price;
        sum += e.price;
        pos = (pos + 1) % L;
        if (filled < L) filled++;
        sink += sum / (double)filled;     // the per-event avg output
    }
    auto t1 = clk::now();
    printf("window %.0f %lld %.1f\n", evs.size() / secs(t0, t1),
           (long long)evs.size(), sink);
}

// pending-instance sequence matcher: every e1[p>100] -> e2[p>e1.p]
// within 1 sec (the host oracle's algorithm, native speed)
static void run_sequence(const std::vector<Ev>& evs) {
    auto t0 = clk::now();
    struct Pend { int64_t ts; float p; };
    std::vector<Pend> pend;
    pend.reserve(4096);
    int64_t matches = 0;
    double sink = 0.0;
    for (const Ev& e : evs) {
        size_t w = 0;
        for (size_t i = 0; i < pend.size(); i++) {
            if (e.ts - pend[i].ts > 1000) continue;       // within expiry
            if (e.price > pend[i].p) {                    // e2 fires
                matches++;
                sink += pend[i].p + e.price;
                continue;                                 // instance done
            }
            pend[w++] = pend[i];
        }
        pend.resize(w);
        if (e.price > 100.0f) pend.push_back({e.ts, e.price});  // every e1
    }
    auto t1 = clk::now();
    printf("sequence %.0f %lld %.1f\n", evs.size() / secs(t0, t1),
           (long long)matches, sink);
}

// partitioned 3-state chain per key: every e1[p>100] -> e2[p>e1.p]
// -> e3[p>e2.p] within 10 sec, partition by key
static void run_partitioned(const std::vector<Ev>& evs, int n_keys) {
    auto t0 = clk::now();
    struct Pend { int64_t ts; float p1, p2; uint8_t stage; };
    std::vector<std::vector<Pend>> pend(n_keys);
    int64_t matches = 0;
    double sink = 0.0;
    for (const Ev& e : evs) {
        auto& ps = pend[e.key];
        size_t w = 0;
        for (size_t i = 0; i < ps.size(); i++) {
            Pend& pd = ps[i];
            if (e.ts - pd.ts > 10000) continue;
            if (pd.stage == 1) {
                if (e.price > pd.p1) { pd.stage = 2; pd.p2 = e.price; }
                ps[w++] = pd;
            } else {
                if (e.price > pd.p2) {
                    matches++;
                    sink += pd.p1 + pd.p2 + e.price;
                    continue;
                }
                ps[w++] = pd;
            }
        }
        ps.resize(w);
        if (e.price > 100.0f) ps.push_back({e.ts, e.price, 0.0f, 1});
    }
    auto t1 = clk::now();
    printf("partitioned %.0f %lld %.1f\n", evs.size() / secs(t0, t1),
           (long long)matches, sink);
}

int main(int argc, char** argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: bench_native <tape.bin> <config...>\n");
        return 2;
    }
    auto evs = load(argv[1]);
    for (int i = 2; i < argc; i++) {
        std::string c = argv[i];
        if (c == "filter") run_filter(evs);
        else if (c == "window") run_window(evs);
        else if (c == "sequence") run_sequence(evs);
        else if (c.rfind("partitioned", 0) == 0) {
            int keys = 1000;
            auto pos = c.find(':');
            if (pos != std::string::npos) keys = atoi(c.c_str() + pos + 1);
            run_partitioned(evs, keys);
        } else {
            fprintf(stderr, "unknown config %s\n", c.c_str());
            return 2;
        }
    }
    return 0;
}
