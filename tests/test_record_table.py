"""External-store table SPI (reference: AbstractRecordTable +
ExpressionBuilder pushdown; test double = InMemoryRecordStore, the analog
of TestStoreContainingInMemoryTable)."""
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.record_table import (InMemoryRecordStore, RecordTable,
                                          StoreCondition, register_store_type)


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


APP = """
define stream S (sym string, price double);
@store(type='testStore')
@PrimaryKey('sym')
define table T (sym string, price double);
@info(name='ins') from S[price > 0] select sym, price insert into T;
"""


def _store_of(rt, tid="T"):
    return rt.tables[tid].store


def test_insert_and_store_query(mgr):
    rt = mgr.create_app_runtime(APP)
    h = rt.input_handler("S")
    rt.start()
    h.send(("IBM", 101.0)); h.send(("WSO2", 55.0))
    rt.flush()
    st = _store_of(rt)
    assert len(st.records) == 2 and st.op_counts["add"] >= 1
    rows = rt.query("from T on price > 60.0 select sym, price")
    assert [r for _t, r in rows] == [("IBM", 101.0)]


def test_update_delete_update_or_insert(mgr):
    rt = mgr.create_app_runtime(APP + """
define stream U (sym string, price double);
@info(name='upd') from U select sym, price update T on T.sym == sym;
define stream D (sym string);
@info(name='del') from D select sym delete T on T.sym == sym;
define stream UO (sym string, price double);
@info(name='uoi') from UO select sym, price update or insert into T
  on T.sym == sym;
""")
    rt.start()
    rt.input_handler("S").send(("IBM", 100.0))
    rt.input_handler("U").send(("IBM", 200.0))
    rt.flush()
    assert rt.tables["T"].all_rows() == [("IBM", 200.0)]
    rt.input_handler("UO").send(("NEW", 7.0))      # no match -> insert
    rt.input_handler("UO").send(("IBM", 300.0))    # match -> update
    rt.flush()
    assert sorted(rt.tables["T"].all_rows()) == [("IBM", 300.0), ("NEW", 7.0)]
    rt.input_handler("D").send(("IBM",))
    rt.flush()
    assert rt.tables["T"].all_rows() == [("NEW", 7.0)]


def test_join_against_record_table(mgr):
    rt = mgr.create_app_runtime(APP + """
define stream Probe (sym string);
@info(name='j') from Probe join T on T.sym == Probe.sym
select Probe.sym as sym, T.price as price insert into O;
""")
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.start()
    rt.input_handler("S").send(("IBM", 42.0))
    rt.flush()
    rt.input_handler("Probe").send(("IBM",))
    rt.input_handler("Probe").send(("MISS",))
    rt.flush()
    assert out == [("IBM", 42.0)]


def test_in_table_membership(mgr):
    rt = mgr.create_app_runtime(APP + """
define stream C (sym string, x int);
@info(name='m') from C[sym in T] select sym, x insert into O;
""")
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.start()
    rt.input_handler("S").send(("IBM", 1.0))
    rt.flush()
    rt.input_handler("C").send(("IBM", 1))
    rt.input_handler("C").send(("NOPE", 2))
    rt.flush()
    assert out == [("IBM", 1)]


def test_condition_pushdown_shape(mgr):
    """The store receives a compiled tree with lifted stream params —
    not row-by-row engine probes."""
    seen = []

    class SpyStore(InMemoryRecordStore):
        def find(self, condition, params):
            seen.append((condition.node, dict(params)))
            return super().find(condition, params)

    register_store_type("spyStore", SpyStore)
    rt = mgr.create_app_runtime("""
define stream S (sym string, price double);
@store(type='spyStore')
define table T (sym string, price double);
define stream P (sym string, lo double);
@info(name='q') from P join T on T.sym == P.sym and T.price > lo + 1.0
select P.sym as sym, T.price as price insert into O;
""")
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.start()
    rt.tables["T"].store.add([{"sym": "A", "price": 10.0},
                              {"sym": "A", "price": 3.0}])
    rt.input_handler("P").send(("A", 5.0))
    rt.flush()
    assert out == [("A", 10.0)]
    node, params = seen[-1]
    assert node[0] == "and"
    assert ("col", "sym") in (node[1][2], node[1][3])
    assert any(isinstance(v, float) and v == 6.0 for v in params.values())


def test_snapshot_restore_record_table(mgr):
    rt = mgr.create_app_runtime(APP)
    rt.start()
    rt.input_handler("S").send(("IBM", 9.0))
    rt.flush()
    snap = rt.snapshot()
    rt2 = mgr.create_app_runtime(APP)
    rt2.restore(snap)
    assert rt2.tables["T"].all_rows() == [("IBM", 9.0)]


def test_connect_retry_and_unknown_type(mgr):
    calls = []

    class Flaky(InMemoryRecordStore):
        def connect(self):
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")

    register_store_type("flakyStore", Flaky)
    with pytest.warns(RuntimeWarning):
        rt = mgr.create_app_runtime("""
@store(type='flakyStore')
define table T (x int);
""")
    assert len(calls) == 3 and rt.tables["T"].store.connected

    from siddhi_tpu.core.planner import PlanError
    with pytest.raises(PlanError, match="unknown store type"):
        mgr.create_app_runtime("@store(type='nosuch')\ndefine table X (x int);")
