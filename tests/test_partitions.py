"""Partitions: per-key query instances (clone path) and the device
partition axis (batched-NFA path).  Reference semantics:
core:partition/PartitionRuntime.java + PartitionStreamReceiver.java."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def test_value_partition_window_agg(mgr):
    # per-key length window: windows must not leak across keys
    rt = mgr.create_app_runtime("""
    define stream S (sym string, p double);
    partition with (sym of S)
    begin
      @info(name='q') from S#window.length(2) select sym, sum(p) as total
      insert into O;
    end;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    for row in (("A", 1.0), ("B", 10.0), ("A", 2.0), ("B", 20.0), ("A", 3.0)):
        h.send(row)
    rt.flush()
    # per-key order is guaranteed; cross-key interleaving is not (batched
    # dispatch processes one instance's sub-batch at a time)
    assert [p for s, p in out if s == "A"] == [1.0, 3.0, 5.0]
    assert [p for s, p in out if s == "B"] == [10.0, 30.0]


def test_value_partition_filter(mgr):
    rt = mgr.create_app_runtime("""
    define stream S (sym string, v int);
    partition with (sym of S)
    begin
      @info(name='q') from S[v > 5] select sym, v insert into O;
    end;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    h.send(("A", 3)); h.send(("B", 7)); h.send(("A", 9))
    rt.flush()
    assert sorted(out) == [("A", 9), ("B", 7)]


def test_range_partition(mgr):
    rt = mgr.create_app_runtime("""
    define stream S (v int);
    partition with (v < 10 as 'small' or v >= 10 as 'big' of S)
    begin
      @info(name='q') from S select v, count() as c insert into O;
    end;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    for v in (1, 2, 100, 3, 200):
        h.send((v,))
    rt.flush()
    # counts are per range bucket (cross-bucket interleaving not guaranteed)
    assert sorted(out) == [(1, 1), (2, 2), (3, 3), (100, 1), (200, 2)]


def test_partition_inner_stream(mgr):
    rt = mgr.create_app_runtime("""
    define stream S (sym string, p double);
    partition with (sym of S)
    begin
      from S select sym, p * 2 as p2 insert into #doubled;
      @info(name='q') from #doubled[p2 > 10] select sym, p2 insert into O;
    end;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    h.send(("A", 3.0)); h.send(("B", 6.0)); h.send(("A", 7.0))
    rt.flush()
    assert sorted(out) == [("A", 14.0), ("B", 12.0)]


PATTERN_PART = """
define stream S (sym string, p double);
partition with (sym of S)
begin
  @info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p]
  select e1.p as p1, e2.p as p2 insert into M;
end;
"""


def test_partitioned_pattern_device_axis(mgr):
    rt = mgr.create_app_runtime(PATTERN_PART)
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    plans = [p for p in rt._plans if isinstance(p, DevicePatternPlan)]
    assert len(plans) == 1, "partitioned pattern should use the device axis"
    out = []
    rt.add_callback("M", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    # interleave keys: matches must stay within their key
    h.send(("A", 101.0), timestamp=1000)
    h.send(("B", 500.0), timestamp=1001)   # B's e1
    h.send(("A", 102.0), timestamp=1002)   # A match (101,102)
    h.send(("B", 400.0), timestamp=1003)   # not > 500
    h.send(("B", 501.0), timestamp=1004)   # B match (500,501)
    rt.flush()
    assert (101.0, 102.0) in out and (500.0, 501.0) in out
    assert (101.0, 500.0) not in out and (500.0, 102.0) not in out


def test_partitioned_pattern_vs_clones(mgr):
    """Differential: device partition axis vs per-key host clones."""
    rng = np.random.default_rng(3)
    syms = ["K%d" % i for i in range(7)]
    sends = []
    for i in range(120):
        sends.append((syms[int(rng.integers(len(syms)))],
                      float(np.round(rng.uniform(90, 120) * 4) / 4), 1000 + i))
    outs = {}
    for mode in ("auto", "never"):
        app = f"@app:devicePatterns('{mode}')\n" + PATTERN_PART
        rt = mgr.create_app_runtime(app)
        out = []
        rt.add_callback("M", lambda evs, o=out: o.extend(e.data for e in evs))
        h = rt.input_handler("S")
        rt.start()
        for sym, p, ts in sends:
            h.send((sym, p), timestamp=ts)
        rt.flush()
        outs[mode] = out
    # cross-key interleaving differs between strategies (clone dispatch is
    # per-instance); the match multiset must be identical
    assert sorted(outs["auto"]) == sorted(outs["never"])


def test_partition_capacity_growth(mgr):
    app = "@app:partitionCapacity(4)\n" + PATTERN_PART
    rt = mgr.create_app_runtime(app)
    out = []
    rt.add_callback("M", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    for i in range(10):             # 10 keys > capacity 4 -> growth
        h.send(("K%d" % i, 101.0), timestamp=1000 + i)
    for i in range(10):
        h.send(("K%d" % i, 102.0), timestamp=2000 + i)
    rt.flush()
    assert len(out) == 10
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    plan = [p for p in rt._plans if isinstance(p, DevicePatternPlan)][0]
    assert plan.P >= 10


def test_partition_snapshot_restore(mgr):
    rt = mgr.create_app_runtime(PATTERN_PART)
    h = rt.input_handler("S")
    rt.start()
    h.send(("A", 101.0), timestamp=1000)
    h.send(("B", 300.0), timestamp=1001)
    rt.flush()
    snap = rt.snapshot()

    rt2 = mgr.create_app_runtime(PATTERN_PART)
    out = []
    rt2.add_callback("M", lambda evs: out.extend(e.data for e in evs))
    rt2.restore(snap)
    h2 = rt2.input_handler("S")
    h2.send(("A", 102.0), timestamp=1002)
    h2.send(("B", 301.0), timestamp=1003)
    rt2.flush()
    assert sorted(out) == [(101.0, 102.0), (300.0, 301.0)]


def test_partition_query_callback(mgr):
    rt = mgr.create_app_runtime("""
    define stream S (sym string, v int);
    partition with (sym of S)
    begin
      @info(name='pq') from S[v > 0] select sym, v insert into O;
    end;
    """)
    got = []
    rt.add_query_callback("pq", lambda ts, ins, outs: got.extend(ins))
    h = rt.input_handler("S")
    rt.start()
    h.send(("A", 1)); h.send(("B", 2))
    rt.flush()
    assert len(got) == 2
