"""Stream-stream window joins (reference: core:query/input/stream/join/
JoinProcessor.java — probe opposite window on arrival, outer variants,
unidirectional)."""
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run(mgr, app, sends, out="O"):
    rt = mgr.create_app_runtime(app)
    got = []
    rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
    hs = {}
    rt.start()
    for sid, row, ts in sends:
        hs.setdefault(sid, rt.input_handler(sid)).send(row, timestamp=ts)
    rt.flush()
    return got, rt


APP = """
define stream L (sym string, lv int);
define stream R (sym string, rv int);
@info(name='j')
from L#window.length(10) as a join R#window.length(10) as b
  on a.sym == b.sym
select a.sym as sym, a.lv as lv, b.rv as rv insert into O;
"""


def test_inner_join_basic(mgr):
    got, _ = run(mgr, APP, [
        ("L", ("IBM", 1), 1000),
        ("R", ("IBM", 2), 1001),     # matches L(IBM,1)
        ("R", ("WSO2", 3), 1002),    # no L yet
        ("L", ("WSO2", 4), 1003),    # matches R(WSO2,3)
        ("L", ("IBM", 5), 1004),     # matches R(IBM,2)
    ])
    assert sorted(got) == [("IBM", 1, 2), ("IBM", 5, 2), ("WSO2", 4, 3)]


def test_join_no_self_match_same_event(mgr):
    app = """
    define stream S (sym string, v int);
    @info(name='j')
    from S#window.length(10) as a join S#window.length(10) as b
      on a.sym == b.sym
    select a.v as av, b.v as bv insert into O;
    """
    got, _ = run(mgr, app, [("S", ("X", 1), 1000), ("S", ("X", 2), 1001)])
    # an arriving event probes existing opposite content only — it never
    # joins itself (probes run before either side retains)
    assert sorted(got) == [(1, 2), (2, 1)]


def test_left_outer_join(mgr):
    app = APP.replace("join", "left outer join", 1)
    got, _ = run(mgr, app, [
        ("L", ("A", 1), 1000),       # no right match -> nulls
        ("R", ("A", 2), 1001),
        ("L", ("A", 3), 1002),       # matches
        ("R", ("B", 9), 1003),       # right arrival unmatched: NOT emitted
    ])
    assert ("A", 1, None) in got     # outer-join miss emits real null
    assert ("A", 3, 2) in got
    assert not any(g[0] == "B" for g in got)


def test_unidirectional_join(mgr):
    app = APP.replace("as a join", "as a unidirectional join", 1)
    got, _ = run(mgr, app, [
        ("L", ("A", 1), 1000),
        ("R", ("A", 2), 1001),       # right arrival must not emit
        ("L", ("A", 3), 1002),       # left arrival emits
    ])
    assert got == [("A", 3, 2)]


def test_time_window_join_expiry(mgr):
    app = """
    define stream L (k int);
    define stream R (k int);
    @info(name='j')
    from L#window.time(1 sec) as a join R#window.time(1 sec) as b on a.k == b.k
    select a.k as k insert into O;
    """
    rt = mgr.create_app_runtime(app)
    got = []
    rt.add_callback("O", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    rt.set_time(1000)                # pin the virtual clock
    rt.input_handler("L").send((7,), timestamp=1000)
    rt.flush()
    rt.set_time(3000)                # L(7) expires from the window
    rt.input_handler("R").send((7,), timestamp=3000)
    rt.flush()
    assert got == []


def test_join_aggregation(mgr):
    app = """
    define stream L (sym string, lv int);
    define stream R (sym string, rv int);
    @info(name='j')
    from L#window.length(10) as a join R#window.length(10) as b
      on a.sym == b.sym
    select a.sym as sym, sum(b.rv) as total group by a.sym insert into O;
    """
    got, _ = run(mgr, app, [
        ("R", ("A", 1), 1000), ("R", ("A", 2), 1001),
        ("L", ("A", 0), 1002),       # joins both retained R rows
    ])
    assert got[-1] == ("A", 3)


def test_join_snapshot_restore(mgr):
    sends = [("L", ("A", 1), 1000), ("R", ("A", 2), 1001)]
    _got, rt = run(mgr, APP, sends)
    snap = rt.snapshot()
    rt2 = mgr.create_app_runtime(APP)
    got2 = []
    rt2.add_callback("O", lambda evs: got2.extend(e.data for e in evs))
    rt2.restore(snap)
    rt2.input_handler("L").send(("A", 9), timestamp=1002)
    rt2.flush()
    assert got2 == [("A", 9, 2)]
