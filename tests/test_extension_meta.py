"""Extension metadata tier (reference: siddhi-annotations @Extension +
SiddhiAnnotationProcessor.java:55-73 compile-time validation) and the
doc generator built on it (reference: siddhi-doc-gen)."""
import pytest

from siddhi_tpu.extension import (Example, ExtensionError, ExtensionMeta,
                                  Parameter, all_meta, meta_for,
                                  validate_meta)
from siddhi_tpu import docgen

# the parser's built-in window dispatch (interp/engine.py make_window)
BUILTIN_WINDOW_NAMES = [
    "length", "lengthbatch", "time", "timebatch", "externaltime",
    "externaltimebatch", "timelength", "batch", "session", "sort",
    "delay", "frequent", "lossyfrequent", "cron"]


def test_every_builtin_window_has_full_metadata():
    have = {m.name.lower(): m for m in all_meta("window")}
    for name in BUILTIN_WINDOW_NAMES:
        m = have.get(name)
        assert m is not None, f"built-in window {name} missing metadata"
        assert m.description and m.parameters and m.examples, name
        for p in m.parameters:
            assert p.name and p.description and p.type, (name, p)
        for e in m.examples:
            assert e.syntax and e.description, (name, e)


def test_every_builtin_aggregator_has_full_metadata():
    from siddhi_tpu.interp.aggregators import AGGREGATOR_CLASSES
    have = {m.name.lower(): m for m in all_meta("aggregator")}
    for name in AGGREGATOR_CLASSES:
        m = have.get(name)
        assert m is not None, f"aggregator {name} missing metadata"
        assert m.description and m.parameters and m.examples, name
        assert m.returns, name


def test_docgen_renders_params_and_examples():
    md = docgen.generate_markdown()
    for name in BUILTIN_WINDOW_NAMES:
        # section header present (case preserved in metadata table)
        assert f"`{name}`" in md.lower(), name
    assert "| parameter | types | description |" in md
    assert "```siddhi" in md
    assert "**Returns**:" in md
    # a known example renders
    assert "from S#window.length(10)" in md


def test_validation_rejects_incomplete_meta():
    with pytest.raises(ExtensionError, match="description is mandatory"):
        validate_meta(ExtensionMeta("x", ""))
    with pytest.raises(ExtensionError, match="needs a description"):
        validate_meta(ExtensionMeta(
            "x", "ok", parameters=(Parameter("p", ("INT",), ""),)))
    with pytest.raises(ExtensionError, match="needs accepted types"):
        validate_meta(ExtensionMeta(
            "x", "ok", parameters=(Parameter("p", (), "d"),)))
    with pytest.raises(ExtensionError, match="example with empty syntax"):
        validate_meta(ExtensionMeta("x", "ok", examples=(Example(""),)))
    with pytest.raises(ExtensionError, match="whitespace"):
        validate_meta(ExtensionMeta("bad name", "ok"))


def test_register_with_meta_flows_to_docs():
    from siddhi_tpu.interp.engine import WINDOW_TYPES, register_window_type
    meta = ExtensionMeta(
        "testwin", "A test window retaining everything.",
        parameters=(Parameter("n", ("INT",), "retention count"),),
        examples=(Example("from S#window.testwin(5) select * insert into O;",
                          "keeps 5"),))
    register_window_type("testwin", lambda a, c, s: None, meta=meta)
    try:
        assert meta_for("window", "testwin") is meta
        md = docgen.generate_markdown()
        assert "A test window retaining everything." in md
        assert "retention count" in md
    finally:
        WINDOW_TYPES.pop((None, "testwin"), None)


def test_register_with_bad_meta_raises_at_registration():
    from siddhi_tpu.interp.engine import register_window_type
    with pytest.raises(ExtensionError):
        register_window_type(
            "badwin", lambda a, c, s: None,
            meta=ExtensionMeta("badwin", ""))
