"""Multi-query device batching: structurally identical pattern queries
fuse into one kernel whose lanes are the query instances (BASELINE
config 5; reference analog = N QueryRuntimes walking processor chains)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.multi_query import MultiQueryDevicePatternPlan


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def _app(n_queries=12, shapes=(0,)):
    parts = ["define stream S (sym string, price double);"]
    for i in range(n_queries):
        lo = 100 + (i % 8)
        shape = shapes[i % len(shapes)]
        if shape == 0:
            parts.append(
                f"@info(name='q{i}') from every e1=S[price > {lo}.0] -> "
                f"e2=S[price > e1.price] within 1 sec "
                f"select e1.price as a{i}, e2.price as b{i} "
                f"insert into Out{i % 4};")
        else:
            parts.append(
                f"@info(name='q{i}') from e1=S[price > {lo + 1}.0] -> "
                f"not S[price < {lo - 20}.0] for 500 milliseconds "
                f"select e1.price as a{i} insert into Out{i % 4};")
    return "\n".join(parts)


def _run(mgr, app, sends, n_out=4):
    rt = mgr.create_app_runtime(app)
    got = {f"Out{j}": [] for j in range(n_out)}
    for j in range(n_out):
        rt.add_callback(f"Out{j}",
                        lambda evs, g=got[f"Out{j}"]:
                        g.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for p, ts in sends:
        h.send(("A", p), timestamp=ts)
    rt.flush()
    return got, rt


def _tape(n=250, seed=4):
    rng = np.random.default_rng(seed)
    return [(float(np.round(rng.uniform(95, 112) * 4) / 4), 1000 + k * 20)
            for k in range(n)]


def test_fused_equals_sequential(mgr):
    app = _app(12)
    sends = _tape()
    dev, drt = _run(mgr, app, sends)
    fused = [p for p in drt._plans
             if isinstance(p, MultiQueryDevicePatternPlan)]
    assert len(fused) == 1 and fused[0].n_queries == 12
    host, hrt = _run(mgr, "@app:devicePatterns('never')\n" + app, sends)
    assert not any(isinstance(p, MultiQueryDevicePatternPlan)
                   for p in hrt._plans)
    for j in range(4):
        assert sorted(dev[f"Out{j}"]) == sorted(host[f"Out{j}"])
    assert sum(len(v) for v in dev.values()) > 0


def test_mixed_shapes_group_separately(mgr):
    app = _app(16, shapes=(0, 1))
    sends = _tape(300)
    dev, drt = _run(mgr, "@app:playback\n" + app, sends)
    fused = [p for p in drt._plans
             if isinstance(p, MultiQueryDevicePatternPlan)]
    assert sorted(p.n_queries for p in fused) == [8, 8]
    host, _ = _run(mgr, "@app:playback\n@app:devicePatterns('never')\n" + app,
                   sends)
    for j in range(4):
        assert sorted(dev[f"Out{j}"]) == sorted(host[f"Out{j}"])


def test_small_groups_stay_individual(mgr):
    app = _app(4)          # below MIN_GROUP
    _got, rt = _run(mgr, app, _tape(40))
    assert not any(isinstance(p, MultiQueryDevicePatternPlan)
                   for p in rt._plans)


def test_fused_snapshot_restore(mgr):
    app = _app(12)
    sends = _tape(120)
    dev, rt = _run(mgr, app, sends)
    snap = rt.snapshot()
    rt2 = mgr.create_app_runtime(app)
    got2 = {f"Out{j}": [] for j in range(4)}
    for j in range(4):
        rt2.add_callback(f"Out{j}", lambda evs, g=got2[f"Out{j}"]:
                         g.extend(e.data for e in evs))
    rt2.restore(snap)
    h = rt2.input_handler("S")
    # a pending e1 from before the snapshot should complete after restore
    h.send(("A", 130.0), timestamp=sends[-1][1] + 10)
    rt2.flush()
    assert sum(len(v) for v in got2.values()) > 0
