"""Device-resident aggregation (core/agg_device.py): forced-path
differential matrix (device bucket stores byte-identical to the host
reduce path), @purge retention/eviction, capacity growth, and the
placement/telemetry surfaces (docs/AGGREGATION.md)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.query.ast import Duration

def _app(select, group_by, durations, header="", agg_header=""):
    gb = f"group by {group_by}\n" if group_by else ""
    return (f"{header}"
            f"define stream S (k string, k2 string, v double, w double, "
            f"ts long);\n"
            f"{agg_header}"
            f"define aggregation A\nfrom S\nselect {select}\n{gb}"
            f"aggregate by ts every {durations};\n")


def _feed(rt, rows):
    h = rt.input_handler("S")
    h.send(rows)
    rt.flush()


def _rows(rng, n, nk=4, nk2=3, span_ms=400_000):
    """n events over ~span_ms of event time: raw uniform doubles —
    byte-identity must hold without any value quantization because both
    paths fold events in the same order."""
    ts0 = 1_700_000_000_000
    ts = np.sort(ts0 + rng.integers(0, span_ms, n))
    return [(f"K{rng.integers(0, nk)}", f"G{rng.integers(0, nk2)}",
             float(rng.uniform(-50, 150)), float(rng.uniform(0, 9)),
             int(t)) for t in ts]


def _run(app, batches):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.start()
    for b in batches:
        _feed(rt, b)
    agg = rt.aggregations["A"]
    state = agg.state_dict()
    mgr.shutdown()
    return state, agg


# ---------------------------------------------------------------------------
# forced-path differential matrix: every base function x group-by arity
# 0/1/2 x duration ladders — the device store must be BYTE-IDENTICAL to
# the host reduce path's (same floats, same keys), not merely close
# ---------------------------------------------------------------------------

MATRIX = [
    ("sum(v) as s", "k", "sec, min"),
    ("avg(v) as a", "k, k2", "sec, min, hour"),
    ("min(v) as lo, max(v) as hi", None, "sec"),
    ("count() as n", "k", "sec, min"),
    ("sum(v) as s, avg(w) as a, min(v) as lo, max(w) as hi, count() as n",
     "k, k2", "sec, min, hour, day"),
    ("sum(v) as s, avg(v) as a", None, "sec, min"),
]


@pytest.mark.parametrize("select,group_by,durations", MATRIX)
def test_device_resident_matches_host_bytes(select, group_by, durations):
    batches = [_rows(np.random.default_rng(17 + i), 257 + 31 * i)
               for i in range(4)]
    dev_state, dev_agg = _run(_app(select, group_by, durations), batches)
    host_state, host_agg = _run(
        _app(select, group_by, durations,
             header="@app:deviceAggregations('off')\n"), batches)
    assert dev_agg.device_plan is not None and host_agg.device_plan is None
    assert dev_state == host_state


def test_differential_query_rows_identical():
    """The user-visible surface too: rt.query rows (finalized avg etc.)
    equal between the paths, at every duration level."""
    select = "k, sum(v) as s, avg(v) as a, count() as n"
    batches = [_rows(np.random.default_rng(5), 900, span_ms=7_200_000)]
    results = {}
    for name, header in (("dev", ""),
                         ("host", "@app:deviceAggregations('off')\n")):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(_app(select, "k", "sec, min, hour",
                                         header=header))
        rt.start()
        for b in batches:
            _feed(rt, b)
        results[name] = {
            per: sorted(rt.query(
                f"from A within 0L, 4000000000000L per '{per}' "
                f"select k, s, a, n"))
            for per in ("sec", "min", "hour")}
        mgr.shutdown()
    assert results["dev"] == results["host"]
    assert all(results["dev"][per] for per in ("sec", "min", "hour"))


def test_incremental_merge_across_batches():
    """A key seen in several batches merges into the SAME device slot
    (old op new), not a fresh row per batch."""
    app = _app("k, sum(v) as s, min(v) as lo, max(v) as hi, count() as n",
               "k", "sec")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.start()
    _feed(rt, [("A", "x", 10.25, 0.0, 1000), ("A", "x", 2.5, 0.0, 1500)])
    _feed(rt, [("A", "x", -4.0, 0.0, 1200), ("A", "x", 100.0, 0.0, 1900)])
    rows = rt.query("from A within 0L, 10000L per 'sec' "
                    "select k, s, lo, hi, n")
    assert rows == [(1000, ("A", 108.75, -4.0, 100.0, 4))]
    agg = rt.aggregations["A"]
    assert agg.device_plan.live_buckets(Duration.SECONDS) == 1
    mgr.shutdown()


# ---------------------------------------------------------------------------
# placement: the D-AGG demotion taxonomy + explain() surfaces
# ---------------------------------------------------------------------------

def test_default_is_device_resident_and_explained():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app("sum(v) as s", "k", "sec, min"))
    agg = rt.aggregations["A"]
    assert agg.device_plan is not None and not agg.device
    ex = rt.explain()["aggregations"]["A"]
    assert ex["path"] == "device-resident"
    assert ex["durations"] == ["SECONDS", "MINUTES"]
    mgr.shutdown()


def test_opt_out_demotes_with_d_agg():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app(
        "sum(v) as s", "k", "sec",
        header="@app:deviceAggregations('off')\n"))
    agg = rt.aggregations["A"]
    assert agg.device_plan is None and not agg.device
    ex = rt.explain()["aggregations"]["A"]
    assert ex["path"] == "host"
    assert any(d["rule_id"] == "D-AGG" for d in ex["demotions"])
    mgr.shutdown()


def test_env_opt_out_demotes(monkeypatch):
    monkeypatch.setenv("SIDDHI_AGG_DEVICE", "off")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app("sum(v) as s", "k", "sec"))
    agg = rt.aggregations["A"]
    assert agg.device_plan is None
    ex = rt.explain()["aggregations"]["A"]
    assert any(d["rule_id"] == "D-AGG" for d in ex["demotions"])
    mgr.shutdown()


def test_calendar_durations_stay_on_host():
    """MONTHS/YEARS buckets are calendar-truncated (datetime64 math on
    the host); the resident plan declines them loudly instead of
    approximating."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app("k, sum(v) as s", "k", "sec, month"))
    agg = rt.aggregations["A"]
    assert agg.device_plan is None
    ex = rt.explain()["aggregations"]["A"]
    assert ex["path"] == "host"
    assert any(d["rule_id"] == "D-AGG" and "calendar" in d["reason"]
               for d in ex["demotions"])
    # the host fallback still aggregates correctly
    _feed(rt, [("A", "x", 1.5, 0.0, 1000), ("A", "x", 2.0, 0.0, 1500)])
    rows = rt.query("from A within 0L, 10000L per 'sec' select k, s")
    assert rows == [(1000, ("A", 3.5))]
    mgr.shutdown()


def test_legacy_always_mode_keeps_batch_kernel():
    # @app:deviceAggregations('always') keeps the pre-existing per-batch
    # device reduce semantics (mesh-shardable) — not the resident plan
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app(
        "sum(v) as s", "k", "sec",
        header="@app:deviceAggregations('always')\n"))
    agg = rt.aggregations["A"]
    assert agg.device and agg.device_plan is None
    assert rt.explain()["aggregations"]["A"]["path"] == "device-batch"
    mgr.shutdown()


# ---------------------------------------------------------------------------
# @purge retention: per-duration eviction, host/device parity
# ---------------------------------------------------------------------------

def test_purge_evicts_old_buckets_both_paths():
    rows = ([("A", "x", 1.0, 0.0, 1_000)] +
            [("A", "x", 2.0, 0.0, 5_000)] +
            [("B", "x", 3.0, 0.0, 600_000)])  # 10 min later
    states = {}
    for name, header in (("dev", ""),
                         ("host", "@app:deviceAggregations('off')\n")):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(_app(
            "k, sum(v) as s", "k", "sec, min", header=header,
            agg_header="@purge(retention='1 min')\n"))
        rt.start()
        _feed(rt, rows[:2])
        agg = rt.aggregations["A"]
        assert agg.retention_ms == {Duration.SECONDS: 60_000,
                                    Duration.MINUTES: 60_000}
        assert agg.evicted[Duration.SECONDS] == 0
        _feed(rt, rows[2:])      # newest bucket moves -> cutoff passes
        assert agg.evicted[Duration.SECONDS] == 2, name
        assert agg.evicted[Duration.MINUTES] == 1, name
        states[name] = agg.state_dict()
        # evicted buckets are gone from query results too
        got = rt.query("from A within 0L, 4000000000000L per 'sec' "
                       "select k, s")
        assert got == [(600_000, ("B", 3.0))], name
        ex = rt.explain()["aggregations"]["A"]
        assert ex["evicted"] == {"SECONDS": 2, "MINUTES": 1}
        assert ex["retention_ms"] == {"SECONDS": 60_000,
                                      "MINUTES": 60_000}
        mgr.shutdown()
    assert states["dev"] == states["host"]


def test_purge_per_duration_spans():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app(
        "sum(v) as s", "k", "sec, min, hour",
        agg_header="@purge(sec='2 min', min='1 hour')\n"))
    agg = rt.aggregations["A"]
    assert agg.retention_ms == {Duration.SECONDS: 120_000,
                                Duration.MINUTES: 3_600_000}
    assert Duration.HOURS not in agg.retention_ms
    mgr.shutdown()


def test_purge_disable_is_respected():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app(
        "sum(v) as s", "k", "sec",
        agg_header="@purge(enable='false')\n"))
    assert rt.aggregations["A"].retention_ms == {}
    mgr.shutdown()


def test_eviction_frees_slots_for_reuse():
    """Device rings recycle evicted slots (host-side frees, zero device
    traffic): sustained ingest under retention never grows capacity."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app(
        "sum(v) as s", "k", "sec", header="@app:aggCapacity(8)\n",
        agg_header="@purge(retention='2 sec')\n"))
    rt.start()
    agg = rt.aggregations["A"]
    for k in range(40):      # 40 buckets through an 8-slot ring
        _feed(rt, [("A", "x", 1.0, 0.0, 1_000 * k)])
    assert agg.device_plan.capacity(Duration.SECONDS) == 8
    assert agg.evicted[Duration.SECONDS] >= 30
    assert agg.device_plan.live_buckets(Duration.SECONDS) <= 4
    mgr.shutdown()


# ---------------------------------------------------------------------------
# capacity: annotation knob + growth, parity preserved across a grow
# ---------------------------------------------------------------------------

def test_capacity_annotation_and_growth():
    batches = [_rows(np.random.default_rng(3), 400, nk=6, nk2=1,
                     span_ms=90_000)]
    dev_state, dev_agg = _run(_app(
        "sum(v) as s, count() as n", "k", "sec",
        header="@app:aggCapacity(8)\n"), batches)
    host_state, _ = _run(_app(
        "sum(v) as s, count() as n", "k", "sec",
        header="@app:deviceAggregations('off')\n"), batches)
    # ~90 buckets x 6 keys blew well past 8 slots: the ring doubled
    cap = dev_agg.device_plan.capacity(Duration.SECONDS)
    live = dev_agg.device_plan.live_buckets(Duration.SECONDS)
    assert cap >= live > 8
    assert dev_state == host_state


# ---------------------------------------------------------------------------
# telemetry: metrics()/statistics()/prometheus surfaces
# ---------------------------------------------------------------------------

def test_metrics_and_statistics_block():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_app("k, sum(v) as s", "k", "sec, min"))
    rt.start()
    _feed(rt, [("A", "x", 1.0, 0.0, 1000), ("B", "x", 2.0, 0.0, 2000)])
    agg = rt.aggregations["A"]
    m = agg.metrics()
    assert m["device"] and m["resident"] and m["groups"] == 2
    assert m["durations"]["SECONDS"]["buckets"] == 2
    rt.query("from A within 0L, 10000L per 'sec' select k, s")
    stats = rt.statistics()["aggregation"]
    assert stats["aggregations"]["A"]["groups"] == 2
    sq = stats["store_query"]
    assert sq["batches"] == 1 and sq["events"] == 2
    from siddhi_tpu.core.telemetry import render_prometheus
    text = render_prometheus({"AggApp": rt.stats.report()})
    assert "siddhi_tpu_agg_groups{" in text
    assert "siddhi_tpu_agg_buckets{" in text
    assert "siddhi_tpu_agg_store_queries_total{" in text
    assert "siddhi_tpu_agg_store_query_latency_seconds_bucket{" in text
    mgr.shutdown()
