"""Incremental (multi-granularity) aggregation (reference test surface:
modules/siddhi-core/src/test/java/org/wso2/siddhi/core/aggregation/
AggregationTestCase — define aggregation, within/per store queries and
joins, restart continuity)."""
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.planner import PlanError

H = 3_600_000
MIN = 60_000


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


APP = """
    define stream Trades (sym string, price double, vol long, ts long);
    define aggregation TradeAgg
      from Trades
      select sym, sum(price) as total, avg(price) as avgPrice,
             count() as n, min(price) as lo, max(price) as hi
      group by sym
      aggregate by ts every sec, min, hour;
"""


def _feed(rt):
    h = rt.input_handler("Trades")
    # two seconds, two symbols
    h.send([("A", 10.0, 1, 1000), ("A", 20.0, 1, 1400),
            ("B", 5.0, 1, 1900), ("A", 30.0, 1, 2100),
            ("B", 7.0, 1, 2500)])
    rt.flush()


def test_store_query_per_seconds(mgr):
    rt = mgr.create_app_runtime(APP)
    _feed(rt)
    rows = rt.query("from TradeAgg within 0L, 100000L per 'seconds' "
                    "select sym, total, n")
    got = sorted((t, r) for t, r in rows)
    assert got == [(1000, ("A", 30.0, 2)), (1000, ("B", 5.0, 1)),
                   (2000, ("A", 30.0, 1)), (2000, ("B", 7.0, 1))]


def test_store_query_per_minutes_rolls_up(mgr):
    rt = mgr.create_app_runtime(APP)
    _feed(rt)
    rows = rt.query("from TradeAgg within 0L, 100000L per 'minutes' "
                    "select sym, total, avgPrice, lo, hi")
    got = sorted(r for _t, r in rows)
    assert got == [("A", 60.0, 20.0, 10.0, 30.0), ("B", 12.0, 6.0, 5.0, 7.0)]


def test_store_query_on_condition(mgr):
    rt = mgr.create_app_runtime(APP)
    _feed(rt)
    rows = rt.query("from TradeAgg on sym == 'A' within 0L, 100000L "
                    "per 'minutes' select sym, n")
    assert [r for _t, r in rows] == [("A", 3)]


def test_within_bounds_filter_buckets(mgr):
    rt = mgr.create_app_runtime(APP)
    _feed(rt)
    rows = rt.query("from TradeAgg within 2000L, 3000L per 'seconds' "
                    "select sym, total")
    assert sorted(r for _t, r in rows) == [("A", 30.0), ("B", 7.0)]


def test_aggregation_join(mgr):
    rt = mgr.create_app_runtime(APP + """
        define stream Probe (sym string);
        from Probe as p join TradeAgg as a
          on a.sym == p.sym
          within 0L, 100000L per 'minutes'
          select p.sym as sym, a.total as total
          insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    _feed(rt)
    rt.input_handler("Probe").send(("A",))
    rt.flush()
    assert out == [("A", 60.0)]


def test_aggregation_snapshot_restore(mgr):
    rt = mgr.create_app_runtime(APP)
    _feed(rt)
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(APP)
    rt2.restore(snap)
    # continuity: keep aggregating into the same buckets
    rt2.input_handler("Trades").send(("A", 40.0, 1, 2600))
    rt2.flush()
    rows = rt2.query("from TradeAgg on sym == 'A' within 0L, 100000L "
                     "per 'minutes' select total, n")
    assert [r for _t, r in rows] == [(100.0, 4)]
    m2.shutdown()


def test_arrival_time_when_no_aggregate_by(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (x int);
        define aggregation A from S select sum(x) as s every sec;
    """)
    h = rt.input_handler("S")
    h.send((1,), timestamp=1000)
    h.send((2,), timestamp=1500)
    h.send((3,), timestamp=2200)
    rt.flush()
    rows = rt.query("from A within 0L, 10000L per 'seconds' select s")
    assert [(t, r) for t, r in rows] == [(1000, (3,)), (2000, (3,))]


def test_unsupported_incremental_aggregator_rejected(mgr):
    with pytest.raises(PlanError):
        mgr.create_app_runtime("""
            define stream S (x int);
            define aggregation A from S select distinctCount(x) as d every sec;
        """)


def test_per_outside_range_rejected(mgr):
    rt = mgr.create_app_runtime(APP)
    _feed(rt)
    with pytest.raises(PlanError):
        rt.query("from TradeAgg within 0L, 10000L per 'days' select total")


def test_wildcard_within_pattern(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int, ts long);
        define aggregation A from S select sum(x) as s
            aggregate by ts every hour, day;
    """)
    # 2017-06-01 10:30 UTC
    base = 1496313000000
    rt.input_handler("S").send([(5, base), (6, base + H)])
    rt.flush()
    rows = rt.query("from A within '2017-06-01 **:**:**' per 'hours' select s")
    assert sorted(r for _t, r in rows) == [(5,), (6,)]
    rows = rt.query("from A within '2017-06-02 **:**:**' per 'hours' select s")
    assert rows == []


def test_device_aggregation_differential(mgr):
    """Opt-in device segmented-reduction path == host numpy path."""
    import numpy as np
    body = """
    define stream Trades (sym string, price double, vol long);
    define aggregation TradeAgg
    from Trades select sym, sum(price) as total, avg(price) as ap,
                      min(price) as lo, max(price) as hi, count() as n
    group by sym
    aggregate every sec, min, hour;
    """
    rng = np.random.default_rng(9)
    sends = []
    for i in range(500):
        sends.append((f"S{int(rng.integers(6))}",
                      float(np.round(rng.uniform(10, 50) * 4) / 4),
                      int(rng.integers(1, 100)),
                      1_700_000_000_000 + int(rng.integers(0, 3_600_000))))
    results = {}
    for mode in ("@app:deviceAggregations('always')\n", ""):
        rt = mgr.create_app_runtime(mode + body)
        h = rt.input_handler("Trades")
        rt.start()
        for sym, p, v, ts in sends:
            h.send((sym, p, v), timestamp=ts)
        rt.flush()
        agg = rt.aggregations["TradeAgg"]
        assert agg.device == bool(mode)
        rows = rt.query("from TradeAgg within 1700000000000L, 1800000000000L "
                        "per 'hours' select sym, total, ap, lo, hi, n")
        results[mode or "host"] = sorted((t, r) for t, r in rows)
    dev, host = results.values()
    assert len(dev) == len(host) > 0
    for (td, rd), (th, rh) in zip(dev, host):
        assert td == th and rd[0] == rh[0]
        for a, b in zip(rd[1:], rh[1:]):
            assert float(b) == pytest.approx(float(a), rel=2e-5, abs=2e-4), \
                (rd, rh)
