"""Unified async dispatch pipeline (core/pipeline.py): depth-D deferred
materialization under @app:devicePipeline must be output-invariant across
every device plan kind, flush() must be a full barrier, and the runtime's
dispatch rounds (all plans dispatch before any materializes) must not
change results.  Also sanity-checks the overlap/queue-depth telemetry."""
import random

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.pipeline import DispatchPipeline, PadPool

WHEAD = "@app:playback define stream S (sym string, p double, v long);\n"
JHEAD = ("define stream L (sym string, lp double);\n"
         "define stream R (sym string, rp double);\n")


def _rows(n, seed=1, n_syms=3):
    r = random.Random(seed)
    ts, rows = 1000, []
    for _ in range(n):
        ts += r.randint(0, 80)
        rows.append((ts, (f"s{r.randint(0, n_syms - 1)}",
                          round(r.uniform(-50, 150), 2), r.randint(1, 9))))
    return rows


def _run_window(depth, rows, batch=9):
    head = "@app:deviceWindows('always')\n"
    if depth:
        head += f"@app:devicePipeline({depth})\n"
    m = SiddhiManager()
    rt = m.create_app_runtime(
        head + WHEAD +
        "from S#window.length(6) select sym, sum(p) as s, count() as c "
        "group by sym insert into O;")
    out = []
    rt.add_callback("O", lambda evs: out.extend((e.timestamp, e.data)
                                                for e in evs))
    h = rt.input_handler("S")
    for i, (ts, row) in enumerate(rows):
        h.send(row, timestamp=ts)
        if i % batch == batch - 1:
            rt.flush()
    rt.flush()
    dev = rt.statistics().get("device", {})
    m.shutdown()
    return out, dev


@pytest.mark.parametrize("depth", [1, 4])
def test_window_pipeline_depth_output_invariant(depth):
    rows = _rows(120, seed=5)
    base, _ = _run_window(0, rows)
    piped, dev = _run_window(depth, rows)
    assert piped == base and base
    # flush() drained everything: nothing left in flight
    m = next(iter(dev.values()))
    assert m["dispatch_queue_depth"] == 0
    assert m["pipeline_dispatches"] > 0
    assert m["pipeline_depth"] == depth


def test_window_pipeline_flush_is_barrier():
    """With depth D and no flush, up to D batches of output are withheld;
    flush() delivers them."""
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:deviceWindows('always')\n@app:devicePipeline(4)\n" + WHEAD +
        "from S#window.length(3) select sum(p) as s insert into O;")
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    for ts, row in _rows(8, seed=2):
        h.send(row, timestamp=ts)
    # 8 rows fit one builder batch; drain it WITHOUT the barrier by
    # sending through set_time (playback apps flush on the clock)
    rt.set_time(10_000_000)
    n_before = len(out)
    rt.flush()
    assert len(out) == 8
    assert n_before == 8    # set_time ends in a flush barrier too
    m.shutdown()


def _run_join(depth, sends, flush_every=6):
    head = ""
    if depth:
        head += f"@app:devicePipeline({depth})\n"
    m = SiddhiManager()
    rt = m.create_app_runtime(
        head + JHEAD +
        "from L#window.length(5) as a join R#window.length(4) as b "
        "on a.sym == b.sym select a.sym as s, a.lp as lp, b.rp as rp "
        "insert into O;")
    assert any(type(p).__name__ == "DeviceJoinPlan" for p in rt._plans)
    rows = []
    rt.add_callback("O", lambda evs: rows.extend((e.timestamp, e.data)
                                                 for e in evs))
    rt.start()
    for i, (sid, row, ts) in enumerate(sends):
        rt.send(sid, row, timestamp=ts)
        if i % flush_every == flush_every - 1:
            rt.flush()
    rt.flush()
    m.shutdown()
    return rows


@pytest.mark.parametrize("depth", [1, 4])
def test_join_pipeline_depth_output_invariant(depth):
    rng = np.random.default_rng(3)
    sends = [("L" if rng.random() < 0.5 else "R",
              (f"K{int(rng.integers(3))}", float(rng.integers(1, 40))),
              1000 + i) for i in range(90)]
    base = _run_join(0, sends)
    piped = _run_join(depth, sends)
    assert piped == base and base


def test_multi_plan_dispatch_round_output_invariant():
    """N device plans on ONE stream: the runtime dispatches all of them
    before materializing any (cross-plan overlap).  Outputs must match
    the single-plan runs exactly, per plan."""
    queries = [
        "@info(name='q0') from S#window.length(4) select sum(p) as s "
        "insert into O0;",
        "@info(name='q1') from S#window.length(7) select sym, max(p) as hi "
        "group by sym insert into O1;",
        "@info(name='q2') from S[p > 0] select sym, p insert into O2;",
    ]
    rows = _rows(80, seed=11)

    def run(qs):
        m = SiddhiManager()
        rt = m.create_app_runtime(
            "@app:deviceWindows('always')\n" + WHEAD + "\n".join(qs))
        outs = {i: [] for i in range(len(queries))}
        for i in range(len(queries)):
            if f"O{i}" in rt.schemas:
                rt.add_callback(
                    f"O{i}",
                    lambda evs, i=i: outs[i].extend(e.data for e in evs))
        h = rt.input_handler("S")
        for j, (ts, row) in enumerate(rows):
            h.send(row, timestamp=ts)
            if j % 9 == 8:
                rt.flush()
        rt.flush()
        m.shutdown()
        return outs

    combined = run(queries)
    for i, q in enumerate(queries):
        solo = run([q])
        assert combined[i] == solo[i] and combined[i], f"plan {i} diverged"


def test_overlap_telemetry_reported():
    rows = _rows(60, seed=4)
    _out, dev = _run_window(2, rows, batch=6)
    m = next(iter(dev.values()))
    # something was deferred, so both sides of the overlap accounting ran
    assert m["pipeline_max_depth"] >= 1
    assert "overlap_ratio" in m
    assert 0.0 <= m["overlap_ratio"] <= 1.0


def test_dispatch_pipeline_unit():
    """Unit surface: FIFO order, depth policy, hold/collect, drain."""
    seen = []
    pipe = DispatchPipeline("t", lambda e: seen.append(e) or [e], depth=2)
    assert pipe.push("a") == []
    assert pipe.push("b") == []
    assert pipe.push("c") == ["a"]          # over depth: oldest first
    pipe.hold()
    assert pipe.push("d") == []             # held: nothing materializes
    assert pipe.push("e") == []
    assert pipe.collect() == ["b", "c"]     # back to depth 2
    assert pipe.drain() == ["d", "e"]
    assert seen == list("abcde")
    assert len(pipe) == 0
    m = pipe.metrics()
    assert m["pipeline_dispatches"] == 5
    assert m["pipeline_max_depth"] == 4


def test_pad_pool_rotation_and_batch_memo():
    pool = PadPool()
    a = pool.take(("s", "x", 8, "f4"), 8, np.float32, min_slots=2)
    b = pool.take(("s", "x", 8, "f4"), 8, np.float32, min_slots=2)
    assert a is not b                       # rotation: adjacent takes differ
    c = pool.take(("s", "x", 8, "f4"), 8, np.float32, min_slots=2)
    assert c is a                           # and cycle back around

    from siddhi_tpu.core.batch import EventBatch
    from siddhi_tpu.core.schema import StreamSchema
    from siddhi_tpu.query import ast
    schema = StreamSchema("S", (ast.Attribute("p", ast.AttrType.DOUBLE),))
    batch = EventBatch(schema, np.array([10, 20], np.int64),
                       {"p": np.array([1.5, 2.5])}, 2)
    p1 = batch.padded("p", 8, pool=pool)
    p2 = batch.padded("p", 8, pool=pool)
    assert p1 is p2                         # memoized per batch
    assert p1.shape == (8,) and p1[:2].tolist() == [1.5, 2.5]
    assert not p1[2:].any()
    off, base = batch.padded_ts_offsets(8, pool=pool)
    assert base == 10 and off[:2].tolist() == [0, 10] and not off[2:].any()
